from . import vgg
