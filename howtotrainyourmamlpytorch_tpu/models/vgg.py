"""The few-shot backbone: ``num_stages`` conv blocks + linear head, functional.

TPU-native re-design of the reference's ``VGGReLUNormNetwork``
(meta_neural_network_architectures.py:545-689) and its block
(``MetaConvNormLayerReLU`` :323-436):

* parameters are a flat ``{name: array}`` pytree — the reference's entire
  external-weight routing machinery (``extract_top_level_dict``
  meta_...py:11-38, per-layer params switches) dissolves into ordinary
  function arguments;
* activations are NHWC, kernels HWIO (MXU-friendly), vs the reference's NCHW;
* batch-norm running statistics are explicit state in/out rather than module
  mutation, so the reference's backup/restore dance
  (meta_...py:200-201,240-255) becomes "discard the returned state at eval";
* the architecture itself is identical: per stage a 3x3 conv (stride 1 +
  2x2 maxpool when ``max_pooling``, stride 2 otherwise — meta_...py:568-573),
  norm, leaky-relu; global avg-pool when not max-pooling (:608-609); flatten;
  linear head (:614-615).

Per-step batch-norm (MAML++ BNWB/BNRS, meta_...py:177-185,226-234): when
``per_step_bn_statistics``, gamma/beta and running mean/var have a leading
inner-step axis and are indexed by the current inner step.
"""

from __future__ import annotations

import math
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import MAMLConfig
from ..ops import functional as F

Params = Dict[str, jnp.ndarray]
BNState = Dict[str, jnp.ndarray]


def _xavier_uniform(key, shape, fan_in, fan_out, dtype=jnp.float32):
    """torch.nn.init.xavier_uniform_ (gain=1), as used for conv and linear
    weights (meta_...py:64,117)."""
    a = math.sqrt(6.0 / (fan_in + fan_out))
    return jax.random.uniform(key, shape, dtype, minval=-a, maxval=a)


def _stage_dims(cfg: MAMLConfig):
    """Per-stage spatial dims: yields (h_in, w_in, h_conv, w_conv, h_out,
    w_out) for each stage — the single home of the conv/pool geometry (shape
    inference, replacing the reference's dummy-tensor trace
    meta_...py:581-618).
    """
    h, w = cfg.image_height, cfg.image_width
    pad = 1 if cfg.conv_padding else 0
    for _ in range(cfg.num_stages):
        if cfg.max_pooling:
            # stride-1 conv then 2x2/2 maxpool (meta_...py:570,604-605)
            ch, cw = h + 2 * pad - 2, w + 2 * pad - 2
            oh, ow = ch // 2, cw // 2
        else:
            # stride-2 conv (meta_...py:573)
            ch = (h + 2 * pad - 3) // 2 + 1
            cw = (w + 2 * pad - 3) // 2 + 1
            oh, ow = ch, cw
        yield h, w, ch, cw, oh, ow
        h, w = oh, ow


def _feature_hw(cfg: MAMLConfig) -> Tuple[int, int]:
    """Spatial size after the conv stack."""
    oh, ow = cfg.image_height, cfg.image_width
    for _, _, _, _, oh, ow in _stage_dims(cfg):
        pass
    return oh, ow


def feature_dim(cfg: MAMLConfig) -> int:
    """Flattened feature dim entering the linear head."""
    if cfg.max_pooling:
        h, w = _feature_hw(cfg)
        return h * w * cfg.cnn_num_filters
    # global avg pool -> 1x1xC (meta_...py:608-612)
    return cfg.cnn_num_filters


def init(cfg: MAMLConfig, key: jax.Array) -> Tuple[Params, BNState]:
    """Build the parameter and BN-state pytrees.

    Naming: ``conv{i}.conv.{weight,bias}``, ``conv{i}.norm.{gamma,beta}``,
    ``linear.{weight,bias}`` — flat keys, one array per leaf. BN state:
    ``conv{i}.norm.{mean,var}``.
    """
    params: Params = {}
    bn_state: BNState = {}
    steps = cfg.bn_num_steps
    c_in = cfg.image_channels
    f = cfg.cnn_num_filters
    keys = jax.random.split(key, cfg.num_stages + 1)
    conv_first = cfg.block_order == "conv_norm_relu"

    for i, (h, w, ch, cw, _, _) in enumerate(_stage_dims(cfg)):
        params[f"conv{i}.conv.weight"] = _xavier_uniform(
            keys[i], (3, 3, c_in, f), fan_in=c_in * 9, fan_out=f * 9
        )
        params[f"conv{i}.conv.bias"] = jnp.zeros((f,))
        # norm features: the used block normalizes conv OUTPUT
        # (MetaConvNormLayerReLU, meta_...py:356-385); the alternate block
        # normalizes the block INPUT (MetaNormLayerConvReLU, :477-489)
        nf = f if conv_first else c_in
        if cfg.norm_layer == "batch_norm":
            if cfg.per_step_bn_statistics and not cfg.enable_inner_loop_optimizable_bn_params:
                # per-step gamma/beta (meta_...py:182-185)
                params[f"conv{i}.norm.gamma"] = jnp.ones((steps, nf))
                params[f"conv{i}.norm.beta"] = jnp.zeros((steps, nf))
            else:
                # plain or inner-loop-adaptable scalars-per-feature
                # (meta_...py:187-198)
                params[f"conv{i}.norm.gamma"] = jnp.ones((nf,))
                params[f"conv{i}.norm.beta"] = jnp.zeros((nf,))
            if cfg.per_step_bn_statistics:
                bn_state[f"conv{i}.norm.mean"] = jnp.zeros((steps, nf))
                bn_state[f"conv{i}.norm.var"] = jnp.ones((steps, nf))
        else:  # layer_norm, validated at config build
            # normalized over the full per-sample feature shape
            # (meta_...py:379/:485: input_feature_shape=out.shape[1:])
            lh, lw = (ch, cw) if conv_first else (h, w)
            params[f"conv{i}.norm.gamma"] = jnp.ones((lh, lw, nf))
            params[f"conv{i}.norm.beta"] = jnp.zeros((lh, lw, nf))
        c_in = f

    feat = feature_dim(cfg)
    params["linear.weight"] = _xavier_uniform(
        keys[-1], (feat, cfg.num_classes_per_set), fan_in=feat,
        fan_out=cfg.num_classes_per_set,
    )
    params["linear.bias"] = jnp.zeros((cfg.num_classes_per_set,))
    return params, bn_state


def layer1_patches(cfg: MAMLConfig, x: jnp.ndarray):
    """The stage-0 conv's patch tensor for raw images ``x`` — the hoistable
    invariant of the MAML inner loop (``core.maml._task_learner`` computes
    it ONCE per task outside the scan and threads it into every
    ``apply(..., x_patches=...)`` call, so layer 1's im2col over the
    largest spatial tensor is not re-extracted ``num_steps``x in the
    forward and the remat backward).

    Returns None when hoisting is inapplicable — the resolved conv
    lowering consumes raw NHWC (``'lax'``), or the block normalizes its
    INPUT with adapted params (``block_order='norm_conv_relu'``: the conv
    input changes every inner step, so there is no invariant to hoist) —
    letting callers thread the result through unconditionally.  When a
    tensor is returned it is bitwise the value the inline extraction
    would produce (``ops.functional.conv_patches``), so consuming it is
    bit-exact by construction.
    """
    if not cfg.resolved_im2col_hoist:
        return None
    if cfg.block_order != "conv_norm_relu":
        return None
    if cfg.resolved_conv_impl not in ("im2col", "gemm"):
        return None
    dtype = jnp.bfloat16 if cfg.compute_dtype == "bfloat16" else jnp.float32
    stride = 1 if cfg.max_pooling else 2
    pad = 1 if cfg.conv_padding else 0
    return F.conv_patches(
        x.astype(dtype), 3, 3, stride, pad,
        pad_channels=cfg.resolved_pad_channels,
    )


def apply(
    cfg: MAMLConfig,
    params: Params,
    bn_state: BNState,
    x: jnp.ndarray,
    num_step,
    training: bool = True,
    x_patches=None,
) -> Tuple[jnp.ndarray, BNState]:
    """Forward pass.

    :param x: (batch, h, w, c) images, NHWC.
    :param num_step: current inner-loop step (traced scalar ok) — indexes the
        per-step BN params/stats (meta_...py:226-234). Clamped to the stored
        step count so eval with more steps than train stays in bounds
        (SURVEY.md §7 hazard; the reference would index out of bounds).
    :param training: only affects whether updated BN running stats are
        *returned*; normalization always uses batch stats, exactly like the
        reference's ``training=True`` call (meta_...py:246-247).
    :param x_patches: optional pre-extracted stage-0 patch tensor
        (``layer1_patches(cfg, x)``) — consumed by the first conv instead
        of re-running im2col on ``x``; bit-exact with the inline
        extraction. None keeps the self-contained forward.
    :return: (logits (batch, way), new_bn_state).
    """
    dtype = jnp.bfloat16 if cfg.compute_dtype == "bfloat16" else jnp.float32
    out = x.astype(dtype)
    stride = 1 if cfg.max_pooling else 2
    pad = 1 if cfg.conv_padding else 0
    # compute-only MXU channel padding, resolved once per trace; conv/linear
    # slice back to logical channels before bias/norm so the math is
    # bit-exact with the unpadded program (ops.functional.pad_target)
    pad_ch = cfg.resolved_pad_channels
    new_bn: BNState = {}
    step = jnp.clip(num_step, 0, cfg.bn_num_steps - 1)

    conv_first = cfg.block_order == "conv_norm_relu"

    def bn_inputs(i):
        """This stage's (gamma, beta, running mean/var) at the current
        inner step — running stats None when not tracked."""
        gamma = params[f"conv{i}.norm.gamma"]
        beta = params[f"conv{i}.norm.beta"]
        if gamma.ndim == 2:  # per-step (steps, f)
            gamma = gamma[step]
            beta = beta[step]
        mean_key = f"conv{i}.norm.mean"
        if mean_key in bn_state:
            rm = bn_state[mean_key][step]
            rv = bn_state[f"conv{i}.norm.var"][step]
        else:
            rm = rv = None
        return gamma, beta, rm, rv

    def store_bn(i, nm, nv):
        """Thread this stage's updated running stats into the returned BN
        state (discarded at eval, like the reference's training=False)."""
        mean_key, var_key = f"conv{i}.norm.mean", f"conv{i}.norm.var"
        if mean_key not in bn_state:
            return
        if training:
            new_bn[mean_key] = bn_state[mean_key].at[step].set(nm)
            new_bn[var_key] = bn_state[var_key].at[step].set(nv)
        else:
            new_bn[mean_key] = bn_state[mean_key]
            new_bn[var_key] = bn_state[var_key]

    def apply_norm(out, i):
        if cfg.norm_layer == "batch_norm":
            gamma, beta, rm, rv = bn_inputs(i)
            out, nm, nv = F.batch_norm(
                out, gamma, beta, rm, rv,
                stats_impl=cfg.resolved_bn_stats_impl,
            )
            store_bn(i, nm, nv)
        else:
            out = F.layer_norm(
                out, params[f"conv{i}.norm.gamma"],
                params[f"conv{i}.norm.beta"],
            )
        return out

    # the reference's used block (conv -> BN -> leaky-relu) goes through
    # the FUSED op: one GEMM whose elementwise epilogue (bias, BN stats +
    # normalize + affine, activation) is a single saved region under
    # remat_policy='save_conv' — the backward recomputes none of the
    # per-layer elementwise tail (ops.functional.conv_bn_act; bit-
    # identical to the unfused sequence). The alternate block order and
    # layer_norm keep the unfused path.
    fused_block = conv_first and cfg.norm_layer == "batch_norm"
    bn_stats = cfg.resolved_bn_stats_impl

    for i in range(cfg.num_stages):
        # the hoisted stage-0 patches are only valid for the conv-first
        # block (its conv input IS the raw image; the alternate block
        # normalizes the input with adapted params first)
        patches = x_patches if (i == 0 and conv_first) else None
        if not conv_first:  # alternate block: norm the INPUT (meta_...py:527-533)
            out = apply_norm(out, i)
        if fused_block:
            gamma, beta, rm, rv = bn_inputs(i)
            out, nm, nv = F.conv_bn_act(
                out,
                params[f"conv{i}.conv.weight"],
                params[f"conv{i}.conv.bias"],
                gamma, beta, rm, rv,
                stride=stride,
                padding=pad,
                impl=cfg.resolved_conv_impl,
                pad_channels=pad_ch,
                bn_stats_impl=bn_stats,
                patches=patches,
            )
            store_bn(i, nm, nv)
        else:
            out = F.conv2d(
                out,
                params[f"conv{i}.conv.weight"],
                params[f"conv{i}.conv.bias"],
                stride=stride,
                padding=pad,
                impl=cfg.resolved_conv_impl,
                pad_channels=pad_ch,
                patches=patches,
            )
            if conv_first:
                out = apply_norm(out, i)
            out = F.leaky_relu(out)
        if cfg.max_pooling:
            out = F.max_pool2d(out, impl=cfg.resolved_pool_impl)

    if not cfg.max_pooling:
        out = F.global_avg_pool2d(out)
    out = out.reshape(out.shape[0], -1)
    logits = F.linear(
        out, params["linear.weight"], params["linear.bias"], pad_channels=pad_ch
    )
    return logits.astype(jnp.float32), new_bn


def num_params(params: Params) -> int:
    return int(sum(np.prod(v.shape) for v in params.values()))
