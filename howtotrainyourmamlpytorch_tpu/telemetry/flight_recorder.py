"""Flight recorder: a bounded ring of recent training-health entries,
dumped to disk when something goes wrong.

The recorder is a host-side ``deque`` holding the last
``flight_recorder_steps`` per-step probe entries plus the builder's
lifecycle notes (epoch summaries, checkpoints, anomalies) — a few floats
per step, so a 256-entry ring costs kilobytes. When an anomaly fires (or
the hang watchdog stalls), ``dump()`` writes one incident directory under
``logs/incidents/``:

* ``incident.json`` — the trigger (reason, iteration, rule details),
  timestamps, and what the dump contains;
* ``ring.jsonl``    — the ring's entries, oldest first (the N steps of
  context BEFORE the blow-up — exactly what a NaN postmortem needs and
  what the epoch-granular CSV can never show);
* ``state/``        — optionally, a full orbax checkpoint of the live
  ``MetaState`` (params + LSLR + BN + Adam moments) via the caller's
  ``state_dump_fn``, so the divergent state itself is inspectable/
  resumable instead of being lost to the next (possibly NaN-poisoned)
  checkpoint.

Rate limiting: ``cooldown_steps`` suppresses a second dump within the
window (a run wedged at NaN produces one incident per window, not one per
step), and ``max_state_dumps`` caps the expensive state checkpoints per
run — later incidents still write their ring + manifest.

All entry points are lock-guarded: the hang watchdog dumps from its own
thread while the train loop records.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from .sinks import _jsonable

INCIDENT_MANIFEST = "incident.json"
RING_FILENAME = "ring.jsonl"


class FlightRecorder:
    """Ring buffer + anomaly-triggered incident dumps (see module doc)."""

    def __init__(
        self,
        capacity: int,
        incidents_dir: str,
        max_state_dumps: int = 3,
        cooldown_steps: int = 200,
        is_primary: bool = True,
    ):
        self.capacity = int(capacity)
        self.incidents_dir = incidents_dir
        self.max_state_dumps = int(max_state_dumps)
        self.cooldown_steps = int(cooldown_steps)
        self.is_primary = bool(is_primary)
        self._ring: deque = deque(maxlen=max(1, self.capacity))
        self._lock = threading.Lock()
        self._last_dump_iter: Optional[int] = None
        self.state_dumps_done = 0
        self.incidents_written = 0

    @property
    def enabled(self) -> bool:
        """Non-primary hosts keep a no-op recorder (one incident per run,
        not one per host, and the primary's ring sees the same replicated
        metrics every host computes)."""
        return self.capacity > 0 and self.is_primary

    # -- ring producers (train loop + builder hooks) -----------------------

    def record_step(self, entry: Dict[str, Any]) -> None:
        """Append one per-step health entry (already host scalars)."""
        if not self.enabled:
            return
        with self._lock:
            self._ring.append(dict(entry))

    def note_event(self, kind: str, **payload: Any) -> None:
        """Append a lifecycle note (epoch summary, checkpoint, anomaly) so
        the dumped ring shows WHERE in the run the steps sat."""
        if not self.enabled:
            return
        with self._lock:
            self._ring.append({
                "event": kind,
                "ts": time.time(),  # lint-ok: MP007 wall-clock timestamp correlating ring entries with external logs
                **payload,
            })

    def snapshot(self) -> List[Dict[str, Any]]:
        """The ring's current contents, oldest first."""
        with self._lock:
            return list(self._ring)

    # -- incident dumps ----------------------------------------------------

    def dump(
        self,
        reason: str,
        iter_idx: int,
        details: Optional[Dict[str, Any]] = None,
        state_dump_fn: Optional[Callable[[str], None]] = None,
        force: bool = False,
    ) -> Optional[str]:
        """Write one incident directory; returns its path, or None when the
        recorder is disabled or the cooldown suppressed the dump.

        ``state_dump_fn(path)`` — when given and under the
        ``max_state_dumps`` cap — is called with the incident directory to
        add the ``state/`` checkpoint; its failure is recorded in the
        manifest, never raised (an incident dump must not kill the run it
        is documenting). ``force=True`` bypasses the cooldown (never the
        disabled/non-primary gate): the halt escalation's final forensic
        dump must not be swallowed because a routine anomaly dumped
        moments earlier.
        """
        iter_idx = int(iter_idx)
        with self._lock:
            if not self.enabled:
                return None
            if (
                not force
                and self._last_dump_iter is not None
                and self.cooldown_steps > 0
                and 0 <= iter_idx - self._last_dump_iter < self.cooldown_steps
            ):
                return None
            self._last_dump_iter = iter_idx
            ring = list(self._ring)
            self.incidents_written += 1
            dump_state = (
                state_dump_fn is not None
                and self.state_dumps_done < self.max_state_dumps
            )
            if dump_state:
                self.state_dumps_done += 1

        base = os.path.join(
            self.incidents_dir, f"incident_iter{iter_idx:08d}_{reason}"
        )
        path, n = base, 1
        while os.path.exists(path):  # same iter+reason twice: never clobber
            path = f"{base}.{n}"
            n += 1
        # assembled under a tmp name and renamed into place once complete:
        # a crash mid-dump (the PR 6 fault matrix kills runs at arbitrary
        # points) must never leave a manifest-less partial incident dir
        # that postmortem tooling mistakes for a real incident
        tmp = path + ".tmp"
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)
        with open(os.path.join(tmp, RING_FILENAME), "w") as f:
            for entry in ring:
                f.write(json.dumps(_jsonable(entry)) + "\n")
        state_error = None
        if dump_state:
            try:
                state_dump_fn(tmp)
            except Exception as e:  # noqa: BLE001 - see docstring
                state_error = repr(e)
        manifest = {
            "reason": reason,
            "iter": iter_idx,
            "ts": time.time(),  # lint-ok: MP007 wall-clock timestamp in the incident manifest
            "ring_entries": len(ring),
            "state_dumped": bool(dump_state and state_error is None),
            "state_error": state_error,
            "details": _jsonable(details or {}),
        }
        with open(os.path.join(tmp, INCIDENT_MANIFEST), "w") as f:
            json.dump(manifest, f, indent=2)
        os.rename(tmp, path)
        return path
