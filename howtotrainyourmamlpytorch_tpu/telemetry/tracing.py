"""End-to-end causal tracing: the span layer under schema v10.

The telemetry stack up to v9 answers "what happened per epoch / per
dispatch"; this module answers "where did THIS request / THIS step spend
its time". A **span** is one named, timed interval on one thread —
``queue`` / ``assemble`` / ``dispatch`` / ``sync`` for a serving
request, ``train_dispatch`` / ``eval_chunk`` / ``epoch_summary`` /
``checkpoint`` for the train loop, ``sample`` / ``stack`` /
``queue_put`` for the data producer — emitted as a schema-v10 ``span``
telemetry record and assembled downstream into Dapper-style trees and
Chrome/Perfetto timelines (``cli trace``).

Design constraints (the same proof standard as ``telemetry_level='off'``
and the fault seams):

* **off is free and bit-identical** — a disabled tracer allocates no
  span objects (``start_span`` returns ``None`` after one attribute
  check, the ``span()`` context manager yields without constructing
  anything) and emits nothing; tracing never touches a jitted program,
  so jaxprs are unchanged BY CONSTRUCTION (tested anyway);
* **no device syncs** — spans record ``time.perf_counter`` only; a span
  around an asynchronous dispatch measures the ENQUEUE interval, and the
  separate ``sync`` span measures the host-blocking fetch, which is
  exactly the decomposition a latency postmortem needs;
* **monotonic clocks** — span times are perf_counter milliseconds (one
  process-wide monotonic origin, shared across threads), never
  ``time.time()`` (lint rule MP007 enforces this repo-wide);
* **causality across threads** — each thread keeps its own parent
  stack (``threading.local``), and a span can be parented EXPLICITLY
  (``parent=``, or ``use_parent()`` around a region) so a request
  submitted on one thread nests the dispatch work a worker thread did
  for it;
* **causality across processes** — a span parented under a
  ``remote_span`` (trace/span ids that arrived over the wire, e.g. in
  the gateway's forward-frame header) INHERITS the remote trace id, so
  a fleet host's ``queue→assemble→dispatch→sync`` tree hangs under the
  gateway's ``forward`` span in the merged export. Per-process tracers
  take a ``span_prefix`` (span ids stay unique across the merged fleet
  log) and a ``process`` label (stamped on every emitted record so
  ``cli trace --fleet`` can assign per-process Perfetto tracks). The
  clocks themselves never cross the wire: each process records its own
  perf_counter origin, and the gateway's health sweep estimates each
  host's clock offset (Cristian) so the merge can align them offline.

Record shape (``kind='span'``, schema v10; since v14 optionally
``process``): ``name``, ``cat``, ``trace_id`` (run-scoped), ``span_id``,
``start_ms`` / ``dur_ms`` (perf_counter based), optional ``parent_id``,
``tid`` (thread name) and ``attrs`` (small JSON payload: program /
bucket / shots / request_id / iter ...).

Pure stdlib — importable without jax or numpy, so the exporters below
run on a laptop against a scp'd log.
"""

from __future__ import annotations

import contextlib
import itertools
import threading
import time
import uuid
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional

__all__ = [
    "Span",
    "Tracer",
    "NULL_TRACER",
    "new_trace_id",
    "remote_span",
    "span_records",
    "to_chrome_trace",
    "critical_path_summary",
    "fleet_critical_path",
    "SERVING_STAGES",
    "FLEET_STAGES",
]

#: the serving decomposition stages, in causal order (queue wait in the
#: micro-batcher, host batch assembly, device dispatch enqueue, host sync)
SERVING_STAGES = ("queue", "assemble", "dispatch", "sync")

#: the fleet decomposition stages, in causal order: edge decode+admission
#: (gateway_queue), network + host HTTP handling outside the batcher
#: (wire, net of the host's own request span), then the host-side serving
#: stages (queue renamed host_queue to disambiguate from the edge wait)
FLEET_STAGES = ("gateway_queue", "wire", "host_queue",
                "assemble", "dispatch", "sync")


def new_trace_id() -> str:
    """A fresh run-scoped trace id (16 hex chars)."""
    return uuid.uuid4().hex[:16]


def remote_span(trace_id: str, span_id: str) -> "Span":
    """A synthetic handle for a span that lives in ANOTHER process.

    The cross-process adoption hook: a fleet host that received
    ``trace_id`` / ``parent_span_id`` in the wire header wraps them in a
    ``remote_span`` and passes it as ``parent=`` — the local root then
    inherits the remote trace id and parents under the remote span id,
    so the merged fleet export reassembles one tree. The handle itself
    is never emitted (it was already emitted by its owning process)."""
    return Span(name="remote", cat="remote", trace_id=trace_id,
                span_id=span_id, parent_id=None, start_ms=0.0,
                tid="", attrs={})


class Span:
    """One open interval; closed (and emitted) by ``Tracer.end_span``."""

    __slots__ = ("name", "cat", "trace_id", "span_id", "parent_id",
                 "start_ms", "tid", "attrs")

    def __init__(self, name: str, cat: str, trace_id: str, span_id: str,
                 parent_id: Optional[str], start_ms: float, tid: str,
                 attrs: Dict[str, Any]):
        self.name = name
        self.cat = cat
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start_ms = start_ms
        self.tid = tid
        self.attrs = attrs


class Tracer:
    """Span factory + emitter.

    :param emit: ``emit(**fields)`` receives each closed span's record
        fields (the builder passes ``telemetry.event('span', ...)``, the
        serving engine a ``make_record``-over-sink wrapper). ``None``
        DISABLES the tracer: every entry point is a single attribute
        check, no span objects are allocated, nothing is emitted.
    :param trace_id: run-scoped id stamped on every span (defaults to a
        fresh ``new_trace_id()``). Spans opened under an explicit remote
        parent inherit the PARENT's trace id instead (see
        ``remote_span``).
    :param span_prefix: prefix for generated span ids (default none —
        ``s000001`` ...). Fleet processes each pass a distinct prefix
        (``gw-``, ``host00-``) so span ids stay unique in the merged
        multi-process log.
    :param process: when set, stamped as a top-level ``process`` field
        on every emitted span record (schema v14) — the per-process
        track label ``cli trace --fleet`` groups by.
    """

    def __init__(self, emit: Optional[Callable[..., None]] = None,
                 trace_id: Optional[str] = None,
                 span_prefix: str = "",
                 process: Optional[str] = None):
        self.enabled = emit is not None
        self.trace_id = trace_id or new_trace_id()
        self.process = process
        self._span_prefix = span_prefix
        self._emit = emit
        self._ids = itertools.count(1)
        self._ids_lock = threading.Lock()
        self._local = threading.local()

    # -- parent bookkeeping (per thread) -----------------------------------

    def _stack(self) -> List[Span]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = []
            self._local.stack = stack
        return stack

    def current(self) -> Optional[Span]:
        """The innermost open span on THIS thread (or None)."""
        stack = getattr(self._local, "stack", None)
        return stack[-1] if stack else None

    @contextlib.contextmanager
    def use_parent(self, parent: Optional[Span]) -> Iterator[None]:
        """Adopt ``parent`` (a span possibly opened on another thread) as
        this thread's current parent for the duration — the cross-thread
        causality hook: a batcher worker wraps the engine dispatch in the
        submitting request's span so the dispatch tree nests under it."""
        if not self.enabled or parent is None:
            yield
            return
        stack = self._stack()
        stack.append(parent)
        try:
            yield
        finally:
            stack.pop()

    # -- span lifecycle ----------------------------------------------------

    def _next_id(self) -> str:
        with self._ids_lock:
            return f"{self._span_prefix}s{next(self._ids):06d}"

    def start_span(self, name: str, cat: str = "default",
                   parent: Optional[Span] = None,
                   start_ms: Optional[float] = None,
                   trace_id: Optional[str] = None,
                   **attrs: Any) -> Optional[Span]:
        """Open a span; returns ``None`` when the tracer is disabled (the
        off path allocates nothing). ``parent=None`` nests under this
        thread's innermost open span, if any. ``start_ms`` (perf_counter
        milliseconds) backdates the span to a stamp the caller already
        took — the hot-path pattern: measure with bare perf_counter,
        emit the span AFTER the timed interval so the record's own
        serialization never rides the numbers it reports. ``trace_id``
        overrides the inherited id — how the gateway mints a FRESH trace
        per admitted request (each edge request is its own causal tree,
        not a twig of a run-wide one)."""
        if not self.enabled:
            return None
        if parent is None:
            parent = self.current()
        return Span(
            name=name,
            cat=cat,
            # inherit the parent's trace id: in-process parents carry this
            # tracer's own id (no change), a remote_span parent carries the
            # originating process's — cross-process propagation for free
            trace_id=(trace_id if trace_id is not None
                      else parent.trace_id if parent is not None
                      else self.trace_id),
            span_id=self._next_id(),
            parent_id=parent.span_id if parent is not None else None,
            start_ms=(start_ms if start_ms is not None
                      else time.perf_counter() * 1e3),
            tid=threading.current_thread().name,
            attrs=dict(attrs) if attrs else {},
        )

    def end_span(self, span: Optional[Span],
                 end_ms: Optional[float] = None, **attrs: Any) -> None:
        """Close ``span`` and emit its record; no-op on ``None`` (the
        handle a disabled tracer handed out). ``end_ms`` (perf_counter
        milliseconds) closes the span at a stamp the caller already took
        — the companion to ``start_span(start_ms=...)``."""
        if span is None or not self.enabled:
            return
        if attrs:
            span.attrs.update(attrs)
        emit = self._emit
        if emit is None:  # pragma: no cover - enabled implies emit
            return
        if end_ms is None:
            end_ms = time.perf_counter() * 1e3
        fields: Dict[str, Any] = {
            "name": span.name,
            "cat": span.cat,
            "trace_id": span.trace_id,
            "span_id": span.span_id,
            "start_ms": round(span.start_ms, 3),
            "dur_ms": round(end_ms - span.start_ms, 3),
            "tid": span.tid,
        }
        if span.parent_id is not None:
            fields["parent_id"] = span.parent_id
        if self.process is not None:
            fields["process"] = self.process
        if span.attrs:
            fields["attrs"] = span.attrs
        emit(**fields)

    @contextlib.contextmanager
    def span(self, name: str, cat: str = "default",
             parent: Optional[Span] = None, **attrs: Any) -> Iterator[
                 Optional[Span]]:
        """Context-manager form; nests via the thread-local parent stack.
        Yields the open span (None when disabled) so callers can attach
        late attrs (``span.attrs['bucket'] = b``)."""
        if not self.enabled:
            yield None
            return
        sp = self.start_span(name, cat=cat, parent=parent, **attrs)
        stack = self._stack()
        stack.append(sp)  # type: ignore[arg-type]
        try:
            yield sp
        finally:
            stack.pop()
            self.end_span(sp)


#: the shared disabled tracer: modules take ``tracer or NULL_TRACER`` so
#: the hot paths carry one attribute check when tracing is off
NULL_TRACER = Tracer(emit=None)


# -- exporters (jax-free, numpy-free: `cli trace` runs these) ---------------


def span_records(records: Iterable[dict]) -> List[dict]:
    """The ``span`` records of a telemetry record stream, in file order."""
    return [r for r in records if r.get("kind") == "span"]


def _numeric(value: Any) -> Optional[float]:
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        return None
    return float(value)


def to_chrome_trace(spans: Iterable[dict],
                    offsets_ms: Optional[Dict[str, float]] = None,
                    ) -> Dict[str, Any]:
    """Assemble span records into Chrome/Perfetto trace-event JSON.

    One complete (``ph='X'``) event per span — ``ts``/``dur`` in
    microseconds from the span's perf_counter milliseconds (one
    process-wide monotonic origin, so cross-thread ordering is real) —
    plus ``M``-phase thread-name metadata so the timeline shows
    ``serving-batcher`` / ``MainThread`` / producer threads by name.
    ``args`` carries span/parent ids and the span attrs, which is what
    lets Perfetto's flow/selection UI reconstruct the causal tree. Spans
    missing their required numeric fields are skipped, never fatal — a
    truncated log from a crashed run must still render.

    Fleet logs (spans carrying a ``process`` label, schema v14) get one
    Perfetto process track per label — ``pid`` assigned in first-seen
    order, ``process_name`` metadata, thread ids scoped per process —
    and ``offsets_ms`` (process label → that process's estimated clock
    offset vs the reference process, the gateway's Cristian estimate)
    SHIFTS each process's timestamps onto the reference clock
    (``ts - offset``), so a host span renders INSIDE the gateway span
    that caused it. Single-process logs (no ``process`` field anywhere)
    keep the exact v10 shape: everything on ``pid`` 1, no process
    metadata."""
    pids: Dict[str, int] = {}
    tids: Dict[Any, int] = {}
    events: List[Dict[str, Any]] = []
    for rec in spans:
        start_ms = _numeric(rec.get("start_ms"))
        dur_ms = _numeric(rec.get("dur_ms"))
        name = rec.get("name")
        if start_ms is None or dur_ms is None or not isinstance(name, str):
            continue
        process = rec.get("process")
        process = process if isinstance(process, str) else None
        if process is not None:
            pid = pids.setdefault(process, len(pids) + 1)
            if offsets_ms:
                off = offsets_ms.get(process)
                if isinstance(off, (int, float)):
                    start_ms -= float(off)
        else:
            pid = 1
        tid_name = str(rec.get("tid", "main"))
        tid = tids.setdefault((process, tid_name), len(tids) + 1)
        args: Dict[str, Any] = {
            "trace_id": rec.get("trace_id"),
            "span_id": rec.get("span_id"),
        }
        if rec.get("parent_id") is not None:
            args["parent_id"] = rec["parent_id"]
        attrs = rec.get("attrs")
        if isinstance(attrs, dict):
            args.update(attrs)
        events.append({
            "name": name,
            "cat": str(rec.get("cat", "default")),
            "ph": "X",
            "ts": round(start_ms * 1e3, 1),
            "dur": max(0.0, round(dur_ms * 1e3, 1)),
            "pid": pid,
            "tid": tid,
            "args": args,
        })
    events.sort(key=lambda e: e["ts"])
    meta: List[Dict[str, Any]] = [
        {
            "name": "process_name",
            "ph": "M",
            "pid": pid,
            "tid": 0,
            "args": {"name": process},
        }
        for process, pid in sorted(pids.items(), key=lambda kv: kv[1])
    ]
    meta += [
        {
            "name": "thread_name",
            "ph": "M",
            "pid": pids.get(process, 1) if process is not None else 1,
            "tid": tid,
            "args": {"name": tid_name},
        }
        for (process, tid_name), tid in sorted(
            tids.items(), key=lambda kv: kv[1])
    ]
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def _serving_key(attrs: Dict[str, Any]) -> str:
    return (
        f"{attrs.get('program', '?')}"
        f"/b{attrs.get('bucket', '?')}/s{attrs.get('shots', '?')}"
    )


def critical_path_summary(spans: Iterable[dict]) -> Dict[str, Any]:
    """Condense a span stream into the critical-path report ``cli trace``
    prints.

    * ``serving`` — per (program, bucket, shots): mean milliseconds in
      each decomposition stage (queue wait, host assembly, device
      dispatch enqueue, sync/readback), their sum (``stages_ms``), the
      mean end-to-end request latency when request root spans are
      present, and the dispatch count. The queue+assemble+dispatch+sync
      ≈ end-to-end identity is this report's acceptance check;
    * ``by_name`` — every span name's count / total / mean milliseconds,
      the flat profile (train + data spans live here).
    """
    by_name: Dict[str, Dict[str, float]] = {}
    serving: Dict[str, Dict[str, Any]] = {}
    for rec in spans:
        dur = _numeric(rec.get("dur_ms"))
        name = rec.get("name")
        if dur is None or not isinstance(name, str):
            continue
        agg = by_name.setdefault(name, {"count": 0, "total_ms": 0.0})
        agg["count"] += 1
        agg["total_ms"] += dur
        attrs = rec.get("attrs")
        if not isinstance(attrs, dict):
            attrs = {}
        if rec.get("cat") == "serving" and (
            name in SERVING_STAGES or name == "request"
        ):
            if name in ("queue", "request"):
                # queue/request spans predate grouping (no bucket yet):
                # attribute them to the shots bucket only
                key = f"*/b*/s{attrs.get('shots', '?')}"
            else:
                key = _serving_key(attrs)
            if key not in serving:
                serving[key] = {
                    s: {"count": 0, "total_ms": 0.0}
                    for s in (*SERVING_STAGES, "request")
                }
            entry = serving[key]
            slot = entry[name]
            slot["count"] += 1
            slot["total_ms"] += dur
    for agg in by_name.values():
        agg["mean_ms"] = round(agg["total_ms"] / agg["count"], 3)
        agg["total_ms"] = round(agg["total_ms"], 3)
    out_serving: Dict[str, Any] = {}
    for key, entry in sorted(serving.items()):
        row: Dict[str, Any] = {}
        stages_total = 0.0
        for stage in SERVING_STAGES:
            slot = entry[stage]
            mean = (
                round(slot["total_ms"] / slot["count"], 3)
                if slot["count"] else None
            )
            row[f"{stage}_ms_mean"] = mean
            row[f"{stage}_count"] = slot["count"]
            if mean is not None:
                stages_total += mean
        row["stages_ms"] = round(stages_total, 3)
        req = entry["request"]
        row["request_ms_mean"] = (
            round(req["total_ms"] / req["count"], 3) if req["count"] else None
        )
        row["requests"] = req["count"]
        out_serving[key] = row
    return {"by_name": by_name, "serving": out_serving}


def fleet_critical_path(spans: Iterable[dict]) -> Dict[str, Any]:
    """Attribute fleet end-to-end latency into the cross-process stages.

    Groups spans by ``trace_id`` (each gateway-minted root is one
    request), keeps the traces that hold a gateway-side ``request`` root,
    and decomposes each into ``FLEET_STAGES``:

    * ``gateway_queue`` — edge decode + admission before the first
      forward attempt;
    * ``wire`` — the forward socket round trips MINUS the host-side
      request span: network transit + HTTP framing + host decode, the
      time neither process's serving stages can see;
    * ``host_queue`` — the host micro-batcher's ``queue`` span (renamed
      so the edge wait and the host wait stay distinguishable);
    * ``assemble`` / ``dispatch`` / ``sync`` — the host serving stages.

    Only durations are compared — never absolute timestamps — so the
    attribution is exact WITHOUT clock alignment. ``assemble`` /
    ``dispatch`` / ``sync`` are emitted once per dispatch GROUP (parented
    under the group leader), so traces that rode along in someone else's
    group carry only queue+wire; the summary separates ``complete``
    traces (all stages present) from the total and averages stages over
    the traces that have them. The ``complete``-trace identity
    ``sum(stages) ≈ e2e`` is this report's acceptance check (CI gates
    on ``coverage``)."""
    traces: Dict[str, Dict[str, Any]] = {}
    processes: set = set()
    for rec in spans:
        dur = _numeric(rec.get("dur_ms"))
        name = rec.get("name")
        trace_id = rec.get("trace_id")
        if dur is None or not isinstance(name, str) or not trace_id:
            continue
        proc = rec.get("process")
        if isinstance(proc, str):
            processes.add(proc)
        entry = traces.setdefault(
            trace_id,
            {"root_ms": None, "shed": False, "procs": set(),
             "sums": {}, "host_request_ms": 0.0},
        )
        if isinstance(proc, str):
            entry["procs"].add(proc)
        cat = rec.get("cat")
        if name == "request" and cat == "gateway":
            entry["root_ms"] = dur
        elif name == "shed" and cat == "gateway":
            entry["shed"] = True
        elif name == "request" and cat == "serving":
            entry["host_request_ms"] += dur
        else:
            stage = None
            if name == "gateway_queue" and cat == "gateway":
                stage = "gateway_queue"
            elif name == "wire" and cat == "gateway":
                stage = "wire"
            elif name == "queue" and cat == "serving":
                stage = "host_queue"
            elif name in ("assemble", "dispatch", "sync") and cat == "serving":
                stage = name
            elif name == "device_hold" and cat == "serving":
                # emulated device occupancy (serve-bench's CPU shim):
                # device time, so it belongs to the dispatch stage
                stage = "dispatch"
            if stage is not None:
                entry["sums"][stage] = entry["sums"].get(stage, 0.0) + dur
    requests = 0
    sheds = 0
    spanning = 0
    complete_rows: List[Dict[str, float]] = []
    stage_sums: Dict[str, Dict[str, float]] = {
        s: {"count": 0, "total_ms": 0.0} for s in FLEET_STAGES
    }
    for entry in traces.values():
        if entry["root_ms"] is None:
            continue  # a host-local trace (no gateway root): not a fleet e2e
        if entry["shed"]:
            sheds += 1
            continue
        requests += 1
        if len(entry["procs"]) >= 2:
            spanning += 1
        sums = dict(entry["sums"])
        if "wire" in sums:
            # net of the host's own request span: what's left is transit
            # + framing + host decode — clamped, durations only
            sums["wire"] = max(0.0, sums["wire"] - entry["host_request_ms"])
        for stage, total in sums.items():
            slot = stage_sums[stage]
            slot["count"] += 1
            slot["total_ms"] += total
        if all(s in sums for s in FLEET_STAGES):
            complete_rows.append(
                {"e2e_ms": entry["root_ms"],
                 "stage_sum_ms": sum(sums[s] for s in FLEET_STAGES)}
            )
    out: Dict[str, Any] = {
        "requests": requests,
        "sheds": sheds,
        "spanning_traces": spanning,
        "complete": len(complete_rows),
        "processes": sorted(processes),
    }
    stages: Dict[str, Any] = {}
    for stage in FLEET_STAGES:
        slot = stage_sums[stage]
        stages[f"{stage}_ms_mean"] = (
            round(slot["total_ms"] / slot["count"], 3)
            if slot["count"] else None
        )
        stages[f"{stage}_count"] = slot["count"]
    out["stages"] = stages
    if complete_rows:
        e2e = sum(r["e2e_ms"] for r in complete_rows) / len(complete_rows)
        ssum = sum(r["stage_sum_ms"] for r in complete_rows) / len(
            complete_rows)
        out["e2e_ms_mean"] = round(e2e, 3)
        out["stage_sum_ms_mean"] = round(ssum, 3)
        out["coverage"] = round(ssum / e2e, 4) if e2e > 0 else None
    else:
        out["e2e_ms_mean"] = None
        out["stage_sum_ms_mean"] = None
        out["coverage"] = None
    return out
