"""End-to-end causal tracing: the span layer under schema v10.

The telemetry stack up to v9 answers "what happened per epoch / per
dispatch"; this module answers "where did THIS request / THIS step spend
its time". A **span** is one named, timed interval on one thread —
``queue`` / ``assemble`` / ``dispatch`` / ``sync`` for a serving
request, ``train_dispatch`` / ``eval_chunk`` / ``epoch_summary`` /
``checkpoint`` for the train loop, ``sample`` / ``stack`` /
``queue_put`` for the data producer — emitted as a schema-v10 ``span``
telemetry record and assembled downstream into Dapper-style trees and
Chrome/Perfetto timelines (``cli trace``).

Design constraints (the same proof standard as ``telemetry_level='off'``
and the fault seams):

* **off is free and bit-identical** — a disabled tracer allocates no
  span objects (``start_span`` returns ``None`` after one attribute
  check, the ``span()`` context manager yields without constructing
  anything) and emits nothing; tracing never touches a jitted program,
  so jaxprs are unchanged BY CONSTRUCTION (tested anyway);
* **no device syncs** — spans record ``time.perf_counter`` only; a span
  around an asynchronous dispatch measures the ENQUEUE interval, and the
  separate ``sync`` span measures the host-blocking fetch, which is
  exactly the decomposition a latency postmortem needs;
* **monotonic clocks** — span times are perf_counter milliseconds (one
  process-wide monotonic origin, shared across threads), never
  ``time.time()`` (lint rule MP007 enforces this repo-wide);
* **causality across threads** — each thread keeps its own parent
  stack (``threading.local``), and a span can be parented EXPLICITLY
  (``parent=``, or ``use_parent()`` around a region) so a request
  submitted on one thread nests the dispatch work a worker thread did
  for it.

Record shape (``kind='span'``, schema v10): ``name``, ``cat``,
``trace_id`` (run-scoped), ``span_id``, ``start_ms`` / ``dur_ms``
(perf_counter based), optional ``parent_id``, ``tid`` (thread name) and
``attrs`` (small JSON payload: program / bucket / shots / request_id /
iter ...).

Pure stdlib — importable without jax or numpy, so the exporters below
run on a laptop against a scp'd log.
"""

from __future__ import annotations

import contextlib
import itertools
import threading
import time
import uuid
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional

__all__ = [
    "Span",
    "Tracer",
    "NULL_TRACER",
    "new_trace_id",
    "span_records",
    "to_chrome_trace",
    "critical_path_summary",
    "SERVING_STAGES",
]

#: the serving decomposition stages, in causal order (queue wait in the
#: micro-batcher, host batch assembly, device dispatch enqueue, host sync)
SERVING_STAGES = ("queue", "assemble", "dispatch", "sync")


def new_trace_id() -> str:
    """A fresh run-scoped trace id (16 hex chars)."""
    return uuid.uuid4().hex[:16]


class Span:
    """One open interval; closed (and emitted) by ``Tracer.end_span``."""

    __slots__ = ("name", "cat", "trace_id", "span_id", "parent_id",
                 "start_ms", "tid", "attrs")

    def __init__(self, name: str, cat: str, trace_id: str, span_id: str,
                 parent_id: Optional[str], start_ms: float, tid: str,
                 attrs: Dict[str, Any]):
        self.name = name
        self.cat = cat
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start_ms = start_ms
        self.tid = tid
        self.attrs = attrs


class Tracer:
    """Span factory + emitter.

    :param emit: ``emit(**fields)`` receives each closed span's record
        fields (the builder passes ``telemetry.event('span', ...)``, the
        serving engine a ``make_record``-over-sink wrapper). ``None``
        DISABLES the tracer: every entry point is a single attribute
        check, no span objects are allocated, nothing is emitted.
    :param trace_id: run-scoped id stamped on every span (defaults to a
        fresh ``new_trace_id()``).
    """

    def __init__(self, emit: Optional[Callable[..., None]] = None,
                 trace_id: Optional[str] = None):
        self.enabled = emit is not None
        self.trace_id = trace_id or new_trace_id()
        self._emit = emit
        self._ids = itertools.count(1)
        self._ids_lock = threading.Lock()
        self._local = threading.local()

    # -- parent bookkeeping (per thread) -----------------------------------

    def _stack(self) -> List[Span]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = []
            self._local.stack = stack
        return stack

    def current(self) -> Optional[Span]:
        """The innermost open span on THIS thread (or None)."""
        stack = getattr(self._local, "stack", None)
        return stack[-1] if stack else None

    @contextlib.contextmanager
    def use_parent(self, parent: Optional[Span]) -> Iterator[None]:
        """Adopt ``parent`` (a span possibly opened on another thread) as
        this thread's current parent for the duration — the cross-thread
        causality hook: a batcher worker wraps the engine dispatch in the
        submitting request's span so the dispatch tree nests under it."""
        if not self.enabled or parent is None:
            yield
            return
        stack = self._stack()
        stack.append(parent)
        try:
            yield
        finally:
            stack.pop()

    # -- span lifecycle ----------------------------------------------------

    def _next_id(self) -> str:
        with self._ids_lock:
            return f"s{next(self._ids):06d}"

    def start_span(self, name: str, cat: str = "default",
                   parent: Optional[Span] = None,
                   start_ms: Optional[float] = None,
                   **attrs: Any) -> Optional[Span]:
        """Open a span; returns ``None`` when the tracer is disabled (the
        off path allocates nothing). ``parent=None`` nests under this
        thread's innermost open span, if any. ``start_ms`` (perf_counter
        milliseconds) backdates the span to a stamp the caller already
        took — the hot-path pattern: measure with bare perf_counter,
        emit the span AFTER the timed interval so the record's own
        serialization never rides the numbers it reports."""
        if not self.enabled:
            return None
        if parent is None:
            parent = self.current()
        return Span(
            name=name,
            cat=cat,
            trace_id=self.trace_id,
            span_id=self._next_id(),
            parent_id=parent.span_id if parent is not None else None,
            start_ms=(start_ms if start_ms is not None
                      else time.perf_counter() * 1e3),
            tid=threading.current_thread().name,
            attrs=dict(attrs) if attrs else {},
        )

    def end_span(self, span: Optional[Span],
                 end_ms: Optional[float] = None, **attrs: Any) -> None:
        """Close ``span`` and emit its record; no-op on ``None`` (the
        handle a disabled tracer handed out). ``end_ms`` (perf_counter
        milliseconds) closes the span at a stamp the caller already took
        — the companion to ``start_span(start_ms=...)``."""
        if span is None or not self.enabled:
            return
        if attrs:
            span.attrs.update(attrs)
        emit = self._emit
        if emit is None:  # pragma: no cover - enabled implies emit
            return
        if end_ms is None:
            end_ms = time.perf_counter() * 1e3
        fields: Dict[str, Any] = {
            "name": span.name,
            "cat": span.cat,
            "trace_id": span.trace_id,
            "span_id": span.span_id,
            "start_ms": round(span.start_ms, 3),
            "dur_ms": round(end_ms - span.start_ms, 3),
            "tid": span.tid,
        }
        if span.parent_id is not None:
            fields["parent_id"] = span.parent_id
        if span.attrs:
            fields["attrs"] = span.attrs
        emit(**fields)

    @contextlib.contextmanager
    def span(self, name: str, cat: str = "default",
             parent: Optional[Span] = None, **attrs: Any) -> Iterator[
                 Optional[Span]]:
        """Context-manager form; nests via the thread-local parent stack.
        Yields the open span (None when disabled) so callers can attach
        late attrs (``span.attrs['bucket'] = b``)."""
        if not self.enabled:
            yield None
            return
        sp = self.start_span(name, cat=cat, parent=parent, **attrs)
        stack = self._stack()
        stack.append(sp)  # type: ignore[arg-type]
        try:
            yield sp
        finally:
            stack.pop()
            self.end_span(sp)


#: the shared disabled tracer: modules take ``tracer or NULL_TRACER`` so
#: the hot paths carry one attribute check when tracing is off
NULL_TRACER = Tracer(emit=None)


# -- exporters (jax-free, numpy-free: `cli trace` runs these) ---------------


def span_records(records: Iterable[dict]) -> List[dict]:
    """The ``span`` records of a telemetry record stream, in file order."""
    return [r for r in records if r.get("kind") == "span"]


def _numeric(value: Any) -> Optional[float]:
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        return None
    return float(value)


def to_chrome_trace(spans: Iterable[dict]) -> Dict[str, Any]:
    """Assemble span records into Chrome/Perfetto trace-event JSON.

    One complete (``ph='X'``) event per span — ``ts``/``dur`` in
    microseconds from the span's perf_counter milliseconds (one
    process-wide monotonic origin, so cross-thread ordering is real) —
    plus ``M``-phase thread-name metadata so the timeline shows
    ``serving-batcher`` / ``MainThread`` / producer threads by name.
    ``args`` carries span/parent ids and the span attrs, which is what
    lets Perfetto's flow/selection UI reconstruct the causal tree. Spans
    missing their required numeric fields are skipped, never fatal — a
    truncated log from a crashed run must still render."""
    tids: Dict[str, int] = {}
    events: List[Dict[str, Any]] = []
    for rec in spans:
        start_ms = _numeric(rec.get("start_ms"))
        dur_ms = _numeric(rec.get("dur_ms"))
        name = rec.get("name")
        if start_ms is None or dur_ms is None or not isinstance(name, str):
            continue
        tid_name = str(rec.get("tid", "main"))
        tid = tids.setdefault(tid_name, len(tids) + 1)
        args: Dict[str, Any] = {
            "trace_id": rec.get("trace_id"),
            "span_id": rec.get("span_id"),
        }
        if rec.get("parent_id") is not None:
            args["parent_id"] = rec["parent_id"]
        attrs = rec.get("attrs")
        if isinstance(attrs, dict):
            args.update(attrs)
        events.append({
            "name": name,
            "cat": str(rec.get("cat", "default")),
            "ph": "X",
            "ts": round(start_ms * 1e3, 1),
            "dur": max(0.0, round(dur_ms * 1e3, 1)),
            "pid": 1,
            "tid": tid,
            "args": args,
        })
    events.sort(key=lambda e: e["ts"])
    meta: List[Dict[str, Any]] = [
        {
            "name": "thread_name",
            "ph": "M",
            "pid": 1,
            "tid": tid,
            "args": {"name": tid_name},
        }
        for tid_name, tid in sorted(tids.items(), key=lambda kv: kv[1])
    ]
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def _serving_key(attrs: Dict[str, Any]) -> str:
    return (
        f"{attrs.get('program', '?')}"
        f"/b{attrs.get('bucket', '?')}/s{attrs.get('shots', '?')}"
    )


def critical_path_summary(spans: Iterable[dict]) -> Dict[str, Any]:
    """Condense a span stream into the critical-path report ``cli trace``
    prints.

    * ``serving`` — per (program, bucket, shots): mean milliseconds in
      each decomposition stage (queue wait, host assembly, device
      dispatch enqueue, sync/readback), their sum (``stages_ms``), the
      mean end-to-end request latency when request root spans are
      present, and the dispatch count. The queue+assemble+dispatch+sync
      ≈ end-to-end identity is this report's acceptance check;
    * ``by_name`` — every span name's count / total / mean milliseconds,
      the flat profile (train + data spans live here).
    """
    by_name: Dict[str, Dict[str, float]] = {}
    serving: Dict[str, Dict[str, Any]] = {}
    for rec in spans:
        dur = _numeric(rec.get("dur_ms"))
        name = rec.get("name")
        if dur is None or not isinstance(name, str):
            continue
        agg = by_name.setdefault(name, {"count": 0, "total_ms": 0.0})
        agg["count"] += 1
        agg["total_ms"] += dur
        attrs = rec.get("attrs")
        if not isinstance(attrs, dict):
            attrs = {}
        if rec.get("cat") == "serving" and (
            name in SERVING_STAGES or name == "request"
        ):
            if name in ("queue", "request"):
                # queue/request spans predate grouping (no bucket yet):
                # attribute them to the shots bucket only
                key = f"*/b*/s{attrs.get('shots', '?')}"
            else:
                key = _serving_key(attrs)
            if key not in serving:
                serving[key] = {
                    s: {"count": 0, "total_ms": 0.0}
                    for s in (*SERVING_STAGES, "request")
                }
            entry = serving[key]
            slot = entry[name]
            slot["count"] += 1
            slot["total_ms"] += dur
    for agg in by_name.values():
        agg["mean_ms"] = round(agg["total_ms"] / agg["count"], 3)
        agg["total_ms"] = round(agg["total_ms"], 3)
    out_serving: Dict[str, Any] = {}
    for key, entry in sorted(serving.items()):
        row: Dict[str, Any] = {}
        stages_total = 0.0
        for stage in SERVING_STAGES:
            slot = entry[stage]
            mean = (
                round(slot["total_ms"] / slot["count"], 3)
                if slot["count"] else None
            )
            row[f"{stage}_ms_mean"] = mean
            row[f"{stage}_count"] = slot["count"]
            if mean is not None:
                stages_total += mean
        row["stages_ms"] = round(stages_total, 3)
        req = entry["request"]
        row["request_ms_mean"] = (
            round(req["total_ms"] / req["count"], 3) if req["count"] else None
        )
        row["requests"] = req["count"]
        out_serving[key] = row
    return {"by_name": by_name, "serving": out_serving}
