"""The structured telemetry event schema.

Every record the :mod:`telemetry` sinks emit is one JSON object per line
(JSONL) carrying three envelope fields — ``schema`` (the integer schema
version), ``ts`` (unix seconds) and ``kind`` — plus the kind-specific
payload fields listed in ``KIND_FIELDS``. The schema is versioned so
downstream consumers (dashboards, regression tooling, the CI validation
job) can reject records they do not understand instead of silently
misreading them.

Record kinds:

* ``run_start`` / ``run_end`` — run lifecycle markers;
* ``epoch``          — the per-epoch scalar summary (the CSV row's twin);
* ``stream``         — loader producer stats (assembly/stall/queue depth);
* ``dispatch``       — per-epoch dispatch-timing stats (StepTimer summary);
* ``checkpoint``     — a checkpoint write (epoch index + path);
* ``device_memory``  — HBM stats vs. the store registry's expectation;
* ``dynamics``       — on-device training dynamics for one fused dispatch
  (per-inner-step support/target losses, per-layer grad norms, LSLR values,
  MSL weight vector);
* ``trace``          — profiler trace-window start/stop;
* ``watchdog_stall`` — the hang watchdog's diagnostic record (current
  stage, seconds since progress, all-thread stack snapshot).
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterator, Tuple

SCHEMA_VERSION = 1

#: kind -> required payload fields (beyond the schema/ts/kind envelope)
KIND_FIELDS: Dict[str, Tuple[str, ...]] = {
    "run_start": ("experiment_name", "telemetry_level", "resume_iter"),
    "run_end": (),
    "epoch": ("epoch", "scalars"),
    "stream": ("epoch", "batches", "assembly_ms_per_batch",
               "stall_ms_per_batch", "queue_depth_mean"),
    "dispatch": ("epoch",),
    "checkpoint": ("epoch", "path"),
    "device_memory": ("epoch", "store_bytes_expected"),
    "dynamics": ("iter_start", "num_iters", "support_losses",
                 "target_losses", "grad_norms", "lslr", "msl_weights"),
    "trace": ("action",),
    "watchdog_stall": ("stage", "seconds_since_progress", "stacks"),
}


def validate_record(rec: Any) -> None:
    """Raise ``ValueError`` when ``rec`` is not a valid telemetry record."""
    if not isinstance(rec, dict):
        raise ValueError(f"telemetry record must be an object, got {type(rec).__name__}")
    if rec.get("schema") != SCHEMA_VERSION:
        raise ValueError(
            f"unknown telemetry schema version {rec.get('schema')!r} "
            f"(this validator understands {SCHEMA_VERSION})"
        )
    if not isinstance(rec.get("ts"), (int, float)):
        raise ValueError(f"telemetry record missing numeric 'ts': {rec!r}")
    kind = rec.get("kind")
    if kind not in KIND_FIELDS:
        raise ValueError(
            f"unknown telemetry record kind {kind!r}; known kinds: "
            f"{sorted(KIND_FIELDS)}"
        )
    missing = [f for f in KIND_FIELDS[kind] if f not in rec]
    if missing:
        raise ValueError(
            f"telemetry record of kind {kind!r} missing required fields "
            f"{missing}: {rec!r}"
        )
    if kind == "dynamics":
        # the acceptance surface of the on-device collection: per-inner-step
        # losses are lists, grad norms / LSLR are per-layer mappings
        for field in ("support_losses", "target_losses", "msl_weights"):
            if not isinstance(rec[field], list):
                raise ValueError(
                    f"dynamics record field {field!r} must be a list, got "
                    f"{type(rec[field]).__name__}"
                )
        for field in ("grad_norms", "lslr"):
            if not isinstance(rec[field], dict) or not rec[field]:
                raise ValueError(
                    f"dynamics record field {field!r} must be a non-empty "
                    f"per-layer mapping, got {rec[field]!r}"
                )


def iter_records(path: str) -> Iterator[dict]:
    """Yield parsed records from a telemetry JSONL file (no validation)."""
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                yield json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(
                    f"{path}:{lineno}: not valid JSON ({e})"
                ) from e


def validate_file(path: str) -> int:
    """Validate every record in a telemetry JSONL file.

    Returns the number of records; raises ``ValueError`` naming the first
    offending line. This is what the CI schema-validation job runs against
    the log a tiny telemetry-enabled train emits.
    """
    count = 0
    for rec in iter_records(path):
        try:
            validate_record(rec)
        except ValueError as e:
            raise ValueError(f"{path}: record {count + 1}: {e}") from e
        count += 1
    return count
