"""The structured telemetry event schema.

Every record the :mod:`telemetry` sinks emit is one JSON object per line
(JSONL) carrying three envelope fields — ``schema`` (the integer schema
version), ``ts`` (unix seconds) and ``kind`` — plus the kind-specific
payload fields listed in ``KIND_FIELDS``. The schema is versioned so
downstream consumers (dashboards, regression tooling, the CI validation
job) can reject records they do not understand instead of silently
misreading them.

Record kinds:

* ``run_start`` / ``run_end`` — run lifecycle markers;
* ``epoch``          — the per-epoch scalar summary (the CSV row's twin);
* ``stream``         — loader producer stats (assembly/stall/queue depth);
* ``dispatch``       — per-epoch dispatch-timing stats (StepTimer
  summary; since v7 also the epoch-boundary overlap: ``overlap_ms`` =
  host milliseconds of train-summary work that ran under the in-flight
  fused eval tail, ``boundary_overlaps`` = phase-transition lag blocks
  the dispatch pipeline skipped, ``accum_steps`` = the step's
  ``meta_accum_steps`` setting. With two dispatches legally in flight at
  the boundary, per-dispatch timings at the boundary measure ENQUEUE-to-
  ENQUEUE latency, not device occupancy — the overlap fields say how much
  of the boundary was hidden);
* ``checkpoint``     — a checkpoint write (epoch index + path);
* ``device_memory``  — HBM stats vs. the store registry's expectation;
* ``dynamics``       — on-device training dynamics for one fused dispatch
  (per-inner-step support/target losses, per-layer grad norms, LSLR values,
  MSL weight vector);
* ``trace``          — profiler trace-window start/stop;
* ``watchdog_stall`` — the hang watchdog's diagnostic record (current
  stage, seconds since progress, all-thread stack snapshot; since v2 also
  the flight-recorder tail and the last evaluated health entry when the
  training-health monitor is on — hang and divergence diagnosable from
  one record);
* ``anomaly``        — a training-health rule fired (non-finite grads/loss,
  EMA-relative loss/grad-norm spike, absolute grad-norm/update-ratio
  ceiling): the iteration, the rule, the offending value vs its threshold,
  and the full probe entry;
* ``incident``       — the flight recorder dumped its ring (and, when
  legal, a full state checkpoint) to ``logs/incidents/<name>/`` — the
  record carries the reason and the on-disk path. Reason ``halt`` marks
  the escalation dump written just before ``TrainingDivergedError``,
  reason ``preemption`` the forensic dump of a graceful preemption exit;
* ``retry``          — one failed attempt at a retrying I/O seam
  (resilience/retry.py): the seam (``site``), the attempt number vs the
  budget, the error, and the deterministic backoff slept before the next
  attempt. A run that limped through transient filesystem faults says so
  in its own log; the exhausted final attempt is recorded too
  (``backoff_s`` 0);
* ``preemption``     — a SIGTERM/SIGINT preemption was drained at the
  dispatch boundary: the iteration, the signal number, and the resumable
  emergency checkpoint path the run exited behind (exit code
  ``resilience.PREEMPT_EXIT_CODE``);
* ``retrace``        — a dispatch site re-compiled mid-run
  (analysis/auditor.py's RetraceDetector, ``analysis_level != 'off'``):
  the iteration, the dispatch ``site`` (the jitted program incl. its
  static variant keys), the new abstract-signature hash and how many
  distinct signatures the site has now compiled. Every retrace is 20-40s
  of TPU compile the shape discipline should have prevented; under
  ``analysis_level='strict'`` the record is followed by a fatal
  RetraceError;
* ``elastic``        — elastic multi-host coordination
  (resilience/elastic.py, schema v6): ``event`` names the step —
  ``drain_request`` (a signalled worker published its drain request),
  ``drain_commit`` (the primary committed the agreed drain iteration),
  ``drain_ack`` (this process reached the agreed iteration and is
  draining), ``resume`` (a checkpoint written by ``old_process_count``
  processes resumed on ``new_process_count``, with the global
  ``episode_cursor`` re-entry point) — so a pod-scale preemption or a
  topology-changing resume documents itself in the run's own log;
* ``serving``        — the adapt-on-request serving engine (serving/,
  schema v8; extended in v9/v10/v11): ``event`` names the record shape —
  ``dispatch`` (one multi-tenant serving dispatch: real ``tenants``,
  the padded ``bucket`` and ``shots`` point it rode, host ``queue_ms``
  in the micro-batcher and end-to-end ``adapt_ms`` device latency;
  since v9 also the fast-path fields: ``program`` ('adapt' |
  'predict'), ``ingest`` ('f32' | 'uint8' | 'index'), ``ingest_bytes``
  — the dispatch's actual H2D payload — and ``cache_hits``), ``warmup``
  (since v9: how the engine warmed — ``mode`` 'artifacts' (AOT
  export deserialize) or 'compile', ``warmup_ms``, ``xla_compiles`` —
  0 on the artifact path — and ``programs``), ``rollup`` (the run
  condensed: dispatch/tenant counts, ``adapt_ms_p50`` /
  ``adapt_ms_p95``, ``tenants_per_sec``, the strict retrace count — 0
  in any healthy run — and since v9 ``h2d_bytes_per_dispatch`` and
  ``cache_hit_rate``) or, since v11, ``rollover`` (one replica's
  zero-downtime checkpoint-rollover swap, serving/refresh.py:
  ``replica_id``, ``old_iter`` / ``new_iter``, the standby's warmup
  mode/seconds, ``swap_ms`` and ``xla_compiles_at_swap`` — 0 in any
  healthy rollover). Since v11 every record a POOLED engine emits
  (serving/replica.py) additionally carries its ``replica_id``, so a
  multi-replica pool's merged stream stays per-replica attributable;
  single-engine records simply omit the field. The ``serving:`` line
  of ``cli inspect summary`` renders these jax-free, with a
  per-replica breakdown when replica ids are present. Since v12 two
  more shapes: ``deadline`` (one deadline-carrying request resolved:
  its ``deadline_ms`` budget, the signed ``slack_ms``, the ``missed``
  verdict, end-to-end ``e2e_ms`` and the stage attribution —
  per-request ``queue_ms``, router ``route_ms``, and the dispatch's
  ``batch_ms`` / ``dispatch_ms`` / ``sync_ms``), and the rollup gains
  ``window_dropped`` (how many dispatch samples the bounded percentile
  window shed — rollup honesty) plus the mergeable log-bucketed
  distributions ``adapt_ms_hist`` / ``queue_ms_hist``
  (serving/metrics.py ``LogHistogram.to_dict``: sparse bucket counts
  over a fixed geometric ladder, so offline consumers recompute the
  same quantiles the live endpoint serves);
* ``slo``            — the serving SLO report (schema v12,
  serving/metrics.py ``SLOTracker.summary``): the ``target_ms`` /
  ``availability`` objective and its ``error_budget``, total
  deadline-carrying ``requests`` and ``missed`` counts, the
  ``miss_rate``, per-window ``burn_rates`` (window miss rate over the
  error budget; 1.0 spends the budget exactly at the objective rate)
  with the worst window called out, and a ``per_replica`` breakdown.
  Emitted by ``cli serve-bench`` at end of run; derived from the SAME
  ``event='deadline'`` record stream the ``/metrics`` endpoint and
  ``cli slo`` consume, so the three can never disagree;
* ``analysis``       — the build-time program audit ran
  (``analysis_level != 'off'``): how many programs were audited (incl.
  the SPMD family on multi-device builds), how many contract violations
  were found, the audit ``mesh`` (``"1x8"``-style, null single-device)
  and — when the SPMD audit ran — the flagship train step's static
  ``roofline`` summary (bound, predicted HFU/MFU, flops/task), so
  ``cli inspect summary`` can say where the MFU number goes without the
  run's stdout;
* ``gateway``        — the networked fleet front tier (serving/gateway.py,
  schema v13): ``event`` names the record shape — ``shed`` (one request
  rejected at the edge before it could collapse a host queue: the typed
  ``reason`` ('admission' — the home host's depth+in-flight estimate
  exceeded its priority-tier budget — or 'deadline' — the request's
  remaining ``slack_ms`` could not cover the home host's current queue
  estimate), the ``tenant_id`` / ``priority`` / ``deadline_ms`` of the
  rejected request and its ``host`` home assignment), ``rehome`` (a host
  left the serving ring: the tripped ``host``, the chained root
  ``cause``, and ``in_flight`` — how many stranded socket requests were
  failed immediately with that cause instead of hanging), ``clock``
  (since v14: the health sweep's Cristian clock-offset estimate for one
  ``host`` — see the v14 migration note), and ``rollup``
  (the fleet condensed: ``hosts`` / ``healthy_hosts``, admitted /
  shed-by-reason counts, and the EXACT bucket-wise merge of every
  host's ``adapt_ms_hist`` / ``queue_ms_hist`` log histograms — fleet
  p99 from one histogram family, never averaged percentiles). The
  ``fleet:`` line of ``cli inspect summary`` renders these jax-free;
* ``span``           — one causal-tracing interval (telemetry/tracing.py,
  schema v10): ``name`` (queue / assemble / dispatch / sync / request
  for serving, train_dispatch / eval_chunk / epoch_summary /
  eval_sync / checkpoint for training, sample / stack / queue_put /
  consumer_wait for the data producer), ``cat`` (the emitting layer),
  the run-scoped ``trace_id``, ``span_id`` / optional ``parent_id``
  (the Dapper-style tree), ``start_ms`` / ``dur_ms`` (perf_counter
  milliseconds — one process-wide monotonic origin, so cross-thread
  ordering is real), ``tid`` (thread name), since v14 an optional
  ``process`` label (the emitting fleet process — ``gateway`` or a
  host id) and a small ``attrs`` payload (program / bucket / shots /
  request_id / iter). ``cli trace`` assembles these into a
  Chrome/Perfetto timeline and the critical-path summary; ``cli trace
  --fleet`` merges the per-process logs into one clock-aligned export.

Version history / migration notes:

* **v1** — initial schema (run lifecycle, epoch/stream/dispatch/checkpoint/
  device_memory/dynamics/trace/watchdog_stall).
* **v2** — adds the ``anomaly`` and ``incident`` record kinds (the
  training-health monitor) and the optional ``nonfinite_count`` /
  ``nonfinite_fields`` envelope fields (how many non-finite values the
  sink masked to null in this record, total and per payload field — the
  anomaly signal stays queryable from JSONL). Pure additions: every v1
  record validates unchanged under the v2 validator, and v2 validators
  accept records stamped with any version in
  ``[MIN_SCHEMA_VERSION, SCHEMA_VERSION]``. Records stamped with a NEWER
  version are tolerated envelope-only (numeric ``ts``, non-empty string
  ``kind``): unknown kinds and unknown fields from future schemas must
  never make an old reader reject a log it can still mostly use.
* **v3** — adds the ``retry`` and ``preemption`` record kinds (the
  resilience subsystem: retrying I/O seams and graceful preemption
  exits). Pure additions again: every v1/v2 record validates unchanged
  and the v2 forward-compat rules carry over verbatim (pinned fixtures
  ``tests/fixtures/telemetry_future_schema.jsonl`` — a newer-than-current
  writer — and ``tests/fixtures/telemetry_v2_schema.jsonl`` — a v2-era
  log — cover both directions).
* **v4** — adds the ``retrace`` record kind (the static-analysis
  subsystem's runtime retrace detector, ``analysis_level != 'off'``).
  Pure addition: every v1..v3 record validates unchanged
  (``tests/fixtures/telemetry_v3_schema.jsonl`` pins a v3-era log) and
  the forward-compat rules carry over (the future-schema fixture is
  re-pinned at v5-unknown).
* **v5** — adds the ``analysis`` record kind (the build-time program
  audit summary: program/violation counts, the SPMD audit mesh and the
  flagship roofline summary). Pure addition: every v1..v4 record
  validates unchanged (``tests/fixtures/telemetry_v4_schema.jsonl`` pins
  a v4-era log) and the forward-compat rules carry over (the
  future-schema fixture is re-pinned at v6-unknown).
* **v6** — adds the ``elastic`` record kind (elastic multi-host
  training: coordinated preemption drain events and topology-change
  resume markers). Pure addition: every v1..v5 record validates
  unchanged (``tests/fixtures/telemetry_v5_schema.jsonl`` pins a v5-era
  log) and the forward-compat rules carry over (the future-schema
  fixture is re-pinned at v7-unknown).
* **v7** — the ``dispatch`` record gains the optional epoch-boundary
  overlap fields (``overlap_ms`` / ``boundary_overlaps`` /
  ``accum_steps`` — the throughput-overhaul telemetry: how much of the
  epoch boundary the double-buffered dispatch pipeline hid, and the
  train step's gradient-accumulation setting). Pure addition — no new
  kinds, no new REQUIRED fields: every v1..v6 record validates unchanged
  (``tests/fixtures/telemetry_v6_schema.jsonl`` pins a v6-era log) and
  the forward-compat rules carry over (the future-schema fixture is
  re-pinned at v8-unknown).
* **v8** — adds the ``serving`` record kind (the adapt-on-request
  serving engine: per-dispatch tenants/bucket/queue/adapt latency and
  the p50/p95 + tenants-per-sec rollup). Pure addition: every v1..v7
  record validates unchanged (``tests/fixtures/telemetry_v7_schema.jsonl``
  pins a v7-era log) and the forward-compat rules carry over (the
  future-schema fixture is re-pinned at v9-unknown).
* **v9** — the ``serving`` record gains the fast-path fields: dispatch
  records carry ``program`` / ``ingest`` / ``ingest_bytes`` /
  ``cache_hits`` (the uint8/index ingest tiers and the adapted-params
  cache), a new ``event='warmup'`` shape records export-artifact vs
  compile warmups (``mode`` / ``warmup_ms`` / ``xla_compiles``), and
  the rollup gains ``h2d_bytes_per_dispatch`` / ``cache_hit_rate``.
  Pure addition — no new kinds, no new REQUIRED fields (``serving``
  still requires only ``event``): every v1..v8 record validates
  unchanged (``tests/fixtures/telemetry_v8_schema.jsonl`` pins a
  v8-era log) and the forward-compat rules carry over (the
  future-schema fixture is re-pinned at v10-unknown).
* **v10** — adds the ``span`` record kind (the causal-tracing layer:
  request-/step-scoped intervals with trace/span/parent ids, exported
  to Chrome/Perfetto by ``cli trace``), and the ``serving`` dispatch
  record gains the optional latency-decomposition fields ``batch_ms``
  (host batch assembly), ``dispatch_ms`` (device dispatch enqueue) and
  ``sync_ms`` (host-blocking result fetch) — with ``queue_ms`` they
  decompose the end-to-end request latency; the rollup mirrors them as
  ``batch_ms_mean`` / ``dispatch_ms_p50`` / ``sync_ms_p50``. Pure
  addition beyond the new kind: every v1..v9 record validates
  unchanged (``tests/fixtures/telemetry_v9_schema.jsonl`` pins a
  v9-era log) and the forward-compat rules carry over (the
  future-schema fixture is re-pinned at v11-unknown).
* **v11** — the multi-replica serving pool (serving/replica.py /
  router.py / refresh.py): ``serving`` records emitted by a pooled
  engine carry an optional ``replica_id``, and a new
  ``event='rollover'`` shape records each replica's zero-downtime
  checkpoint swap (``old_iter`` / ``new_iter``, standby warmup
  mode/seconds, ``swap_ms``, ``xla_compiles_at_swap``). Pure
  addition — no new kinds, no new REQUIRED fields (``serving`` still
  requires only ``event``): every v1..v10 record validates unchanged
  (``tests/fixtures/telemetry_v10_schema.jsonl`` pins a v10-era log)
  and the forward-compat rules carry over (the future-schema fixture
  is re-pinned at v12-unknown).
* **v12** — the serving SLO observability layer: adds the ``slo``
  record kind (the deadline/burn-rate report — ``target_ms``,
  ``availability``, ``requests``, ``missed``, per-window
  ``burn_rates``), the ``serving`` ``event='deadline'`` shape (one
  resolved deadline-carrying request: ``slack_ms`` / ``missed`` plus
  the queue/route/batch/dispatch/sync stage attribution), and the
  rollup's honesty/distribution fields (``window_dropped``,
  ``adapt_ms_hist`` / ``queue_ms_hist`` — mergeable log-bucketed
  histograms). Pure addition beyond the new kind (``serving`` still
  requires only ``event``; ``slo`` requires ``target_ms`` /
  ``requests`` / ``missed``): every v1..v11 record validates unchanged
  (``tests/fixtures/telemetry_v11_schema.jsonl`` pins a v11-era log)
  and the forward-compat rules carry over (the future-schema fixture
  is re-pinned at v13-unknown).
* **v13** — the networked fleet front tier (serving/gateway.py /
  fleet.py): adds the ``gateway`` record kind (``event`` = ``shed`` —
  one typed edge rejection with its admission/deadline ``reason`` —
  ``rehome`` — a host tripped out of the consistent-hash ring with its
  chained root ``cause`` and the stranded ``in_flight`` count — or
  ``rollup`` — the fleet aggregate with exact bucket-wise histogram
  merges), and the ``serving`` ``event='deadline'`` record gains the
  optional gateway-path fields ``priority`` (the request's admission
  tier) and ``gateway_ms`` (edge time: decode + admission + forward
  before the home host enqueued it). Pure addition beyond the new kind
  (``gateway`` requires only ``event``; ``serving`` still requires only
  ``event``): every v1..v12 record validates unchanged
  (``tests/fixtures/telemetry_v12_schema.jsonl`` pins a v12-era log)
  and the forward-compat rules carry over (the future-schema fixture
  is re-pinned at v14-unknown).
* **v14** — fleet-wide distributed tracing (gateway ↔ host trace
  propagation over the wire): ``span`` records gain the optional
  top-level ``process`` field (the emitting process's fleet identity —
  ``gateway`` or a host id — stamped by per-process tracers so ``cli
  trace --fleet`` can assign Perfetto process tracks), host-side
  request roots adopted from a gateway parent carry the wire-delivered
  ``clock_offset_ms`` attr, and the ``gateway`` kind grows two things:
  a new ``event='clock'`` shape (the health sweep's Cristian clock
  estimate for one ``host`` — ``clock_offset_ms``, the error bound
  ``clock_skew_bound_ms`` = RTT/2 of the min-RTT sample, and
  ``rtt_ms`` — emitted whenever a lower-RTT sample tightens the bound,
  so the LAST clock record per host is always the best estimate) and
  optional ``trace_id`` / ``request_id`` fields on ``shed`` records
  (a typed rejection is joinable to its zero-duration shed span).
  Pure addition — no new kinds, no new REQUIRED fields (``gateway``
  still requires only ``event``; ``span`` required fields unchanged):
  every v1..v13 record validates unchanged
  (``tests/fixtures/telemetry_v13_schema.jsonl`` pins a v13-era log)
  and the forward-compat rules carry over (the future-schema fixture
  is re-pinned at v15-unknown).
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterator, Tuple

SCHEMA_VERSION = 14
#: oldest version this validator fully understands (v1 is a strict subset)
MIN_SCHEMA_VERSION = 1

#: kind -> required payload fields (beyond the schema/ts/kind envelope)
KIND_FIELDS: Dict[str, Tuple[str, ...]] = {
    "run_start": ("experiment_name", "telemetry_level", "resume_iter"),
    "run_end": (),
    "epoch": ("epoch", "scalars"),
    "stream": ("epoch", "batches", "assembly_ms_per_batch",
               "stall_ms_per_batch", "queue_depth_mean"),
    "dispatch": ("epoch",),
    "checkpoint": ("epoch", "path"),
    "device_memory": ("epoch", "store_bytes_expected"),
    "dynamics": ("iter_start", "num_iters", "support_losses",
                 "target_losses", "grad_norms", "lslr", "msl_weights"),
    "trace": ("action",),
    "watchdog_stall": ("stage", "seconds_since_progress", "stacks"),
    "anomaly": ("iter", "reason", "value", "threshold"),
    "incident": ("iter", "reason", "path"),
    "retry": ("site", "attempt", "max_attempts", "error"),
    "preemption": ("iter", "signal", "checkpoint"),
    "retrace": ("iter", "site", "signature"),
    "analysis": ("programs", "violations"),
    "elastic": ("event",),
    "serving": ("event",),
    "gateway": ("event",),
    "slo": ("target_ms", "requests", "missed"),
    "span": ("name", "cat", "trace_id", "span_id", "start_ms", "dur_ms"),
}


def validate_record(rec: Any) -> None:
    """Raise ``ValueError`` when ``rec`` is not a valid telemetry record.

    Forward-compatible by design: a record stamped with a schema version
    NEWER than this validator gets envelope-only checks (numeric ``ts``,
    non-empty string ``kind``) — unknown kinds and unknown fields from a
    future writer pass, so mixed-version logs (resumed runs across
    upgrades, ``telemetry_cli diff`` against a newer run) stay readable.
    Non-integer or pre-``MIN_SCHEMA_VERSION`` versions are still rejected:
    they indicate corruption, not the future.
    """
    if not isinstance(rec, dict):
        raise ValueError(f"telemetry record must be an object, got {type(rec).__name__}")
    ver = rec.get("schema")
    if isinstance(ver, bool) or not isinstance(ver, int) or ver < MIN_SCHEMA_VERSION:
        raise ValueError(
            f"unknown telemetry schema version {ver!r} (this validator "
            f"understands {MIN_SCHEMA_VERSION}..{SCHEMA_VERSION} and "
            "tolerates newer)"
        )
    if not isinstance(rec.get("ts"), (int, float)):
        raise ValueError(f"telemetry record missing numeric 'ts': {rec!r}")
    kind = rec.get("kind")
    if ver > SCHEMA_VERSION:
        # a newer writer: envelope checked above; its kinds and fields are
        # its own business
        if not isinstance(kind, str) or not kind:
            raise ValueError(
                f"telemetry record missing string 'kind': {rec!r}"
            )
        return
    if kind not in KIND_FIELDS:
        raise ValueError(
            f"unknown telemetry record kind {kind!r}; known kinds: "
            f"{sorted(KIND_FIELDS)}"
        )
    missing = [f for f in KIND_FIELDS[kind] if f not in rec]
    if missing:
        raise ValueError(
            f"telemetry record of kind {kind!r} missing required fields "
            f"{missing}: {rec!r}"
        )
    if kind == "dynamics":
        # the acceptance surface of the on-device collection: per-inner-step
        # losses are lists, grad norms / LSLR are per-layer mappings
        for field in ("support_losses", "target_losses", "msl_weights"):
            if not isinstance(rec[field], list):
                raise ValueError(
                    f"dynamics record field {field!r} must be a list, got "
                    f"{type(rec[field]).__name__}"
                )
        for field in ("grad_norms", "lslr"):
            if not isinstance(rec[field], dict) or not rec[field]:
                raise ValueError(
                    f"dynamics record field {field!r} must be a non-empty "
                    f"per-layer mapping, got {rec[field]!r}"
                )


def iter_records(path: str) -> Iterator[dict]:
    """Yield parsed records from a telemetry JSONL file (no validation)."""
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                yield json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(
                    f"{path}:{lineno}: not valid JSON ({e})"
                ) from e


def validate_file(path: str) -> int:
    """Validate every record in a telemetry JSONL file.

    Returns the number of records; raises ``ValueError`` naming the first
    offending line. This is what the CI schema-validation job runs against
    the log a tiny telemetry-enabled train emits.
    """
    count = 0
    for rec in iter_records(path):
        try:
            validate_record(rec)
        except ValueError as e:
            raise ValueError(f"{path}: record {count + 1}: {e}") from e
        count += 1
    return count
