"""Heartbeat hang watchdog — the primitive multihost hang debugging needs.

A multihost mesh hangs silently when one host misses a collective (a
checkpoint barrier, a psum inside a dispatch) — every other host blocks in
XLA with no Python-level symptom. The ``Watchdog`` is a daemon thread fed
progress beats by the experiment loop (``beat(stage)`` at each dispatch /
eval chunk / checkpoint); when no beat arrives for ``timeout_s`` it emits a
diagnostic record — current stage, seconds since progress, and a stack
snapshot of every live thread (which names the exact blocking call) —
through the supplied callback, then re-arms on the next beat. One record
per stall: a wedged run produces one loud diagnostic, not a log flood.
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from typing import Callable, Dict, Optional


def thread_stacks() -> Dict[str, str]:
    """Formatted stack of every live thread, keyed ``name(ident)``."""
    names = {t.ident: t.name for t in threading.enumerate()}
    stacks = {}
    for ident, frame in sys._current_frames().items():
        label = f"{names.get(ident, 'unknown')}({ident})"
        stacks[label] = "".join(traceback.format_stack(frame))
    return stacks


class Watchdog:
    """Fires ``on_stall(record)`` when beats stop arriving for ``timeout_s``.

    ``record`` carries ``stage`` (the last reported stage), ``beat_count``,
    ``seconds_since_progress`` and ``stacks`` — ready to pass to
    ``Telemetry.event("watchdog_stall", **record)``.
    """

    def __init__(
        self,
        timeout_s: float,
        on_stall: Callable[[dict], None],
        poll_s: Optional[float] = None,
        exclude_own_stack: bool = True,
    ):
        if timeout_s <= 0:
            raise ValueError(f"watchdog timeout must be > 0, got {timeout_s}")
        self.timeout_s = float(timeout_s)
        self.poll_s = poll_s if poll_s is not None else min(1.0, timeout_s / 4)
        self.on_stall = on_stall
        self._exclude_own = exclude_own_stack
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._last_beat = time.monotonic()
        self._stage = "startup"
        self._beats = 0
        self._fired = False
        self.stall_count = 0
        self._thread: Optional[threading.Thread] = None

    # -- producer side (the experiment loop) ------------------------------

    def beat(self, stage: str) -> None:
        """Report progress; cheap enough for every dispatch."""
        with self._lock:
            self._stage = stage
            self._last_beat = time.monotonic()
            self._beats += 1
            self._fired = False  # re-arm after recovery

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "Watchdog":
        with self._lock:
            # the stall clock runs from start(), not construction: a builder
            # may be built long before run_experiment() begins beating
            self._last_beat = time.monotonic()
        self._thread = threading.Thread(
            target=self._run, name="telemetry-watchdog", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(self.poll_s * 4 + 1.0)

    def __enter__(self) -> "Watchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- monitor thread ----------------------------------------------------

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            with self._lock:
                stalled_for = time.monotonic() - self._last_beat
                fired = self._fired
                stage = self._stage
                beats = self._beats
                if stalled_for > self.timeout_s and not fired:
                    self._fired = True
                else:
                    continue
            stacks = thread_stacks()
            if self._exclude_own:
                stacks = {
                    k: v for k, v in stacks.items()
                    if not k.startswith("telemetry-watchdog(")
                }
            self.stall_count += 1
            record = {
                "stage": stage,
                "beat_count": beats,
                "seconds_since_progress": round(stalled_for, 3),
                "timeout_s": self.timeout_s,
                "stacks": stacks,
            }
            try:
                self.on_stall(record)
            except Exception:  # noqa: BLE001 - the watchdog must never kill the run
                traceback.print_exc()
