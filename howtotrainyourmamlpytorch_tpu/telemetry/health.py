"""Host-side training-health monitor over the on-device anomaly probes.

``health_level != 'off'`` makes every train dispatch return a tiny
``metrics['health']`` dict computed on device (core/maml._health_probes):
the outer loss, the PRE-clip global meta-gradient L2 norm, the count of
non-finite gradient elements, and the update/parameter norms. The
``HealthMonitor`` here consumes those payloads with a ONE-DISPATCH LAG:
the system facade's one-step-lag sync guarantees that by the time dispatch
N+1 is enqueued, dispatch N's outputs are materialised on device — so
fetching them then is a copy of ready buffers, never a blocking sync, and
the hot loop keeps its zero-added-syncs contract. The price is that an
anomaly is detected up to one dispatch (``steps_per_dispatch`` iterations)
after it happened; the flight recorder's ring preserves the lead-up
regardless.

Detection rules (``AnomalyDetector``):

* ``nonfinite_grads`` / ``nonfinite_loss`` — always armed: any non-finite
  gradient element or loss is an anomaly (MAML++'s second-order path
  through an unrolled inner loop is exactly where an inf/NaN appears many
  iterations before the epoch CSV shows it);
* ``loss_spike`` / ``grad_norm_spike`` — EMA-relative: value > factor ×
  its own exponential moving average, armed after ``warmup_steps``
  observations (factor 0 disables the rule);
* ``grad_norm_limit`` — absolute ceiling on the pre-clip global grad norm
  (0 disables): no warmup needed, so it also catches a run whose
  gradients are already huge at step 0;
* ``update_ratio`` — absolute ceiling on ||update|| / ||params|| (0
  disables): a single outer step moving the parameters by a large fraction
  of their norm means the LR/LSLR schedule has blown up.

Each fired rule is suppressed for ``cooldown_steps`` iterations (a run
wedged at NaN reports once per window, not once per step). Anomalies are
emitted as schema-versioned ``anomaly`` telemetry records, logged to
stderr, and handed to the :class:`~.flight_recorder.FlightRecorder`,
which dumps its ring + (when legal) a full state checkpoint as an
``incident``.

Escalation (``health_level='halt'``): the detector counts anomalous
iterations regardless of cooldown suppression; once the count reaches
``health_patience``, the monitor latches a halt decision. The experiment
builder — the owner of checkpointing — observes ``should_halt`` on the
train-loop thread, writes a resumable emergency checkpoint
(``train_model_emergency``) plus a final forced incident dump, and raises
:class:`TrainingDivergedError` instead of training on garbage.
"""

from __future__ import annotations

import math
import sys
from typing import Any, Callable, Dict, List, Optional

import numpy as np

#: the keys core/maml._health_probes returns per step
PROBE_KEYS = (
    "loss", "grad_norm", "nonfinite_grads", "update_norm", "param_norm",
)


class TrainingDivergedError(RuntimeError):
    """Raised by a ``health_level='halt'`` run once ``health_patience``
    anomalous iterations have been observed — after the emergency
    checkpoint and the forensic incident dump are on disk (their locations
    ride on the exception for the caller / crash log)."""

    def __init__(
        self,
        message: str,
        iter_at_halt: Optional[int] = None,
        dump_dir: Optional[str] = None,
        checkpoint_path: Optional[str] = None,
    ):
        super().__init__(message)
        self.iter_at_halt = iter_at_halt
        self.dump_dir = dump_dir
        self.checkpoint_path = checkpoint_path


class AnomalyDetector:
    """Pure host-side rule engine over per-step probe entries (see module
    doc for the rules). ``update()`` returns the anomalies one step fired —
    each a dict with ``iter``, ``reason``, ``value``, ``threshold``."""

    def __init__(
        self,
        loss_spike_factor: float = 10.0,
        grad_spike_factor: float = 10.0,
        update_ratio_max: float = 0.0,
        grad_norm_limit: float = 0.0,
        ema_beta: float = 0.98,
        warmup_steps: int = 20,
        cooldown_steps: int = 200,
    ):
        self.loss_spike_factor = float(loss_spike_factor)
        self.grad_spike_factor = float(grad_spike_factor)
        self.update_ratio_max = float(update_ratio_max)
        self.grad_norm_limit = float(grad_norm_limit)
        self.ema_beta = float(ema_beta)
        self.warmup_steps = int(warmup_steps)
        self.cooldown_steps = int(cooldown_steps)
        self._ema: Dict[str, float] = {}
        self._seen = 0
        self._last_fired: Dict[str, int] = {}
        #: iterations where any rule condition HELD — counted even when the
        #: cooldown suppressed the report, so halt patience cannot be
        #: stretched by the per-reason report rate limiting
        self.anomalous_iterations = 0
        self._iter_flagged = False

    @classmethod
    def from_config(cls, cfg) -> "AnomalyDetector":
        return cls(
            loss_spike_factor=cfg.anomaly_loss_spike_factor,
            grad_spike_factor=cfg.anomaly_grad_spike_factor,
            update_ratio_max=cfg.anomaly_update_ratio_max,
            grad_norm_limit=cfg.health_grad_norm_limit,
            ema_beta=cfg.anomaly_ema_beta,
            warmup_steps=cfg.anomaly_warmup_steps,
            cooldown_steps=cfg.anomaly_cooldown_steps,
        )

    def ema(self, key: str) -> Optional[float]:
        return self._ema.get(key)

    def _fire(
        self, out: List[Dict[str, Any]], iter_idx: int, reason: str,
        value: float, threshold: float,
    ) -> None:
        self._iter_flagged = True  # condition held; cooldown only gates
        last = self._last_fired.get(reason)  # the report below
        if (
            last is not None
            and self.cooldown_steps > 0
            and 0 <= iter_idx - last < self.cooldown_steps
        ):
            return
        self._last_fired[reason] = iter_idx
        out.append({
            "iter": int(iter_idx),
            "reason": reason,
            "value": float(value),
            "threshold": float(threshold),
        })

    def _spike(
        self, out, iter_idx, reason: str, key: str, value: float,
        factor: float,
    ) -> None:
        """EMA-relative spike rule for ``key``; also folds ``value`` into
        the EMA (finite values only — a NaN loss must not poison the
        baseline the recovery will be judged against)."""
        baseline = self._ema.get(key)
        armed = (
            factor > 0
            and baseline is not None
            and self._seen >= self.warmup_steps
        )
        if armed and math.isfinite(value) and value > factor * baseline:
            self._fire(out, iter_idx, reason, value, factor * baseline)
        if math.isfinite(value):
            if baseline is None:
                self._ema[key] = value
            else:
                b = self.ema_beta
                self._ema[key] = b * baseline + (1.0 - b) * value

    def update(
        self, iter_idx: int, entry: Dict[str, Any]
    ) -> List[Dict[str, Any]]:
        anomalies: List[Dict[str, Any]] = []
        self._iter_flagged = False
        loss = float(entry.get("loss", np.nan))
        grad_norm = float(entry.get("grad_norm", np.nan))
        nonfinite = int(entry.get("nonfinite_grads", 0))
        if nonfinite > 0:
            self._fire(anomalies, iter_idx, "nonfinite_grads",
                       nonfinite, 0.0)
        if not math.isfinite(loss):
            self._fire(anomalies, iter_idx, "nonfinite_loss", loss, 0.0)
        if "grad_norm" in entry and not math.isfinite(grad_norm) \
                and nonfinite == 0:
            # every gradient ELEMENT is finite but the f32 sum-of-squares
            # reduction overflowed to inf: the update built from this
            # gradient is garbage, yet no element-level rule sees it —
            # always armed, like the other non-finite rules
            self._fire(anomalies, iter_idx, "nonfinite_grad_norm",
                       grad_norm, 0.0)
        self._spike(anomalies, iter_idx, "loss_spike", "loss", loss,
                    self.loss_spike_factor)
        self._spike(anomalies, iter_idx, "grad_norm_spike", "grad_norm",
                    grad_norm, self.grad_spike_factor)
        if (
            self.grad_norm_limit > 0
            and math.isfinite(grad_norm)
            and grad_norm > self.grad_norm_limit
        ):
            # a non-finite norm is the nonfinite_grads /
            # nonfinite_grad_norm rules' job (both always armed)
            self._fire(anomalies, iter_idx, "grad_norm_limit", grad_norm,
                       self.grad_norm_limit)
        if self.update_ratio_max > 0:
            ratio = float(entry.get("update_norm", 0.0)) / (
                float(entry.get("param_norm", 0.0)) + 1e-12
            )
            if math.isfinite(ratio) and ratio > self.update_ratio_max:
                self._fire(anomalies, iter_idx, "update_ratio", ratio,
                           self.update_ratio_max)
        self._seen += 1
        if self._iter_flagged:
            self.anomalous_iterations += 1
        return anomalies


class HealthMonitor:
    """Builder-side driver: defers each dispatch's device probe payload,
    evaluates the previous one (already materialised by the one-step-lag
    sync), feeds the ring, and reports anomalies (telemetry ``anomaly``
    record + stderr line + flight-recorder ``incident`` dump)."""

    def __init__(
        self,
        cfg,
        telemetry=None,
        recorder=None,
        state_dump_fn: Optional[Callable[[str], None]] = None,
    ):
        self.detector = AnomalyDetector.from_config(cfg)
        self.level = cfg.health_level
        self.patience = int(cfg.health_patience)
        self.telemetry = telemetry
        self.recorder = recorder
        self.state_dump_fn = state_dump_fn
        self._pending = None  # (iter_start, device payload)
        self.anomaly_count = 0
        self.steps_seen = 0
        #: the most recently evaluated per-step entry (watchdog-stall
        #: context: "what did training health look like when we hung")
        self.last_entry: Optional[Dict[str, Any]] = None
        #: latched halt decision (health_level='halt' only): the anomaly
        #: that crossed the patience threshold. The builder reads
        #: ``should_halt`` on the train-loop thread and performs the
        #: emergency checkpoint + dump + raise — the monitor never raises
        #: itself, so detection stays side-effect-free and testable.
        self.halt_anomaly: Optional[Dict[str, Any]] = None

    @property
    def should_halt(self) -> bool:
        return self.halt_anomaly is not None

    # -- intake ------------------------------------------------------------

    def observe(self, iter_start: int, health) -> None:
        """Queue this dispatch's (device-array) probe payload; evaluate the
        PREVIOUS dispatch's, whose buffers the one-step-lag sync has
        already made ready — detection without ever blocking on the
        dispatch just enqueued."""
        prev, self._pending = self._pending, (int(iter_start), health)
        if prev is not None:
            self._evaluate(*prev)

    def flush(self) -> None:
        """Evaluate the still-deferred last dispatch (epoch summary / run
        end — the one place the monitor does pay a device sync, where the
        builder is already synchronizing for the summary anyway)."""
        prev, self._pending = self._pending, None
        if prev is not None:
            self._evaluate(*prev)

    # -- evaluation --------------------------------------------------------

    @staticmethod
    def _entries(payload) -> List[Dict[str, Any]]:
        """One host dict per iteration from a dispatch payload: a dict of
        scalars (plain step), of (k,)-stacked arrays (fused multi-step), or
        a list of per-iteration dicts (the multihost fallback path)."""
        import jax

        payload = jax.device_get(payload)
        if isinstance(payload, list):
            dicts = payload
        else:
            arrs = {
                k: np.atleast_1d(np.asarray(v)) for k, v in payload.items()
            }
            n = len(next(iter(arrs.values()))) if arrs else 0
            dicts = [
                {k: a[i] for k, a in arrs.items()} for i in range(n)
            ]
        return [
            {k: np.asarray(v).item() for k, v in d.items()} for d in dicts
        ]

    def _evaluate(self, iter_start: int, payload) -> None:
        for j, probes in enumerate(self._entries(payload)):
            it = iter_start + j
            entry = {"iter": it, **probes}
            self.steps_seen += 1
            self.last_entry = entry
            if self.recorder is not None:
                self.recorder.record_step(entry)
            anomalies = self.detector.update(it, entry)
            for anomaly in anomalies:
                self._report(anomaly, entry)
            if (
                self.level == "halt"
                and self.halt_anomaly is None
                and self.detector.anomalous_iterations >= self.patience
            ):
                # latch on the anomalous-ITERATION count, not the reported
                # anomalies: cooldown suppression must not stretch patience
                self.halt_anomaly = (
                    anomalies[0] if anomalies
                    else {"iter": it, "reason": "anomaly_under_cooldown",
                          "value": float("nan"), "threshold": float("nan")}
                )

    def _report(self, anomaly: Dict[str, Any], entry: Dict[str, Any]) -> None:
        self.anomaly_count += 1
        print(
            f"[health] anomaly at iter {anomaly['iter']}: "
            f"{anomaly['reason']} (value={anomaly['value']:.6g}, "
            f"threshold={anomaly['threshold']:.6g})",
            file=sys.stderr,
            flush=True,
        )
        if self.telemetry is not None:
            self.telemetry.event(
                "anomaly",
                iter=anomaly["iter"],
                reason=anomaly["reason"],
                value=anomaly["value"],
                threshold=anomaly["threshold"],
                probes=entry,
            )
        if self.recorder is None:
            return
        self.recorder.note_event("anomaly", **anomaly)
        try:
            path = self.recorder.dump(
                anomaly["reason"],
                anomaly["iter"],
                details={"anomaly": anomaly, "probes": entry},
                state_dump_fn=self.state_dump_fn,
            )
        except Exception as e:  # noqa: BLE001 - best-effort forensics: a
            # disk-full/permission error writing the incident must not kill
            # the (possibly healthy-again) run it is documenting
            print(f"[health] incident dump failed: {e!r}", file=sys.stderr,
                  flush=True)
            return
        if path is None:
            return
        print(f"[health] incident dumped to {path}", file=sys.stderr,
              flush=True)
        if self.telemetry is not None:
            self.telemetry.event(
                "incident",
                iter=anomaly["iter"],
                reason=anomaly["reason"],
                path=path,
            )
