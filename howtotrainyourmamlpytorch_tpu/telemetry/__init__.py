"""Structured telemetry: on-device training-dynamics metrics, JSONL /
TensorBoard sinks, and the multihost hang watchdog.

See ``schema.py`` for the event-record schema, ``sinks.py`` for the
``Telemetry`` facade the experiment layer drives, and ``watchdog.py`` for
the heartbeat hang watchdog.
"""

from .schema import (  # noqa: F401
    KIND_FIELDS,
    SCHEMA_VERSION,
    iter_records,
    validate_file,
    validate_record,
)
from .sinks import (  # noqa: F401
    TELEMETRY_FILENAME,
    JsonlSink,
    Telemetry,
    TensorBoardSink,
)
from .watchdog import Watchdog, thread_stacks  # noqa: F401
