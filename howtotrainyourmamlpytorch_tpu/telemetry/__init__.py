"""Structured telemetry: on-device training-dynamics metrics, JSONL /
TensorBoard sinks, the multihost hang watchdog, and the training-health
monitor.

See ``schema.py`` for the event-record schema, ``sinks.py`` for the
``Telemetry`` facade the experiment layer drives, ``watchdog.py`` for
the heartbeat hang watchdog, ``health.py`` for the anomaly detector over
the on-device probes, and ``flight_recorder.py`` for the incident ring /
state-dump machinery.
"""

from .flight_recorder import (  # noqa: F401
    INCIDENT_MANIFEST,
    RING_FILENAME,
    FlightRecorder,
)
from .health import (  # noqa: F401
    PROBE_KEYS,
    AnomalyDetector,
    HealthMonitor,
    TrainingDivergedError,
)
from .schema import (  # noqa: F401
    KIND_FIELDS,
    MIN_SCHEMA_VERSION,
    SCHEMA_VERSION,
    iter_records,
    validate_file,
    validate_record,
)
from .sinks import (  # noqa: F401
    TELEMETRY_FILENAME,
    JsonlSink,
    Telemetry,
    TensorBoardSink,
    make_record,
)
from .tracing import (  # noqa: F401
    NULL_TRACER,
    Span,
    Tracer,
    critical_path_summary,
    new_trace_id,
    span_records,
    to_chrome_trace,
)
from .watchdog import Watchdog, thread_stacks  # noqa: F401
