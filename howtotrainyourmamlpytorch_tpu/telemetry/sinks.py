"""Telemetry sinks: JSONL structured event log + optional TensorBoard.

The ``Telemetry`` facade is the one object the experiment layer talks to.
It is a no-op when ``cfg.telemetry_level == 'off'`` or on non-primary
hosts, so the hot loop can call it unconditionally; when enabled it writes
schema-versioned records (:mod:`telemetry.schema`) to
``logs/telemetry.jsonl`` and optionally mirrors scalar summaries to
TensorBoard. All writes are lock-guarded — the hang watchdog emits records
from its own thread.
"""

from __future__ import annotations

import json
import math
import os
import sys
import threading
import time
from typing import Any, Dict, Optional

import numpy as np

from .schema import SCHEMA_VERSION

TELEMETRY_FILENAME = "telemetry.jsonl"


def _jsonable(value: Any, counter: Optional[list] = None) -> Any:
    """Recursively convert numpy/device arrays and scalars to JSON types.

    Non-finite floats become null: json.dumps would otherwise emit bare
    NaN/Infinity tokens, which Python's json accepts but spec-strict
    consumers (jq, JSON.parse, warehouse loaders) reject — and a diverging
    run is exactly when the log must stay machine-readable. The masking is
    *counted*, not silent: ``counter`` (a single-element mutable list, when
    given) accumulates how many non-finite values were nulled, and
    ``make_record`` attaches the totals to the record envelope — so the
    anomaly signal the nulls erase stays queryable from JSONL. The one
    device->host synchronization for dynamics happens here, at flush time —
    never inside the train loop.
    """
    if isinstance(value, dict):
        return {str(k): _jsonable(v, counter) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_jsonable(v, counter) for v in value]
    if isinstance(value, float):
        if math.isfinite(value):
            return value
        if counter is not None:
            counter[0] += 1
        return None
    if isinstance(value, (str, bool, int)) or value is None:
        return value
    arr = np.asarray(value)
    if arr.ndim == 0:
        return _jsonable(arr.item(), counter)
    if not (np.issubdtype(arr.dtype, np.integer) or arr.dtype == np.bool_):
        # float64 normalizes the extended float dtypes too (bfloat16 is
        # dtype kind 'V', which issubdtype(..., floating) misses) so the
        # finiteness mask can never be skipped for a float-like payload
        arr = arr.astype(np.float64)
        finite = np.isfinite(arr)
        if not finite.all():
            if counter is not None:
                counter[0] += int((~finite).sum())
            out = arr.astype(object)
            out[~finite] = None
            return out.tolist()
    return arr.tolist()


def make_record(kind: str, **fields: Any) -> Dict[str, Any]:
    """Build one schema-enveloped, JSON-safe record from raw field values.

    The single construction point for every telemetry record. Converts
    every field through ``_jsonable`` tracking per-field non-finite counts;
    when any value was masked to null the envelope gains
    ``nonfinite_count`` (total) and ``nonfinite_fields`` (per payload
    field) — for array payloads like the dynamics stacks this is the
    per-array count that makes "which stack went NaN, and how badly"
    answerable without the original device arrays.
    """
    payload: Dict[str, Any] = {}
    counts: Dict[str, int] = {}
    for key, value in fields.items():
        counter = [0]
        payload[str(key)] = _jsonable(value, counter)
        if counter[0]:
            counts[str(key)] = counter[0]
    record: Dict[str, Any] = {
        "schema": SCHEMA_VERSION,
        "ts": time.time(),  # lint-ok: MP007 the record envelope's wall-clock timestamp
        "kind": kind,
        **payload,
    }
    if counts:
        record["nonfinite_count"] = sum(counts.values())
        record["nonfinite_fields"] = counts
    return record


class JsonlSink:
    """Append-only JSONL event log (one schema-versioned record per line)."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._f = open(path, "a")

    def write(self, record: Dict[str, Any]) -> None:
        line = json.dumps(record)
        with self._lock:
            if self._f.closed:
                return
            self._f.write(line + "\n")
            # flushed per record: the log's whole point is being readable
            # while (or after) the run hangs/crashes
            self._f.flush()

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.close()


def _import_summary_writer():
    """Resolve a SummaryWriter class, or raise ImportError.

    Prefers ``tensorboardX`` (pure-python, no TF dependency), falling back
    to torch's bundled writer. Isolated in a function so tests can simulate
    the no-TensorBoard environment by monkeypatching it.
    """
    try:
        from tensorboardX import SummaryWriter  # type: ignore
        return SummaryWriter
    except ImportError:
        from torch.utils.tensorboard import SummaryWriter  # type: ignore
        return SummaryWriter


class TensorBoardSink:
    """Optional TensorBoard scalar sink.

    Degrades to disabled (with one stderr note) when no SummaryWriter
    implementation is importable — telemetry must never add a hard
    dependency the container doesn't have.
    """

    def __init__(self, log_dir: str):
        self.writer = None
        try:
            writer_cls = _import_summary_writer()
        except ImportError:
            print(
                "[telemetry] TensorBoard sink requested but no SummaryWriter "
                "available (tensorboardX / torch.utils.tensorboard); scalars "
                "go to the JSONL log only",
                file=sys.stderr,
                flush=True,
            )
            return
        self.writer = writer_cls(log_dir=log_dir)

    @property
    def enabled(self) -> bool:
        return self.writer is not None

    def scalars(self, step: int, values: Dict[str, Any]) -> None:
        if self.writer is None:
            return
        for key, value in values.items():
            try:
                self.writer.add_scalar(key, float(value), int(step))
            except (TypeError, ValueError):
                continue  # non-scalar entries (lists, strings) are JSONL-only

    def close(self) -> None:
        if self.writer is not None:
            self.writer.close()
            self.writer = None


class Telemetry:
    """The experiment layer's telemetry facade.

    ``level`` mirrors ``cfg.telemetry_level``: 'off' makes every method a
    cheap no-op (the builder calls them unconditionally), 'scalars' writes
    run/epoch/stream/checkpoint/memory/watchdog events, 'dynamics'
    additionally receives the on-device training-dynamics stacks collected
    inside the fused dispatches (see core.maml) via ``dynamics()``.
    """

    def __init__(self, cfg, log_dir: str, is_primary: bool = True):
        self.level = getattr(cfg, "telemetry_level", "off")
        self.enabled = bool(is_primary) and self.level != "off"
        self.jsonl: Optional[JsonlSink] = None
        self.tensorboard: Optional[TensorBoardSink] = None
        if self.enabled:
            self.jsonl = JsonlSink(os.path.join(log_dir, TELEMETRY_FILENAME))
            if getattr(cfg, "telemetry_tensorboard", False):
                self.tensorboard = TensorBoardSink(
                    os.path.join(log_dir, "tensorboard")
                )

    # -- record emission ---------------------------------------------------

    def event(self, kind: str, **fields: Any) -> None:
        """Write one schema-versioned record (thread-safe)."""
        if not self.enabled or self.jsonl is None:
            return
        self.jsonl.write(make_record(kind, **fields))

    def epoch_scalars(self, epoch: int, scalars: Dict[str, Any]) -> None:
        """The per-epoch summary: one JSONL record + TensorBoard mirror."""
        if not self.enabled:
            return
        numeric = {
            k: v for k, v in scalars.items()
            if isinstance(v, (int, float)) and not isinstance(v, bool)
        }
        self.event("epoch", epoch=int(epoch), scalars=numeric)
        if self.tensorboard is not None:
            self.tensorboard.scalars(int(epoch), numeric)

    def dynamics(self, iter_start: int, num_iters: int,
                 dyn: Dict[str, Any]) -> None:
        """One fused dispatch's on-device dynamics stacks.

        ``dyn`` is the nested dict the train step returned (device or host
        arrays): per-inner-step ``support_losses``/``target_losses``,
        per-layer ``grad_norms``/``lslr``, and the ``msl_weights`` vector.
        The np.asarray conversion here is the only host sync, at flush time.
        """
        if not self.enabled:
            return
        self.event(
            "dynamics",
            iter_start=int(iter_start),
            num_iters=int(num_iters),
            **{k: dyn[k] for k in sorted(dyn)},
        )

    def close(self) -> None:
        if self.tensorboard is not None:
            self.tensorboard.close()
        if self.jsonl is not None:
            self.event("run_end")
            self.jsonl.close()
