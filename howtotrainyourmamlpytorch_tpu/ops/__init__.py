from . import functional
