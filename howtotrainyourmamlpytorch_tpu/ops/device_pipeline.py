"""On-device episode assembly: gather -> decode -> rot90 inside the jit.

The host episode path (data/episodes.py) assembles float32 NHWC arrays with
GIL-bound threads and uploads ~4 bytes/subpixel per dispatch. This module
moves the pixel work into the jitted step so the host ships either

* raw **uint8** batches (``data_placement='uint8_stream'``): host gathers and
  rotates integer pixels, the device does the float cast / ``/255`` /
  stat-normalize — a 4x H2D reduction with no residency requirement; or
* **int32 index tensors only** (``data_placement='device'``): the split's
  flat uint8 store (preprocess.FlatStore) lives in HBM, uploaded once;
  per-batch H2D is a few KB of gather/rot-k indices and the gather itself
  runs on device.

Bit-exactness with the host path holds by construction: the decode applies
the *same* float ops in the *same* order as ``episodes.decode_cached`` +
``episodes.augment_stack`` (float32 cast; ``/255`` for non-Omniglot — the
Omniglot unrescaled-cast quirk preserved; RGB->BGR flip when
``reverse_channels``; ImageNet-stat normalize for the imagenet family), and
rot90 on integer pixels commutes with the elementwise decode. CIFAR is
excluded at config time: its per-image random crop/flip draws from the
episode RNG mid-stream and cannot be replayed from indices.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import MAMLConfig


def _decode_lut(cfg: MAMLConfig) -> np.ndarray:
    """(256, c) float32 lookup: ``lut[v, ch]`` is the host decode of uint8
    value v in channel ch.

    Built on the HOST by running the host pipeline itself
    (``episodes.decode_cached`` + ``augment_stack``'s normalization rules)
    over all 256 possible subpixel values — so device decode is bit-exact
    with the host path *by construction*, immune to XLA rewriting
    ``x / 255`` into a multiply-by-reciprocal (CPU fast-math does, measured
    ULP-level drift) or fusing the normalize into FMAs. A (256·c)-entry
    gather is also cheaper on device than three elementwise passes.
    """
    from ..data.episodes import augment_stack, decode_cached

    c = cfg.image_channels
    vals = np.tile(
        np.arange(256, dtype=np.uint8)[:, None, None, None], (1, 1, 1, c)
    )
    # the channel flip is handled on the uint8 indices (see make_decoder);
    # on this constant-per-channel probe it would be an identity anyway
    cfg_noflip = cfg.replace(reverse_channels=False)
    out = decode_cached(cfg_noflip, vals)  # cast (+ /255 unless Omniglot)
    out = augment_stack(cfg_noflip, out, k=0, augment=False)  # stat-normalize
    return np.ascontiguousarray(out.reshape(256, c))


def make_decoder(cfg: MAMLConfig) -> Callable[[jnp.ndarray], jnp.ndarray]:
    """uint8 pixels -> the reference's float32 values, inside jit.

    The device twin of ``episodes.decode_cached`` followed by
    ``episodes.augment_stack``'s normalization rules (rotation excluded —
    see ``make_index_expander``), realised as a per-channel value lookup so
    the outputs are bit-identical to the host path (see ``_decode_lut``).
    """
    lut = jnp.asarray(_decode_lut(cfg))
    chan = jnp.arange(cfg.image_channels)

    def decode(x: jnp.ndarray) -> jnp.ndarray:
        if cfg.reverse_channels:
            # RGB->BGR before the (per-output-channel) lookup — equivalent
            # to the host's flip-after-scale because the scale step is
            # channel-independent
            x = x[..., ::-1]
        return lut[x.astype(jnp.int32), chan]

    return decode


def _rot_stack(imgs: jnp.ndarray, k: jnp.ndarray) -> jnp.ndarray:
    """rot90 an (s, h, w, c) stack by a *traced* k in {0,1,2,3}.

    ``lax.switch`` over the four static rotations (jnp.rot90 needs a static
    k); all branches must agree on shape, hence the square-image requirement
    enforced in ``make_index_expander``.
    """
    return jax.lax.switch(
        k,
        [
            lambda x: x,
            lambda x: jnp.rot90(x, 1, axes=(1, 2)),
            lambda x: jnp.rot90(x, 2, axes=(1, 2)),
            lambda x: jnp.rot90(x, 3, axes=(1, 2)),
        ],
        imgs,
    )


def pad_store_rows(store: np.ndarray, num_shards: int) -> np.ndarray:
    """Zero-pad a flat store's row axis to a multiple of ``num_shards`` so
    it shards evenly; padding rows are unreachable (every gather index is
    < the logical row count) and masked anyway in the sharded gather."""
    rows = store.shape[0]
    rem = rows % num_shards
    if rem == 0:
        return store
    pad = num_shards - rem
    return np.concatenate(
        [store, np.zeros((pad,) + store.shape[1:], store.dtype)], axis=0
    )


def make_sharded_gather(
    cfg: MAMLConfig, store_mesh, store_axis: str
) -> Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray]:
    """(row-sharded store, gather) -> decoded float pixels, for resident
    stores sharded over a mesh axis (``store_sharding='hosts'``).

    Each shard gathers the requested rows it OWNS (out-of-shard indices
    clipped and masked to zero after decode) and a ``psum`` over the store
    axis assembles the full decoded batch. Exactly one shard contributes a
    non-zero value per row, so the sum is bit-exact with the replicated
    ``decode(store[gather])`` — float addition with zero is exact. The
    collective moves the decoded *batch* (float32), never the store and
    never uint8 pixels, so the PR 8 SPMD invariants (zero store-sized
    collectives, zero uint8 collectives) hold by construction; the output
    is then constrained back to the batch sharding so every downstream op
    — and therefore the gradient all-reduce order — is identical to the
    replicated-store program.
    """
    from jax.experimental.shard_map import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.mesh import TASK_AXIS

    decode = make_decoder(cfg)
    task_axes = tuple(a for a in store_mesh.axis_names if a != store_axis)
    # the canonical batch sharding on this mesh: tasks over every axis,
    # store axis major (parallel.distributed.global_batch_sharding)
    batch_spec = P(tuple([store_axis, *task_axes]))

    def local_gather(store_shard, gather):
        # store_shard: this shard's (rows/n, h, w, c) uint8 block
        shard_rows = store_shard.shape[0]
        lo = jax.lax.axis_index(store_axis) * shard_rows
        local = gather - lo
        ok = (local >= 0) & (local < shard_rows)
        imgs = store_shard[jnp.clip(local, 0, shard_rows - 1)]
        x = decode(imgs)
        # mask AFTER decode: decode(0) != 0 under stat-normalization
        x = jnp.where(ok[..., None, None, None], x, jnp.zeros((), x.dtype))
        return jax.lax.psum(x, store_axis)

    task_spec = P(TASK_AXIS if TASK_AXIS in task_axes else None)
    sharded = shard_map(
        local_gather,
        mesh=store_mesh,
        in_specs=(P(store_axis), task_spec),
        out_specs=task_spec,
    )

    def gather_decode(store, gather):
        x = sharded(store, gather)
        # replicated-over-store-axis -> batch sharding: a local slice (zero
        # communication), restoring the exact compute layout of the
        # replicated-store program
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(store_mesh, batch_spec)
        )

    return gather_decode


def make_serve_expander(
    cfg: MAMLConfig, shots: int
) -> Callable[[jnp.ndarray, jnp.ndarray], Tuple[
    jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray
]]:
    """(store, gather) -> (x_s, y_s, x_t, y_t) for the serving index
    ingest (``serving_ingest='index'``).

    The serving twin of ``make_index_expander``: ``store`` is a resident
    (N, h, w, c) uint8 image store (a registered ``FlatStore``'s data,
    uploaded once), ``gather`` the (tenants, n_way, shots + targets)
    int32 flat store rows of each tenant's support-then-query draw.
    Labels never cross H2D: sample (i, j) of any tenant carries label i
    by construction (slot iota), the training index-path convention — an
    index request's support/query rows are grouped by class slot.
    No rotation branch: serving never augments (the ``augment_stack``
    gate is train-time only), so the decode is the pure LUT lookup and
    stays bit-exact with the host pipeline for every dataset family.
    ``shots`` is static — each shots bucket is its own compiled program,
    exactly like the pixel-ingest serve programs.
    """
    decode = make_decoder(cfg)

    def expand(store, gather):
        x = decode(store[gather])  # (tenants, n, shots+t, h, w, c)
        y = jax.lax.broadcasted_iota(jnp.int32, gather.shape, 1)
        return x[:, :, :shots], y[:, :, :shots], x[:, :, shots:], y[:, :, shots:]

    return expand


def make_index_expander(
    cfg: MAMLConfig, augment: bool, store_mesh=None,
    store_axis: Optional[str] = None,
) -> Callable[..., Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]]:
    """(store, gather, rot_k) -> (x_s, y_s, x_t, y_t), all on device.

    ``store`` is the resident (N, h, w, c) uint8 image store; ``gather`` the
    (tasks, n_way, spc+nts) int32 flat indices and ``rot_k`` the
    (tasks, n_way) int32 rotation draws from
    ``episodes.sample_episode_indices``. Labels never cross H2D at all:
    sample (i, j) of any task has label i by construction (an iota).

    ``augment`` is static (per-set: train-time Omniglot only, matching the
    ``augment_stack`` gate) so the no-rotation programs pay nothing for the
    switch machinery.

    ``store_mesh``/``store_axis`` select the sharded-store gather
    (``make_sharded_gather``) for stores whose row axis is sharded over
    ``store_axis`` of that mesh instead of replicated; None keeps the
    plain resident gather.
    """
    decode = make_decoder(cfg)
    gather_decode = None
    if store_mesh is not None:
        from ..parallel.distributed import DATA_AXIS

        gather_decode = make_sharded_gather(
            cfg, store_mesh, store_axis or DATA_AXIS
        )
    rotate = augment and "omniglot" in cfg.dataset_name
    if rotate and cfg.image_height != cfg.image_width:
        raise ValueError(
            "on-device rot90 augmentation requires square images "
            f"(got {cfg.image_height}x{cfg.image_width}): lax.switch needs "
            "shape-stable rotation branches"
        )
    spc = cfg.num_samples_per_class

    def expand(store, gather, rot_k):
        if gather_decode is not None:
            x = gather_decode(store, gather)
        else:
            imgs = store[gather]  # (tasks, n, spc+nts, h, w, c) uint8
            x = decode(imgs)
        if rotate:
            # per-(task, class) rotation of the (spc+nts, h, w, c) stack —
            # the vectorized form of augment_stack's np.rot90(axes=(1, 2))
            x = jax.vmap(jax.vmap(_rot_stack))(x, rot_k)
        y = jax.lax.broadcasted_iota(jnp.int32, gather.shape, 1)
        return x[:, :, :spc], y[:, :, :spc], x[:, :, spc:], y[:, :, spc:]

    return expand
