"""Pure-functional NN ops, NHWC/TPU-native.

These are the JAX/XLA equivalents of the torch functional calls the reference
makes (``F.conv2d`` meta_neural_network_architectures.py:89, ``F.linear`` :141,
``F.batch_norm`` :246, ``F.layer_norm`` :314, ``F.max_pool2d``/``F.avg_pool2d``
:605/:609, ``F.leaky_relu`` :383, ``F.cross_entropy``
few_shot_learning_system.py:284) — but re-designed for the MXU:

* NHWC activations + HWIO kernels (the layout XLA tiles best on TPU);
* optional bfloat16 compute with float32 parameter master copies;
* batch-norm always normalizes with *batch* statistics, faithfully mirroring
  the reference's ``training=True`` call (meta_...py:246-247) — running stats
  are tracked as explicit state, never used for normalization.
"""

from __future__ import annotations

from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name

# Dimension numbers for NHWC activations with HWIO kernels.
CONV_DIMS = ("NHWC", "HWIO", "NHWC")


# MXU tiling: the lane (minor-most) dimension of every on-chip tile is 128;
# the sublane tile depends on dtype (f32 (8, 128), bf16 (16, 128)).
MXU_LANES = 128
_SUBLANE_TILE = {jnp.dtype(jnp.bfloat16): 16}


def pad_target(c: int, mode: Union[str, int], dtype) -> int:
    """The compute-time channel count for ``c`` logical channels.

    ``mode`` is a *resolved* ``pad_channels`` value ('off' / 'tile' / int):

    * ``'off'``  — no padding, the logical count;
    * ``int N``  — round up to the next multiple of N;
    * ``'tile'`` (what ``pad_channels='auto'`` resolves to on accelerator
      backends) — round up to the next power of two, floored at the dtype's
      sublane tile (8 for f32, 16 for bf16) and snapped to multiples of the
      128-lane width beyond it — e.g. the flagship's 48 filters become 64,
      a 100-channel layer 128, 200 becomes 256.  These are the shapes the
      MXU tiles without relayout padding on every GEMM operand.

    Padded values are zeros, which add exact zeros to every contraction
    partial sum — with the caveat that enlarging the contraction dim can
    shift the backend's GEMM blocking thresholds and reassociate the float
    accumulation.  The 'tile' rule's modest pads stay inside one block at
    the model's sizes (the bit-exactness tests pin this); very large
    explicit multiples on tiny layers may reassociate at ~1e-6 (see
    tests/test_pad_channels.py).
    """
    if mode == "off":
        return c
    if isinstance(mode, int):
        if mode <= 0:
            return c
        return -(-c // mode) * mode
    if mode != "tile":
        raise ValueError(
            f"pad_channels mode must be 'off', 'tile' or an int, got {mode!r}"
        )
    floor = _SUBLANE_TILE.get(jnp.dtype(dtype), 8)
    if c <= floor:
        return floor
    if c >= MXU_LANES:
        return -(-c // MXU_LANES) * MXU_LANES
    return 1 << (c - 1).bit_length()


def _pad_axis(a: jnp.ndarray, axis: int, target: int) -> jnp.ndarray:
    """Zero-pad one axis of ``a`` up to ``target`` (no-op when equal)."""
    grow = target - a.shape[axis]
    if grow == 0:
        return a
    widths = [(0, 0)] * a.ndim
    widths[axis] = (0, grow)
    return jnp.pad(a, widths)


def _im2col(
    x: jnp.ndarray, kh: int, kw: int, stride: int, padding: int
) -> jnp.ndarray:
    """Extract conv patches: (N,H,W,C) -> (N,Ho,Wo,kh*kw*C).

    Built from pad + strided-slice + concat only, so every AD order stays in
    cheap data-movement ops and the conv math itself is a single dot_general.
    """
    if padding:
        x = jnp.pad(x, ((0, 0), (padding, padding), (padding, padding), (0, 0)))
    n, hp, wp, c = x.shape
    ho = (hp - kh) // stride + 1
    wo = (wp - kw) // stride + 1
    cols = []
    for i in range(kh):
        for j in range(kw):
            cols.append(
                lax.slice(
                    x,
                    (0, i, j, 0),
                    (n, i + (ho - 1) * stride + 1, j + (wo - 1) * stride + 1, c),
                    (1, stride, stride, 1),
                )
            )
    return jnp.concatenate(cols, axis=-1)


def conv_patches(
    x: jnp.ndarray,
    kh: int,
    kw: int,
    stride: int,
    padding: int,
    pad_channels: Union[str, int] = "off",
) -> jnp.ndarray:
    """The patch tensor a patch-based conv lowering would extract from ``x``
    — channel padding applied first, then ``_im2col`` — exposed so callers
    can HOIST it out of a loop whose every iteration convolves the same
    input (the MAML inner scan: support/target images are loop constants,
    but layer 1 re-extracts their patches every inner step, forward AND
    remat backward).

    ``_im2col`` is pure data movement (pad + strided-slice + concat — no
    arithmetic), so the hoisted tensor is the *identical value* the conv
    would compute inline: threading it back through ``conv2d(...,
    patches=...)`` / ``conv_bn_act(..., patches=...)`` is bit-exact by
    construction at every derivative order.  Only meaningful for the
    ``'im2col'``/``'gemm'`` lowerings (``'lax'`` consumes raw NHWC and
    ignores no patches — callers gate on the resolved impl).
    """
    cin = x.shape[-1]
    cin_p = pad_target(cin, pad_channels, x.dtype)
    if cin_p != cin:
        x = _pad_axis(x, -1, cin_p)
    return _im2col(x, kh, kw, stride, padding)


def conv2d(
    x: jnp.ndarray,
    w: jnp.ndarray,
    b: Optional[jnp.ndarray],
    stride: int,
    padding: int,
    impl: str = "lax",
    pad_channels: Union[str, int] = "off",
    patches: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """2-D convolution, NHWC x HWIO -> NHWC (ref: F.conv2d, meta_...py:89-97).

    ``padding`` is symmetric integer padding like torch's ``padding=`` int.

    ``impl`` selects the lowering:

    * ``"lax"`` — ``lax.conv_general_dilated``, the native conv XLA tiles
      onto the TPU MXU; the right choice on accelerator backends when the
      kernel is shared across the batch.
    * ``"im2col"`` — patches + ``dot_general``. Mathematically identical
      (same contraction, different op), and the backward of a dot_general is
      two more dot_generals, so EVERY derivative order lowers to GEMMs.
      This sidesteps XLA:CPU's pathological kernel-gradient convolution
      (profiled at ~40x a same-FLOPs GEMM: the f32[3,3,64,64] wgrad conv
      with a 14x14 window costs ~89ms where the equivalent GEMM costs ~2ms)
      — the dominant cost of CPU MAML training. Pure lax ops, so it remains
      valid (just not preferred) on TPU.
    * ``"gemm"`` — the task-batched twin of im2col: patches are flattened to
      ``(N·Ho·Wo, kh·kw·cin)`` and contracted with the ``(kh·kw·cin, cout)``
      kernel in ONE explicit ``dot_general``.  Under ``vmap`` over tasks
      with per-task adapted weights (the MAML inner loop after step 1) the
      batching rule turns this into a single batched GEMM
      ``(task, N·Ho·Wo, K) x (task, K, cout)`` per layer — the contraction
      the MXU runs at peak — where the native conv lowers to a
      ``feature_group_count=tasks`` grouped conv that XLA handles an order
      of magnitude below peak.  Every derivative order of a dot_general is
      again dot_generals, so the whole second-order meta-gradient stays in
      batched GEMMs.

    ``pad_channels`` (a *resolved* config value: 'off'/'tile'/int — see
    ``pad_target``) zero-pads cin and cout up to MXU-friendly counts for the
    compute only: padded input channels contribute exact zeros to the
    contraction and padded output channels are sliced off before the bias
    (and therefore before any norm layer), so results are bit-exact with the
    unpadded op while every GEMM dimension is lane/sublane aligned.

    ``patches`` (optional) short-circuits patch extraction with a
    pre-computed ``conv_patches(x, ...)`` tensor — the invariant-hoisting
    hook (bit-exact: the hoisted tensor IS the value the inline extraction
    would produce). Ignored by the ``'lax'`` lowering.
    """
    out = _conv2d_raw(x, w, b, stride, padding, impl, pad_channels, patches)
    # named for remat_policy='save_conv' (save_only_these_names); a no-op
    # unless a checkpoint policy references the name
    return checkpoint_name(out, "conv_out")


def _conv2d_raw(
    x: jnp.ndarray,
    w: jnp.ndarray,
    b: Optional[jnp.ndarray],
    stride: int,
    padding: int,
    impl: str,
    pad_channels: Union[str, int],
    patches: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """``conv2d`` without the remat checkpoint name — the building block
    ``conv_bn_act`` composes so the save point can sit AFTER the fused
    epilogue instead of between conv and norm."""
    kh, kw, cin, cout = w.shape
    cin_p = pad_target(cin, pad_channels, x.dtype)
    cout_p = pad_target(cout, pad_channels, x.dtype)
    if cin_p != cin:
        if patches is None:
            x = _pad_axis(x, -1, cin_p)
        w = _pad_axis(w, 2, cin_p)
    if cout_p != cout:
        w = _pad_axis(w, 3, cout_p)
    if impl == "im2col":
        if patches is None:
            patches = _im2col(x, kh, kw, stride, padding)
        out = patches @ w.astype(x.dtype).reshape(kh * kw * cin_p, cout_p)
    elif impl == "gemm":
        if patches is None:
            patches = _im2col(x, kh, kw, stride, padding)
        n, ho, wo, k = patches.shape
        out = lax.dot_general(
            patches.reshape(n * ho * wo, k),
            w.astype(x.dtype).reshape(k, cout_p),
            (((1,), (0,)), ((), ())),
        ).reshape(n, ho, wo, cout_p)
    else:
        out = lax.conv_general_dilated(
            x,
            w.astype(x.dtype),
            window_strides=(stride, stride),
            padding=[(padding, padding), (padding, padding)],
            dimension_numbers=CONV_DIMS,
        )
    if cout_p != cout:
        out = out[..., :cout]
    if b is not None:
        out = out + b.astype(out.dtype)
    return out


def conv_bn_act(
    x: jnp.ndarray,
    w: jnp.ndarray,
    b: Optional[jnp.ndarray],
    gamma: jnp.ndarray,
    beta: jnp.ndarray,
    running_mean: Optional[jnp.ndarray],
    running_var: Optional[jnp.ndarray],
    stride: int,
    padding: int,
    impl: str = "lax",
    pad_channels: Union[str, int] = "off",
    negative_slope: float = 0.01,
    bn_stats_impl: str = "twopass",
    patches: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, Optional[jnp.ndarray], Optional[jnp.ndarray]]:
    """The reference's used block (``MetaConvNormLayerReLU``) as ONE op:
    conv -> bias -> batch-norm (batch statistics + running-stat update) ->
    leaky-relu, returning ``(activation, new_running_mean, new_running_var)``.

    Exactly the composition ``conv2d`` + ``batch_norm`` + ``leaky_relu``
    compute — same primitives in the same order, so it is bit-identical
    to the unfused calls (the conv-impl/pad-channels equivalence tests
    gate it). What moves is the remat save point: ``conv2d`` names its
    output ``conv_out`` BETWEEN conv and norm, so under
    ``remat_policy='save_conv'`` the backward re-runs the whole per-layer
    elementwise tail (bias, BN stats + normalize + affine, leaky-relu) —
    the top non-GEMM contributors in the PR 8 roofline decomposition.
    Here the name marks the POST-activation tensor: the GEMM and its
    entire elementwise epilogue become one saved fusion region, and the
    backward recomputes none of it. (``remat_policy='full'`` and the
    no-remat path are indifferent to the name — checkpoint_name is a
    no-op unless a policy references it.)

    ``bn_stats_impl`` selects the statistics pass of the riding batch-norm
    (``batch_norm``'s ``stats_impl``): ``'twopass'`` is the bit-pinned
    separate mean/variance reduction, ``'fused'`` one concatenated
    sum/sum-of-squares reduction (tolerance-bounded — see ``batch_norm``).
    ``patches`` is the invariant-hoisting hook (see ``conv2d``).
    """
    out = _conv2d_raw(x, w, b, stride, padding, impl, pad_channels, patches)
    out, new_mean, new_var = batch_norm(
        out, gamma, beta, running_mean, running_var, stats_impl=bn_stats_impl
    )
    out = jax.nn.leaky_relu(out, negative_slope=negative_slope)
    return checkpoint_name(out, "conv_out"), new_mean, new_var


def linear(
    x: jnp.ndarray,
    w: jnp.ndarray,
    b: Optional[jnp.ndarray],
    pad_channels: Union[str, int] = "off",
) -> jnp.ndarray:
    """Dense layer x @ w + b with w of shape (in, out) (ref: F.linear :141).

    ``pad_channels`` compute-pads both GEMM dimensions like ``conv2d``:
    zero rows contribute nothing to the contraction, padded output columns
    are sliced off before the bias — bit-exact with the unpadded op.
    """
    fin, fout = w.shape
    fin_p = pad_target(fin, pad_channels, x.dtype)
    fout_p = pad_target(fout, pad_channels, x.dtype)
    if fin_p != fin:
        x = _pad_axis(x, -1, fin_p)
        w = _pad_axis(w, 0, fin_p)
    if fout_p != fout:
        w = _pad_axis(w, 1, fout_p)
    out = x @ w.astype(x.dtype)
    if fout_p != fout:
        out = out[..., :fout]
    if b is not None:
        out = out + b.astype(out.dtype)
    return out


def max_pool2d(
    x: jnp.ndarray, window: int = 2, stride: int = 2, impl: str = "reshape"
) -> jnp.ndarray:
    """2x2 max pool, NHWC (ref: F.max_pool2d, meta_...py:605,652).

    Two numerically identical lowerings (VALID: trailing odd rows/cols
    dropped), selected per backend by ``config.resolved_pool_impl``:

    * ``reshape`` (window == stride only): reshape + max over the tile
      axes — its gradient is an elementwise mask instead of XLA's
      select-and-scatter, which profiles ~10x slower on CPU;
    * ``reduce_window``: XLA's native window reduce — on TPU the reshape
      form's (.., ho, 2, wo, 2, c) intermediate is tile-padded ~3.4x in
      HBM (measured: it OOMs the no-remat 84x84 path), while
      reduce_window fuses with no blown-up temp.
    """
    if impl == "reshape" and window == stride:
        n, h, w, c = x.shape
        ho, wo = h // window, w // window
        x = x[:, : ho * window, : wo * window, :]
        x = x.reshape(n, ho, window, wo, window, c)
        return jnp.max(jnp.max(x, axis=4), axis=2)
    return lax.reduce_window(
        x,
        -jnp.inf,
        lax.max,
        window_dimensions=(1, window, window, 1),
        window_strides=(1, stride, stride, 1),
        padding="VALID",
    )


def global_avg_pool2d(x: jnp.ndarray) -> jnp.ndarray:
    """Global average pool over H,W (ref: F.avg_pool2d(out, out.shape[2]),
    meta_...py:609,655 — window == feature map, i.e. global)."""
    return jnp.mean(x, axis=(1, 2), keepdims=True)


def leaky_relu(x: jnp.ndarray, negative_slope: float = 0.01) -> jnp.ndarray:
    """Leaky ReLU with torch's default slope (ref: F.leaky_relu :383,426)."""
    return jax.nn.leaky_relu(x, negative_slope=negative_slope)


def batch_norm(
    x: jnp.ndarray,
    gamma: jnp.ndarray,
    beta: jnp.ndarray,
    running_mean: Optional[jnp.ndarray],
    running_var: Optional[jnp.ndarray],
    momentum: float = 0.1,
    eps: float = 1e-5,
    stats_impl: str = "twopass",
) -> Tuple[jnp.ndarray, Optional[jnp.ndarray], Optional[jnp.ndarray]]:
    """Batch norm over (N, H, W) per channel, NHWC.

    Faithful to the reference's ``F.batch_norm(..., training=True)``
    (meta_...py:246-247): normalization ALWAYS uses the current batch's
    statistics; the running stats are updated (torch momentum convention:
    ``new = (1 - m) * old + m * batch``, with the *unbiased* batch variance
    feeding the running var) but never normalize anything.

    ``stats_impl`` selects how the batch statistics are reduced:

    * ``'twopass'`` — ``jnp.mean`` + ``jnp.var``: the variance pass
      re-reads ``x`` to reduce squared deviations from the already-known
      mean. Numerically the historical (bit-pinned) form.
    * ``'fused'`` — sum and sum-of-squares reduced in ONE pass over the
      conv output (f32 accumulation; XLA multi-output-fuses the two
      same-shape reductions into a single read of ``x``), then
      ``var = E[x^2] - E[x]^2`` (clamped at 0 against cancellation).
      Where twopass reads ``x`` again to reduce squared deviations from
      the already-known mean, the fused pass never revisits it — per
      inner-loop step, forward AND remat backward — and the statistics
      ride the ``conv_bn_act`` epilogue fusion (the train step's total
      ``reduce`` census shrinks strictly, pinned by CONTRACTS.json and
      the CI census-shrink gate). Tolerance-bounded vs twopass
      (reassociation + the E[x^2]-E[x]^2 form; same proof standard as
      the accum chained tails — the ULP bound is pinned in
      tests/test_compute_diet.py for f32 and bf16 at both derivative
      orders).

    Returns (y, new_running_mean, new_running_var); the stats are None-in
    None-out so batch-norm-without-tracking is the same code path.
    """
    reduce_axes = tuple(range(x.ndim - 1))  # all but channel
    if stats_impl == "fused":
        x32 = x.astype(jnp.float32)
        n = 1
        for ax in reduce_axes:
            n *= x.shape[ax]
        s1 = jnp.sum(x32, axis=reduce_axes)
        s2 = jnp.sum(x32 * x32, axis=reduce_axes)
        mean32 = s1 / n
        var32 = jnp.maximum(s2 / n - mean32 * mean32, 0.0)
        mean = mean32.astype(x.dtype)
        var = var32.astype(x.dtype)
    elif stats_impl == "twopass":
        mean = jnp.mean(x, axis=reduce_axes)
        var = jnp.var(x, axis=reduce_axes)
    else:
        raise ValueError(
            f"stats_impl must be 'twopass' or 'fused', got {stats_impl!r}"
        )
    inv = lax.rsqrt(var + eps).astype(x.dtype)
    y = (x - mean.astype(x.dtype)) * inv
    y = y * gamma.astype(x.dtype) + beta.astype(x.dtype)

    new_mean = new_var = None
    if running_mean is not None:
        n = 1
        for ax in reduce_axes:
            n *= x.shape[ax]
        unbiased = var * (n / max(n - 1, 1))
        new_mean = (1.0 - momentum) * running_mean + momentum * mean.astype(
            running_mean.dtype
        )
        new_var = (1.0 - momentum) * running_var + momentum * unbiased.astype(
            running_var.dtype
        )
    return y, new_mean, new_var


def layer_norm(
    x: jnp.ndarray,
    gamma: jnp.ndarray,
    beta: jnp.ndarray,
    eps: float = 1e-5,
) -> jnp.ndarray:
    """Layer norm over the trailing feature dims (ref: F.layer_norm :314-315).

    The reference normalizes over the full per-sample feature shape (c, h, w)
    with affine params of that same shape; here NHWC (h, w, c). The gamma the
    reference uses is frozen at 1 (meta_...py:279) — enforced by the caller's
    trainability partition, not here.
    """
    reduce_axes = tuple(range(1, x.ndim))
    mean = jnp.mean(x, axis=reduce_axes, keepdims=True)
    var = jnp.var(x, axis=reduce_axes, keepdims=True)
    y = (x - mean) * lax.rsqrt(var + eps)
    return y * gamma.astype(x.dtype) + beta.astype(x.dtype)


def cross_entropy(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Mean softmax cross-entropy with integer labels (ref: F.cross_entropy,
    few_shot_learning_system.py:284). Computed in float32 for stability."""
    logits = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[:, None].astype(jnp.int32), axis=-1)
    return jnp.mean(nll)


def accuracy(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Per-sample correctness, float (ref: few_shot_learning_system.py:247-249)."""
    return (jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32)
