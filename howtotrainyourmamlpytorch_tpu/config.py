"""Typed configuration for the TPU-native MAML/MAML++ framework.

Replaces the reference's argparse + JSON-override flag system
(``/root/reference/utils/parser_utils.py:4-106``) with a single typed dataclass.
Key properties preserved:

* every key that appears in the reference's argparse defaults *or* only in its
  JSON experiment configs (``/root/reference/experiment_config/*.json``) is a
  field here, under the same name, so the reference's config files load as-is;
* string booleans ("true"/"false") are coerced (parser_utils.py:63-66);
* ``dataset_path`` is prefixed with ``$DATASET_DIR`` when that env var is set
  (parser_utils.py:67-69);
* JSON keys containing ``continue_from`` or ``gpu_to_use`` are ignored on load
  (parser_utils.py:103), i.e. resume behaviour is controlled by the CLI only;
* the reference's *dead* keys (parsed/stored but never read by the compute
  path — see SURVEY.md §5) are accepted and retained for config-file
  compatibility but do not influence the system, with one documented
  exception: ``init_inner_loop_learning_rate`` can optionally be honoured via
  ``use_config_init_inner_lr`` (the reference reads ``task_learning_rate``
  instead — few_shot_learning_system.py:46-49 — which is a known quirk).
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple, Union


def _coerce_bool(value: Any) -> Any:
    """Reference-compatible string->bool coercion (parser_utils.py:63-66)."""
    if isinstance(value, str):
        if value.lower() == "true":
            return True
        if value.lower() == "false":
            return False
    return value


@dataclass
class MAMLConfig:
    """The union of the reference's argparse defaults and JSON-only keys."""

    # --- experiment identity / bookkeeping -------------------------------
    experiment_name: str = "maml_experiment"
    seed: int = 104
    train_seed: int = 0
    val_seed: int = 0
    continue_from_epoch: str = "latest"  # 'latest' | 'from_scratch' | int
    max_models_to_save: int = 5
    total_epochs_before_pause: int = 100
    evaluate_on_test_set_only: bool = False

    # --- data ------------------------------------------------------------
    dataset_name: str = "omniglot_dataset"
    dataset_path: str = "datasets/omniglot_dataset"
    batch_size: int = 32
    image_height: int = 28
    image_width: int = 28
    image_channels: int = 1
    num_classes_per_set: int = 20
    num_samples_per_class: int = 1
    num_target_samples: int = 15
    num_evaluation_tasks: int = 600
    sets_are_pre_split: bool = False
    load_into_memory: bool = False
    train_val_test_split: List[float] = field(
        default_factory=lambda: [0.73982737361, 0.26, 0.13008631319]
    )
    indexes_of_folders_indicating_class: List[int] = field(
        default_factory=lambda: [-2, -3]
    )
    reverse_channels: bool = False
    labels_as_int: bool = False
    # CIFAR-family normalization stats (ref data.py:86-90 reads
    # args.classification_mean/std); scalar or per-channel list
    classification_mean: Union[float, List[float]] = 0.5
    classification_std: Union[float, List[float]] = 0.5
    reset_stored_filepaths: bool = False
    num_dataprovider_workers: int = 4
    samples_per_iter: int = 1

    # --- model -----------------------------------------------------------
    num_stages: int = 4
    cnn_num_filters: int = 64
    conv_padding: bool = True
    max_pooling: bool = False
    norm_layer: str = "batch_norm"  # 'batch_norm' | 'layer_norm'
    # block op order: 'conv_norm_relu' is the reference's used block
    # (MetaConvNormLayerReLU, meta_...py:323-436); 'norm_conv_relu' is its
    # alternate (MetaNormLayerConvReLU, :438-542 — norm on block INPUT)
    block_order: str = "conv_norm_relu"
    per_step_bn_statistics: bool = False
    learnable_bn_gamma: bool = True
    learnable_bn_beta: bool = True
    enable_inner_loop_optimizable_bn_params: bool = False

    # --- meta-optimization -----------------------------------------------
    total_epochs: int = 100
    total_iter_per_epoch: int = 500
    meta_learning_rate: float = 0.001
    min_learning_rate: float = 0.00001
    task_learning_rate: float = 0.1
    init_inner_loop_learning_rate: float = 0.01  # honoured iff use_config_init_inner_lr
    number_of_training_steps_per_iter: int = 1
    number_of_evaluation_steps_per_iter: int = 1
    second_order: bool = False
    first_order_to_second_order_epoch: int = -1
    use_multi_step_loss_optimization: bool = False
    multi_step_loss_num_epochs: int = 15
    learnable_per_layer_per_step_inner_loop_learning_rate: bool = False

    # --- TPU-native knobs (new; no reference counterpart) ----------------
    inner_loop_optimizer: str = "lslr"  # 'lslr' | 'sgd' (plain fixed-LR GD)
    compute_dtype: str = "float32"  # 'float32' | 'bfloat16' compute precision
    # MXU multiply precision for matmuls/convs ('jax_default_matmul_precision').
    # TPU multiplies fp32 operands in bf16 passes under 'default' — measured
    # to stall second-order MAML++ learning (20-way val 14% vs 65% at 100
    # iters) because meta-gradients through the unrolled inner loop lose too
    # many mantissa bits. 'auto' => 'highest' (true fp32 multiplies) when
    # compute_dtype is float32, 'default' for bfloat16 (already bf16).
    matmul_precision: str = "auto"  # 'auto' | 'default' | 'high' | 'highest'
    use_remat: bool = True  # jax.checkpoint the inner step (memory vs FLOPs)
    # remat policy when use_remat: 'full' rematerializes everything;
    # 'save_conv' saves the conv outputs (named checkpoints in
    # ops.functional.conv2d) and recomputes only the cheap elementwise tail —
    # less MXU recompute, more memory; tune per hardware with bench_sweep
    remat_policy: str = "full"
    num_devices: int = 0  # 0 => use all visible devices for the task mesh
    # task-axis execution: 'vmap' batches tasks into grouped convs (MXU-
    # friendly, the TPU default); 'map' runs tasks sequentially with ordinary
    # convs — 5-10x faster on CPU hosts where XLA's grouped-conv path is slow
    task_axis_mode: str = "vmap"
    # conv lowering: 'lax' = native conv (XLA tiles it onto the MXU — right
    # when the kernel is shared across the batch); 'im2col' = patches +
    # dot_general, whose every AD order is a GEMM — sidesteps XLA:CPU's
    # ~40x-slow kernel-gradient conv (see ops.functional.conv2d); 'gemm' =
    # the task-batched dot_general conv — under vmap with per-task adapted
    # weights (every inner step past the first) each layer lowers to ONE
    # batched (task, N*Ho*Wo, K) x (task, K, cout) GEMM instead of the
    # grouped conv XLA runs an order of magnitude below MXU peak; 'auto' =
    # im2col on CPU backends, gemm on accelerators when task_axis_mode
    # batches per-task weights ('vmap'), lax otherwise
    conv_impl: str = "auto"
    # compute-only channel padding to the MXU lane/sublane tile: 'auto'
    # (off on CPU; the 'tile' rule on accelerators), 'tile' (force the rule
    # on any backend: round channel counts to the next power of two,
    # floored at the dtype sublane tile and snapped to multiples of the
    # 128-lane width — the flagship's 48 filters compute as 64), 'off', or
    # an explicit integer multiple. Zero channels contribute nothing to the
    # contraction and outputs are sliced back to logical channels before
    # bias/norm, so results are bit-exact with the unpadded op under the
    # tile rule (tests/test_pad_channels.py) while every GEMM dimension
    # tiles cleanly
    pad_channels: Union[str, int] = "auto"
    # task-microbatched meta-gradient accumulation: the train step scans the
    # meta-batch in N microbatches of batch_size/N tasks INSIDE one compiled
    # dispatch, stacking per-task meta-grads and reducing them once in f32 —
    # the per-device activation peak of differentiating through the inner
    # loop shrinks ~N-fold while the effective meta-batch (and the update
    # math) is unchanged: the accumulated step reduces the same per-task
    # values in the same order as the monolithic step, so results are
    # bit-exact in f32 at equal total batch (tests/test_accum.py). 1 (the
    # default) keeps the single-pass program. Must divide batch_size. Tune
    # with `cli tune`: larger meta-batches at fixed HBM is how gemm+pad
    # configs reach MXU saturation (ROADMAP item 2).
    meta_accum_steps: int = 1
    # pool lowering: 'reshape' = tile-axes reshape + max, whose gradient is
    # an elementwise mask (~10x faster than select-and-scatter on CPU);
    # 'reduce_window' = XLA's native window reduce — on TPU the reshape
    # form's (.., 2, .., 2, ..) intermediate pads 3.4x in HBM tiles and
    # OOMs the no-remat path; 'auto' = the tuning table's measured winner
    # for this device kind, else reshape on CPU, reduce_window elsewhere.
    # Both are bit-exact VALID pools (trailing odd rows/cols sliced off
    # before the reshape, so odd feature maps are handled identically);
    # geometry that VANISHES under pooling (a stage's pool input smaller
    # than the 2x2 window) is rejected at config build, not at trace time
    pool_impl: str = "auto"
    # batch-norm statistics pass (ops.functional.batch_norm stats_impl):
    # 'twopass' = separate mean + variance reductions over the conv output
    # (the historical bit-pinned form); 'fused' = ONE concatenated
    # sum/sum-of-squares reduction with f32 accumulation riding the
    # conv_bn_act epilogue — halves the BN statistics passes per inner
    # step (forward AND remat backward) at a pinned ULP tolerance
    # (reassociation + E[x^2]-E[x]^2, tests/test_compute_diet.py);
    # 'auto' = the tuning table's winner, else fused on CPU (where the
    # scan-body reduction work dominates after the GEMM diet), twopass on
    # accelerators (keeps the pinned TPU lowering until a sweep measures
    # a win)
    bn_stats_impl: str = "auto"
    # invariant im2col hoisting: the support/target images are loop
    # constants of the inner scan, so layer 1's patch extraction (the
    # im2col over the largest spatial tensor) can be computed once per
    # task outside the scan and threaded in as an invariant — bit-exact
    # by construction (pure data movement; the hoisted tensor IS the
    # inline value) while eliminating num_steps x re-extraction in the
    # forward and the remat backward. 'auto' = on whenever it applies
    # (patch-based conv lowering + conv-first block), 'on' forces it
    # (rejected at config build when the lowering can never consume
    # patches), 'off' keeps the self-contained per-step extraction
    im2col_hoist: str = "auto"
    use_config_init_inner_lr: bool = False  # fix the task_learning_rate quirk
    # layout of incoming image batches: 'nchw' (the reference's torch layout,
    # data.py tensors are (..., c, h, w)), 'nhwc' (already TPU-native), or
    # 'auto' — match the trailing dims against im_shape, falling back to a
    # channels-position heuristic, and error when genuinely ambiguous
    input_layout: str = "auto"
    cache_dir: str = ""  # where dataset path-index JSON caches go ('' => experiment dir)
    use_mmap_cache: bool = False  # preprocessed uint8 memmap image cache (data/preprocess.py)
    prefetch_batches: int = 2  # host->device pipeline depth
    # where episode pixels are assembled (ops/device_pipeline.py):
    # 'host'         — the classic path: host threads gather/decode/augment
    #                  float32 NHWC arrays and upload them every dispatch
    #                  (~8.5 MB/task for Mini-ImageNet);
    # 'uint8_stream' — host gathers/rotates raw uint8; decode (float cast,
    #                  /255, stat-normalize) runs on device — 4x less H2D,
    #                  no residency requirement;
    # 'device'       — the split's whole uint8 image store lives in HBM
    #                  (uploaded once); host episode RNG emits only int32
    #                  gather/rot-k index tensors (a few KB/batch) and
    #                  gather+decode+rot90 run inside the jitted step.
    # Both non-host tiers require use_mmap_cache (the flat uint8 store) and
    # exclude CIFAR (its per-image RNG crop/flip can't be vectorized on
    # device); bit-exact with the host path by construction (tested).
    data_placement: str = "host"  # 'host' | 'uint8_stream' | 'device'
    # residency layout of the data_placement='device' uint8 stores on a
    # multi-host mesh: 'replicated' (default) uploads the full store to
    # every device; 'hosts' shards the store's row axis over the mesh's
    # host (DCN) axis — per-host HBM drops to store/n_hosts and the
    # on-device gather becomes a masked local gather + a hosts-axis psum
    # of the *decoded batch* (exactly one shard contributes per row, so
    # the sum is bit-exact with the replicated gather; the collective is
    # batch-sized float32, never store-sized and never uint8 — the PR 8
    # SPMD invariants hold by construction). Single-host meshes have no
    # host axis and degrade to 'replicated' with a log line.
    store_sharding: str = "replicated"  # 'replicated' | 'hosts'
    # outer-loop updates fused into ONE device dispatch (lax.scan over
    # stacked batches). >1 amortizes per-dispatch host round-trips — vital
    # over networked device transports (remote-TPU tunnel: ~0.5s/dispatch
    # vs ~30ms compute measured at paper width). Must divide cleanly into
    # the epoch (the builder flushes at epoch boundaries regardless);
    # single-host only (multi-host falls back to per-iter dispatch).
    steps_per_dispatch: int = 1
    # eval twin of steps_per_dispatch: evaluation passes fused into ONE
    # device dispatch (lax.scan over stacked eval batches). Amortizes the
    # per-dispatch round-trip over the fixed 600-task validation epoch and
    # the top-N test ensemble; metrics come back (k,)-stacked, preds
    # (k, tasks, ...). Single-host only (multi-host falls back to per-iter
    # dispatch, same as steps_per_dispatch).
    eval_batches_per_dispatch: int = 1
    profile_trace_dir: str = ""  # jax profiler trace output ('' => disabled)
    profile_num_steps: int = 5  # train iterations captured in the trace
    # trace-window scheduling (telemetry ISSUE 3): capture train iterations
    # [profile_start_step, profile_start_step + profile_num_steps) of epoch
    # `profile_epoch` without code edits. profile_epoch=-1 keeps the legacy
    # behaviour (first steps of THIS run, whatever epoch resume landed on);
    # >= 0 targets that global epoch, 0-BASED like every other epoch-valued
    # knob here (first_order_to_second_order_epoch, the LR/MSL schedules).
    # NB the CSV/telemetry `epoch` labels are 1-based at write time, so to
    # trace the epoch recorded as epoch N pass profile_epoch = N - 1.
    # start_step defaults past iteration 0 so the compile step never
    # pollutes the trace.
    profile_epoch: int = -1
    profile_start_step: int = 1
    # --- observability (telemetry/) --------------------------------------
    # 'off'      — reference-style reporting only (CSV + tqdm), zero overhead
    #              and bit-identical metrics;
    # 'scalars'  — schema-versioned JSONL event log (logs/telemetry.jsonl):
    #              epoch scalars, dispatch timings, loader stream stats,
    #              checkpoint events, device memory, watchdog diagnostics —
    #              host-side only, the device programs are untouched;
    # 'dynamics' — additionally collect MAML++'s training dynamics ON DEVICE
    #              inside the fused train dispatches (per-inner-step support/
    #              target losses, per-layer inner-grad norms, the learned
    #              LSLR vectors, the MSL weight vector), stacked in the
    #              existing lax.scan so collection adds zero extra device
    #              syncs; flushed to the JSONL log at epoch-summary time.
    telemetry_level: str = "off"
    telemetry_tensorboard: bool = False  # mirror epoch scalars to TensorBoard
    # causal tracing (telemetry/tracing.py): 'on' emits schema-v10 `span`
    # records — train dispatch / eval chunk / epoch summary / checkpoint
    # intervals, data-producer sample/stack/queue-put intervals, and (in a
    # serving process) the per-request queue/assemble/dispatch/sync
    # decomposition — into the telemetry JSONL for `cli trace` to render
    # as a Chrome/Perfetto timeline. Requires telemetry_level != 'off'
    # (spans ride the same sink). 'off' (default) allocates no span
    # objects and leaves every jitted program bit-identical (the
    # telemetry_level='off' proof standard); tracing is host-side only
    # and never adds a device sync either way.
    tracing_level: str = "off"  # 'off' | 'on'
    # heartbeat hang watchdog: when > 0, a daemon thread dumps a diagnostic
    # JSONL record + all-thread stack snapshot if the train/eval/checkpoint
    # loop reports no progress for this many seconds (multihost hang
    # debugging: the stack names the blocking collective). 0 disables.
    watchdog_timeout_s: float = 0.0
    # --- training-health monitor (telemetry/health.py, flight_recorder.py) -
    # 'monitor' adds a handful of on-device health reductions to the train
    # step — global meta-gradient L2 norm (pre-clip), non-finite grad-element
    # count, update and post-update parameter norms — riding back with the
    # metrics (zero extra device syncs; the traced training math is
    # untouched, so loss/accuracy/params stay bit-identical, tested). The
    # host-side AnomalyDetector evaluates them one dispatch behind the device
    # (the one-step-lag sync has already materialised the previous dispatch's
    # outputs, so detection adds no blocking), flags non-finite grads/loss
    # always and EMA-relative loss/grad-norm spikes per the knobs below, and
    # triggers the flight recorder. 'halt' additionally ESCALATES: once
    # health_patience anomalous iterations have been observed, the builder
    # writes a resumable emergency checkpoint (train_model_emergency) plus a
    # forensic incident dump and raises TrainingDivergedError instead of
    # training on garbage. 'off' (default) traces the exact pre-probe
    # program.
    health_level: str = "off"  # 'off' | 'monitor' | 'halt'
    # EMA-relative spike rules (0 disables a rule; non-finite rules are
    # always armed while probes are on): anomaly when
    # value > factor * EMA(value), after anomaly_warmup_steps observations
    anomaly_loss_spike_factor: float = 10.0
    anomaly_grad_spike_factor: float = 10.0
    # absolute pre-clip global grad-norm ceiling (0 disables): unlike the
    # EMA-relative spike rule this needs no warmup and catches a run whose
    # gradients are ALREADY huge at step 0
    health_grad_norm_limit: float = 0.0
    # at health_level='halt': anomalous iterations tolerated before the
    # builder halts the run (>=1; anomalies during warmup count too — the
    # non-finite rules are always armed)
    health_patience: int = 1
    # absolute ||update|| / ||params|| ceiling (0 disables): catches LR/LSLR
    # blowups that move parameters by a large fraction of their norm in one
    # outer step
    anomaly_update_ratio_max: float = 0.0
    anomaly_ema_beta: float = 0.98  # EMA decay for the spike baselines
    anomaly_warmup_steps: int = 20  # observations before spike rules arm
    # per-reason re-report suppression (steps): a run wedged at NaN emits
    # one anomaly record per reason per window, not one per step
    anomaly_cooldown_steps: int = 200
    # flight recorder: ring buffer of the last N per-step health entries +
    # builder events (host-side, a few floats per step); anomalies and
    # watchdog stalls dump it with a full state checkpoint to
    # logs/incidents/. 0 disables the recorder (anomaly records still go to
    # the telemetry log).
    flight_recorder_steps: int = 256
    # per-run cap on anomaly-triggered incident dumps (each carries an
    # orbax state checkpoint — params + LSLR + BN + Adam moments)
    max_state_dumps: int = 3
    # --- resilience (resilience/) ----------------------------------------
    # deterministic fault injection into the named host-side I/O seams
    # (resilience/faults.py — e.g. "ckpt_save:oserror@call=1x2,
    # producer:raise@batch=10,signal:sigterm@iter=55"). '' (default)
    # installs nothing: every seam is a single attribute check and the
    # jitted device programs are bit-identical to a spec-free build
    # (tested). The MAML_FAULT_SPEC env var supplies the spec when the
    # field is empty (chaos CI drives subprocesses through it).
    fault_spec: str = ""
    # retry/backoff for the checkpoint + statistics I/O seams
    # (resilience/retry.py): max attempts per write, first backoff, and
    # the exponential factor. Backoff is deterministic (no jitter) so
    # kill/resume equivalence tests and log diffs see the same sequence.
    io_retry_attempts: int = 3
    io_retry_backoff_s: float = 0.5
    io_retry_backoff_factor: float = 2.0
    # graceful preemption: install SIGTERM/SIGINT handlers for the duration
    # of run_experiment; on a signal the builder finishes the in-flight
    # dispatch, drains pending async checkpoints, writes a resumable
    # train_model_emergency checkpoint (incl. the partial epoch's metric
    # history) and exits with resilience.PREEMPT_EXIT_CODE. false keeps
    # the process's default signal behaviour (die, lose up to an epoch).
    handle_preemption_signals: bool = True
    # coordinated drain (resilience/elastic.py, multi-process runs): when
    # ONE worker is signalled, the primary publishes a drain commit at
    # `its iter + drain_margin_iters`, and every process trains up to that
    # iteration before the COLLECTIVE emergency checkpoint — the margin
    # must cover host-loop skew (~1 dispatch) plus one boundary poll of
    # shared-filesystem propagation. Single-process runs drain at the next
    # boundary as before and never consult this.
    drain_margin_iters: int = 4
    # bound on the collective checkpoint path's cross-process barriers
    # (experiment/checkpoint.py): a gang member that dies mid-save turns
    # into CheckpointBarrierTimeoutError on the survivors after this many
    # seconds, naming the primary's expected swap path, instead of the
    # former unbounded spin-wait.
    ckpt_follower_timeout_s: float = 600.0

    # --- serving (serving/) -----------------------------------------------
    # tenant-count bucket ladder for the adapt-on-request serving engine
    # (serving/engine.py): every dispatch is padded up to the smallest
    # ladder entry >= its tenant count, so steady-state traffic cycles
    # through a FIXED set of compiled programs (one per bucket x shots
    # value) and never retraces — the engine runs a strict RetraceDetector
    # to enforce it. Must be strictly increasing positive ints; pad
    # tenants are masked out of the aggregate metrics (core/maml.py,
    # make_serve_step) and cannot perturb real tenants' outputs.
    serving_bucket_ladder: List[int] = field(
        default_factory=lambda: [1, 2, 4, 8]
    )
    # micro-batching front end (serving/batcher.py): a queued request is
    # dispatched when serving_max_tenants_per_dispatch requests of its
    # shots bucket are waiting OR the oldest has waited this long —
    # the latency/throughput knob of the serving path. 0 dispatches
    # immediately (bucket-of-one latency floor).
    serving_max_wait_ms: float = 5.0
    # cap on the tenants one serving dispatch carries; must not exceed
    # the ladder's top bucket (every full group must fit a bucket)
    serving_max_tenants_per_dispatch: int = 8
    # serving ingest tier (serving/engine.py) — what crosses H2D per
    # dispatch:
    # 'f32'   — host-assembled float32 NHWC pixels (the classic path);
    # 'uint8' — raw uint8 pixels, decoded on device through the
    #           device-pipeline LUT (bit-exact with the host decode by
    #           construction, ~4x less H2D per dispatch);
    # 'index' — int32 store-row indices only; the engine must be handed a
    #           registered uint8 FlatStore (resident in HBM, uploaded
    #           once) and per-dispatch H2D drops to the index tensors
    #           (<1KB). Labels never cross H2D (slot iota, the training
    #           index-path convention).
    serving_ingest: str = "f32"  # 'f32' | 'uint8' | 'index'
    # adapted-params cache (serving/engine.py): LRU capacity in tenants.
    # >0 stores each tenant's post-adaptation fast weights keyed by its
    # support-set fingerprint (content hash + shots + snapshot id);
    # repeat tenants skip the inner loop entirely and ride the cheap
    # predict-only program (forward GEMMs only), bit-exact with full
    # re-adaptation at the same tenant width. 0 (default) disables the
    # cache and keeps the engine's program family unchanged.
    serving_adapted_cache_size: int = 0
    # AOT export artifacts (serving/export.py): when set, the engine's
    # warmup loads serialized (bucket x shots) executables from this
    # directory (keyed by device-kind/dtype/config-fingerprint) instead
    # of compiling, falling back to compile-then-save on any mismatch;
    # `cli serve-export` writes the artifacts ahead of time. '' disables.
    serving_export_dir: str = ""
    # multi-replica scale-out (serving/replica.py): how many shared-
    # nothing serving replicas a ReplicaSet builds — the visible devices
    # are partitioned into this many DISJOINT slices, one full engine
    # (own program ladder, own adapted-params cache, own micro-batcher)
    # per slice. 1 (default) is the single-engine shape; on CPU/CI extra
    # replicas come from --xla_force_host_platform_device_count (the
    # serve-bench --replicas path forces it), so the pool is testable
    # without a TPU.
    serving_replicas: int = 1
    # cache-affinity router (serving/router.py): a request is routed to
    # its HOME replica (stable content hash of its adapted-cache key) so
    # LRU hit rates survive scale-out; when the home replica's micro-
    # batcher backlog reaches this depth, the request spills over to the
    # least-loaded healthy replica instead (a cold adapt there beats
    # queueing behind a saturated home). Must be >= 1.
    serving_router_spill_depth: int = 8
    # checkpoint-rollover refresh daemon (serving/refresh.py): how often
    # the daemon polls the experiment checkpoint dir for a new snapshot
    # to pre-warm into the standby slot and swap in. Must be > 0.
    serving_rollover_poll_s: float = 5.0
    # serving SLO (serving/metrics.py SLOTracker): the per-request
    # latency objective in ms. > 0 arms deadline accounting — serve-bench
    # stamps it as the default deadline_ms on every generated request
    # (per-request deadlines override it), and the /metrics endpoint,
    # the end-of-run `slo` telemetry record and `cli slo` all report
    # slack/miss against it. 0 (default) disables SLO machinery; traffic
    # without deadlines emits no deadline records either way.
    serving_slo_target_ms: float = 0.0
    # the availability objective: the fraction of deadline-carrying
    # requests that must meet their deadline. The error budget is the
    # 1 - availability remainder; burn rate = window miss rate over the
    # error budget (1.0 spends the budget exactly at the objective
    # rate). Must be in (0, 1).
    serving_slo_availability: float = 0.99
    # burn-rate windows in seconds (the multi-window alerting form:
    # short windows catch fast burns, long ones slow leaks). Must be
    # positive and strictly increasing.
    serving_slo_burn_windows_s: List[float] = field(
        default_factory=lambda: [60.0, 300.0, 3600.0]
    )
    # fleet gateway (serving/gateway.py): the per-host admission budget —
    # a request is shed (typed 'admission' rejection) when its home
    # host's queue-depth + in-flight estimate reaches this budget,
    # right-shifted by the request's priority tier (tier 0 keeps the
    # full budget, tier 1 half, tier 2 a quarter, ...). Must be >= 1.
    serving_gateway_queue_budget: int = 64
    # how many admission tiers the gateway accepts (priorities
    # 0..tiers-1, 0 highest; an out-of-range wire priority is clamped).
    # Must be >= 1.
    serving_gateway_priority_tiers: int = 3
    # gateway health-poll cadence in seconds: how often the membership
    # thread probes each host's /healthz and trips unreachable hosts out
    # of the consistent-hash ring. Must be > 0.
    serving_gateway_health_interval_s: float = 0.5

    # --- static analysis (analysis/) --------------------------------------
    # program-contract audits + runtime retrace detection:
    # 'off'    — (default) nothing installed; the jitted programs and the
    #            dispatch paths are bit-identical to a pre-analysis build
    #            (tested, same discipline as fault_spec/telemetry off);
    # 'warn'   — at program-build time the builder audits the canonical
    #            program family (donation honored, no host<->device
    #            transfer inside the step, dtype policy, op-census — the
    #            CONTRACTS.json regression compare arms only when the
    #            baseline was pinned for this jax version and config
    #            fingerprint, otherwise it is skipped with a logged note
    #            while the invariant census constraints still run) and
    #            logs violations; at
    #            run time every dispatch site's abstract signature is
    #            hashed and a mid-run retrace emits a telemetry `retrace`
    #            record (schema v4) plus a stderr warning;
    # 'strict' — the same checks, but contract violations fail the build
    #            (analysis.AuditError) and a retrace fails the run
    #            (analysis.auditor.RetraceError).
    analysis_level: str = "off"  # 'off' | 'warn' | 'strict'
    # static per-device HBM budget (GiB) for the SPMD audit
    # (analysis/spmd.py): when > 0, the build-time audit of multi-device
    # runs (and `cli audit --mesh`) verifies the compiled step's static
    # per-device peak (memory_analysis: args + outputs + temps - aliased)
    # fits the budget — an OOM config fails the audit on a laptop instead
    # of a pod job. 0 (default) disables the check. Set it to the chip's
    # usable HBM (e.g. 16 for TPU v5e) minus headroom.
    hbm_budget_gb: float = 0.0

    # persistent XLA compilation cache: resumed runs (and repeated runs of
    # the same config) skip the 20-40s TPU compile of the train/eval steps.
    # 'auto' (default) => <experiment_dir>/xla_cache, resolved by the
    # experiment builder once the experiment folder exists (standalone
    # system/bench use leaves it disabled); '' => disabled; any other
    # string => that directory
    compilation_cache_dir: str = "auto"

    # --- accepted-but-inert reference keys (SURVEY.md §5 "dead keys") ----
    dropout_rate_value: float = 0.0
    weight_decay: float = 0.0
    cnn_blocks_per_stage: int = 1
    cnn_num_blocks: int = 4
    learnable_batch_norm_momentum: bool = False
    minimum_per_task_contribution: float = 0.01
    evalute_on_test_set_only: bool = False  # reference's typo twin, kept inert
    meta_opt_bn: bool = False
    num_of_gpus: int = 1
    gpu_to_use: int = 0
    architecture_name: Optional[str] = None
    name_of_args_json_file: str = "None"
    reset_stored_paths: bool = False

    # ---------------------------------------------------------------------

    def __post_init__(self) -> None:
        for f in dataclasses.fields(self):
            setattr(self, f.name, _coerce_bool(getattr(self, f.name)))
        if self.inner_loop_optimizer not in ("lslr", "sgd"):
            raise ValueError(
                f"inner_loop_optimizer must be 'lslr' or 'sgd', got "
                f"{self.inner_loop_optimizer!r}"
            )
        if self.compute_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"compute_dtype must be 'float32' or 'bfloat16', got "
                f"{self.compute_dtype!r}"
            )
        if self.norm_layer not in ("batch_norm", "layer_norm"):
            raise ValueError(
                f"norm_layer must be 'batch_norm' or 'layer_norm', got "
                f"{self.norm_layer!r}"
            )
        if self.block_order not in ("conv_norm_relu", "norm_conv_relu"):
            raise ValueError(
                f"block_order must be 'conv_norm_relu' or 'norm_conv_relu', "
                f"got {self.block_order!r}"
            )
        if self.task_axis_mode not in ("vmap", "map"):
            raise ValueError(
                f"task_axis_mode must be 'vmap' or 'map', got "
                f"{self.task_axis_mode!r}"
            )
        if self.conv_impl not in ("auto", "lax", "im2col", "gemm"):
            raise ValueError(
                f"conv_impl must be 'auto', 'lax', 'im2col' or 'gemm', got "
                f"{self.conv_impl!r}"
            )
        # pad_channels: 'auto' | 'off' | 'tile' | positive int (JSON
        # configs may carry the multiple as a string — coerce digits)
        if isinstance(self.pad_channels, str) and self.pad_channels.isdigit():
            self.pad_channels = int(self.pad_channels)
        if isinstance(self.pad_channels, bool) or not (
            self.pad_channels in ("auto", "off", "tile")
            or (isinstance(self.pad_channels, int) and self.pad_channels > 0)
        ):
            raise ValueError(
                f"pad_channels must be 'auto', 'off', 'tile' or a positive "
                f"int, got {self.pad_channels!r}"
            )
        if not (
            isinstance(self.meta_accum_steps, int)
            and not isinstance(self.meta_accum_steps, bool)
            and self.meta_accum_steps >= 1
        ):
            raise ValueError(
                f"meta_accum_steps must be an int >= 1, got "
                f"{self.meta_accum_steps!r}"
            )
        if self.batch_size % self.meta_accum_steps != 0:
            raise ValueError(
                f"meta_accum_steps={self.meta_accum_steps} must divide "
                f"batch_size={self.batch_size}: the train step scans the "
                "task axis in equal microbatches"
            )
        if self.meta_accum_steps > 1 and self.steps_per_dispatch > 8:
            # the fused multi-step scan only unrolls at k <= 8 (compile
            # time); a rolled outer scan compiles its body with
            # width-dependent fusion, which would silently void the
            # accumulation bit-exactness contract (core/maml.py,
            # _meta_loss_and_grads) — refuse the combination loudly
            raise ValueError(
                f"meta_accum_steps={self.meta_accum_steps} requires "
                f"steps_per_dispatch <= 8 (got {self.steps_per_dispatch}): "
                "larger fused chunks keep a rolled outer scan whose "
                "codegen breaks the accumulated-vs-monolithic equivalence"
            )
        if self.pool_impl not in ("auto", "reshape", "reduce_window"):
            raise ValueError(
                f"pool_impl must be 'auto', 'reshape' or 'reduce_window', "
                f"got {self.pool_impl!r}"
            )
        if self.bn_stats_impl not in ("auto", "twopass", "fused"):
            raise ValueError(
                f"bn_stats_impl must be 'auto', 'twopass' or 'fused', got "
                f"{self.bn_stats_impl!r}"
            )
        if self.im2col_hoist not in ("auto", "on", "off"):
            raise ValueError(
                f"im2col_hoist must be 'auto', 'on' or 'off', got "
                f"{self.im2col_hoist!r}"
            )
        if self.im2col_hoist == "on" and self.conv_impl == "lax":
            # the native conv consumes raw NHWC — there is no patch tensor
            # to hoist; refuse the contradiction at config time instead of
            # silently ignoring the forced knob at trace time
            raise ValueError(
                "im2col_hoist='on' requires a patch-based conv lowering "
                "(conv_impl 'im2col', 'gemm' or 'auto'), got "
                f"conv_impl={self.conv_impl!r}"
            )
        if self.im2col_hoist == "on" and self.block_order != "conv_norm_relu":
            raise ValueError(
                "im2col_hoist='on' requires block_order='conv_norm_relu': "
                "the alternate block normalizes the conv INPUT with "
                "adapted params, so layer 1's patches change every inner "
                f"step and cannot be hoisted (got {self.block_order!r})"
            )
        if self.max_pooling:
            # pool geometry is static — walk the stage dims (the same
            # recurrence as models.vgg._stage_dims) and reject feature
            # maps that VANISH under the 2x2/2 VALID pool at config time,
            # not as a reshape/reduce_window trace error deep in the step
            _h, _w = self.image_height, self.image_width
            _pad = 1 if self.conv_padding else 0
            for _stage in range(self.num_stages):
                _ch, _cw = _h + 2 * _pad - 2, _w + 2 * _pad - 2
                if _ch < 2 or _cw < 2:
                    raise ValueError(
                        f"max_pooling geometry vanishes at stage {_stage}: "
                        f"the pool input is {_ch}x{_cw}, smaller than the "
                        "2x2 window (VALID pooling would produce an empty "
                        "feature map) — reduce num_stages or grow "
                        f"image_height/image_width "
                        f"({self.image_height}x{self.image_width}, "
                        f"num_stages={self.num_stages})"
                    )
                _h, _w = _ch // 2, _cw // 2
        if self.steps_per_dispatch < 1:
            raise ValueError(
                f"steps_per_dispatch must be >= 1, got {self.steps_per_dispatch}"
            )
        if self.eval_batches_per_dispatch < 1:
            raise ValueError(
                f"eval_batches_per_dispatch must be >= 1, got "
                f"{self.eval_batches_per_dispatch}"
            )
        if self.matmul_precision not in ("auto", "default", "high", "highest"):
            raise ValueError(
                f"matmul_precision must be 'auto', 'default', 'high' or "
                f"'highest', got {self.matmul_precision!r}"
            )
        if self.input_layout not in ("auto", "nhwc", "nchw"):
            raise ValueError(
                f"input_layout must be 'auto', 'nhwc' or 'nchw', got "
                f"{self.input_layout!r}"
            )
        if self.data_placement not in ("host", "uint8_stream", "device"):
            raise ValueError(
                f"data_placement must be 'host', 'uint8_stream' or 'device', "
                f"got {self.data_placement!r}"
            )
        if self.data_placement != "host":
            # validated HERE, at config time, so a wrong combination fails
            # with a clear message instead of a silent wrong-numbers path
            # deep inside the loader/step machinery
            if "cifar" in self.dataset_name:
                raise ValueError(
                    f"data_placement={self.data_placement!r} is not "
                    f"supported for dataset {self.dataset_name!r}: CIFAR's "
                    "train-time augmentation (random crop + flip) draws "
                    "per-image randomness from the episode RNG stream and "
                    "cannot be vectorized into the on-device pipeline; use "
                    "data_placement='host' for CIFAR configs"
                )
            if not self.use_mmap_cache:
                raise ValueError(
                    f"data_placement={self.data_placement!r} requires "
                    "use_mmap_cache=true: the on-device pipeline gathers "
                    "from the flat uint8 image store that only the mmap "
                    "cache builds (data/preprocess.py)"
                )
        if self.store_sharding not in ("replicated", "hosts"):
            raise ValueError(
                f"store_sharding must be 'replicated' or 'hosts', got "
                f"{self.store_sharding!r}"
            )
        if self.store_sharding == "hosts" and self.data_placement != "device":
            raise ValueError(
                "store_sharding='hosts' only applies to the resident-store "
                "tier (data_placement='device'); the other placements keep "
                "no device store to shard"
            )
        if self.drain_margin_iters < 1:
            raise ValueError(
                f"drain_margin_iters must be >= 1, got "
                f"{self.drain_margin_iters}"
            )
        if self.ckpt_follower_timeout_s <= 0:
            raise ValueError(
                f"ckpt_follower_timeout_s must be > 0, got "
                f"{self.ckpt_follower_timeout_s}"
            )
        if self.telemetry_level not in ("off", "scalars", "dynamics"):
            raise ValueError(
                f"telemetry_level must be 'off', 'scalars' or 'dynamics', "
                f"got {self.telemetry_level!r}"
            )
        if self.tracing_level not in ("off", "on"):
            raise ValueError(
                f"tracing_level must be 'off' or 'on', got "
                f"{self.tracing_level!r}"
            )
        if self.tracing_level == "on" and self.telemetry_level == "off":
            raise ValueError(
                "tracing_level='on' requires telemetry_level != 'off': "
                "span records ride the telemetry JSONL sink (enable "
                "telemetry_level='scalars' or 'dynamics')"
            )
        # serving knobs: the ladder must be strictly increasing positive
        # ints (JSON configs may carry integral floats — coerce), and
        # every full batcher group must fit the top bucket
        ladder = self.serving_bucket_ladder
        if isinstance(ladder, list):
            self.serving_bucket_ladder = ladder = [
                int(v) if isinstance(v, float) and v.is_integer() else v
                for v in ladder
            ]
        if (
            not isinstance(ladder, list)
            or not ladder
            or not all(
                isinstance(v, int) and not isinstance(v, bool) and v >= 1
                for v in ladder
            )
            or any(a >= b for a, b in zip(ladder, ladder[1:]))
        ):
            raise ValueError(
                "serving_bucket_ladder must be a non-empty strictly "
                f"increasing list of positive ints, got {ladder!r}"
            )
        if self.serving_max_wait_ms < 0:
            raise ValueError(
                f"serving_max_wait_ms must be >= 0 (0 dispatches "
                f"immediately), got {self.serving_max_wait_ms}"
            )
        # same integral-float coercion as the ladder (JSON round-trips)
        if isinstance(
            self.serving_max_tenants_per_dispatch, float
        ) and self.serving_max_tenants_per_dispatch.is_integer():
            self.serving_max_tenants_per_dispatch = int(
                self.serving_max_tenants_per_dispatch
            )
        if not (
            isinstance(self.serving_max_tenants_per_dispatch, int)
            and not isinstance(self.serving_max_tenants_per_dispatch, bool)
            and 1 <= self.serving_max_tenants_per_dispatch <= ladder[-1]
        ):
            raise ValueError(
                "serving_max_tenants_per_dispatch must be an int in "
                f"[1, max(serving_bucket_ladder)={ladder[-1]}] so every "
                "full dispatch group fits a bucket, got "
                f"{self.serving_max_tenants_per_dispatch!r}"
            )
        if self.serving_ingest not in ("f32", "uint8", "index"):
            raise ValueError(
                f"serving_ingest must be 'f32', 'uint8' or 'index', got "
                f"{self.serving_ingest!r}"
            )
        if self.serving_ingest != "f32" and "cifar" in self.dataset_name:
            # same exclusion (and the same reason) as the training-side
            # non-host placements: CIFAR's per-image RNG augmentation
            # cannot be replayed on device
            raise ValueError(
                f"serving_ingest={self.serving_ingest!r} is not supported "
                f"for dataset {self.dataset_name!r}: the on-device decode "
                "cannot replay CIFAR's per-image RNG crop/flip; use "
                "serving_ingest='f32' for CIFAR configs"
            )
        if isinstance(
            self.serving_adapted_cache_size, float
        ) and self.serving_adapted_cache_size.is_integer():
            self.serving_adapted_cache_size = int(
                self.serving_adapted_cache_size
            )
        if not (
            isinstance(self.serving_adapted_cache_size, int)
            and not isinstance(self.serving_adapted_cache_size, bool)
            and self.serving_adapted_cache_size >= 0
        ):
            raise ValueError(
                "serving_adapted_cache_size must be an int >= 0 (0 "
                "disables the adapted-params cache), got "
                f"{self.serving_adapted_cache_size!r}"
            )
        # multi-replica / router / rollover knobs (same integral-float
        # coercion as the other serving ints — JSON round-trips)
        for knob in ("serving_replicas", "serving_router_spill_depth"):
            v = getattr(self, knob)
            if isinstance(v, float) and v.is_integer():
                setattr(self, knob, int(v))
        if not (
            isinstance(self.serving_replicas, int)
            and not isinstance(self.serving_replicas, bool)
            and self.serving_replicas >= 1
        ):
            raise ValueError(
                "serving_replicas must be an int >= 1 (each replica owns "
                "a disjoint device slice; 1 is the single-engine shape), "
                f"got {self.serving_replicas!r}"
            )
        if not (
            isinstance(self.serving_router_spill_depth, int)
            and not isinstance(self.serving_router_spill_depth, bool)
            and self.serving_router_spill_depth >= 1
        ):
            raise ValueError(
                "serving_router_spill_depth must be an int >= 1 (the "
                "home-replica backlog at which affinity routing spills "
                "to the least-loaded healthy replica), got "
                f"{self.serving_router_spill_depth!r}"
            )
        if not self.serving_rollover_poll_s > 0:
            raise ValueError(
                "serving_rollover_poll_s must be > 0 (how often the "
                "refresh daemon polls the checkpoint dir for rollover), "
                f"got {self.serving_rollover_poll_s!r}"
            )
        # SLO knobs (serving/metrics.py SLOTracker)
        if not (
            isinstance(self.serving_slo_target_ms, (int, float))
            and not isinstance(self.serving_slo_target_ms, bool)
            and self.serving_slo_target_ms >= 0
        ):
            raise ValueError(
                "serving_slo_target_ms must be a number >= 0 (0 disables "
                "deadline/SLO accounting), got "
                f"{self.serving_slo_target_ms!r}"
            )
        self.serving_slo_target_ms = float(self.serving_slo_target_ms)
        if not (
            isinstance(self.serving_slo_availability, float)
            and 0.0 < self.serving_slo_availability < 1.0
        ):
            raise ValueError(
                "serving_slo_availability must be a float in (0, 1) — the "
                "error budget is the 1 - availability remainder, so 0 and "
                "1 are both degenerate — got "
                f"{self.serving_slo_availability!r}"
            )
        windows = self.serving_slo_burn_windows_s
        if isinstance(windows, list):
            self.serving_slo_burn_windows_s = windows = [
                float(w) if isinstance(w, int)
                and not isinstance(w, bool) else w
                for w in windows
            ]
        if (
            not isinstance(windows, list)
            or not windows
            or not all(
                isinstance(w, float) and w > 0 for w in windows
            )
            or any(a >= b for a, b in zip(windows, windows[1:]))
        ):
            raise ValueError(
                "serving_slo_burn_windows_s must be a non-empty strictly "
                "increasing list of positive seconds (the multi-window "
                f"burn-rate alerting form), got {windows!r}"
            )
        # fleet gateway knobs (serving/gateway.py)
        for knob in (
            "serving_gateway_queue_budget",
            "serving_gateway_priority_tiers",
        ):
            val = getattr(self, knob)
            if isinstance(val, float) and val.is_integer():
                setattr(self, knob, int(val))
            val = getattr(self, knob)
            if not (
                isinstance(val, int)
                and not isinstance(val, bool)
                and val >= 1
            ):
                raise ValueError(
                    f"{knob} must be an int >= 1 (the gateway sheds "
                    "against the budget and clamps priorities into the "
                    f"tier range), got {val!r}"
                )
        if not (
            isinstance(self.serving_gateway_health_interval_s, (int, float))
            and not isinstance(self.serving_gateway_health_interval_s, bool)
            and self.serving_gateway_health_interval_s > 0
        ):
            raise ValueError(
                "serving_gateway_health_interval_s must be > 0 (the "
                "membership thread's /healthz poll cadence), got "
                f"{self.serving_gateway_health_interval_s!r}"
            )
        self.serving_gateway_health_interval_s = float(
            self.serving_gateway_health_interval_s
        )
        if self.analysis_level not in ("off", "warn", "strict"):
            raise ValueError(
                f"analysis_level must be 'off', 'warn' or 'strict', got "
                f"{self.analysis_level!r}"
            )
        if self.hbm_budget_gb < 0:
            raise ValueError(
                f"hbm_budget_gb must be >= 0 (0 disables the static HBM "
                f"budget check), got {self.hbm_budget_gb}"
            )
        if self.health_level not in ("off", "monitor", "halt"):
            raise ValueError(
                f"health_level must be 'off', 'monitor' or 'halt', got "
                f"{self.health_level!r}"
            )
        if self.health_patience < 1:
            raise ValueError(
                f"health_patience must be >= 1, got {self.health_patience}"
            )
        for knob in ("anomaly_loss_spike_factor", "anomaly_grad_spike_factor",
                     "anomaly_update_ratio_max", "health_grad_norm_limit"):
            if getattr(self, knob) < 0:
                raise ValueError(
                    f"{knob} must be >= 0 (0 disables the rule), got "
                    f"{getattr(self, knob)}"
                )
        if not (0.0 < self.anomaly_ema_beta < 1.0):
            raise ValueError(
                f"anomaly_ema_beta must be in (0, 1), got "
                f"{self.anomaly_ema_beta}"
            )
        for knob in ("anomaly_warmup_steps", "anomaly_cooldown_steps",
                     "flight_recorder_steps", "max_state_dumps"):
            if getattr(self, knob) < 0:
                raise ValueError(
                    f"{knob} must be >= 0, got {getattr(self, knob)}"
                )
        if self.watchdog_timeout_s < 0:
            raise ValueError(
                f"watchdog_timeout_s must be >= 0 (0 disables), got "
                f"{self.watchdog_timeout_s}"
            )
        if self.profile_start_step < 0:
            raise ValueError(
                f"profile_start_step must be >= 0, got "
                f"{self.profile_start_step}"
            )
        if self.io_retry_attempts < 1:
            raise ValueError(
                f"io_retry_attempts must be >= 1, got {self.io_retry_attempts}"
            )
        if self.io_retry_backoff_s < 0:
            raise ValueError(
                f"io_retry_backoff_s must be >= 0, got "
                f"{self.io_retry_backoff_s}"
            )
        if self.io_retry_backoff_factor < 1.0:
            raise ValueError(
                f"io_retry_backoff_factor must be >= 1, got "
                f"{self.io_retry_backoff_factor}"
            )
        # validated at config time so a typo'd spec fails the run with a
        # grammar error before any training (or CI chaos matrix) happens
        from .resilience.faults import parse_fault_spec

        parse_fault_spec(self.fault_spec)
        if self.remat_policy not in ("full", "save_conv"):
            raise ValueError(
                f"remat_policy must be 'full' or 'save_conv', got "
                f"{self.remat_policy!r}"
            )
        if os.environ.get("DATASET_DIR") and not os.path.isabs(self.dataset_path):
            # parser_utils.py:67-69 — dataset_path lives under $DATASET_DIR.
            self.dataset_path = os.path.join(
                os.environ["DATASET_DIR"], self.dataset_path
            )

    # -- derived quantities ------------------------------------------------

    @property
    def im_shape(self) -> Tuple[int, int, int]:
        """(h, w, c) — NHWC, the TPU-native layout."""
        return (self.image_height, self.image_width, self.image_channels)

    @property
    def inner_lr_init(self) -> float:
        """The inner-loop LR actually used at init.

        The reference initialises LSLR from ``task_learning_rate``
        (few_shot_learning_system.py:46-51) and never reads the JSON's
        ``init_inner_loop_learning_rate`` — preserved by default, fixable via
        ``use_config_init_inner_lr``.
        """
        if self.use_config_init_inner_lr:
            return self.init_inner_loop_learning_rate
        return self.task_learning_rate

    @property
    def clip_grads(self) -> bool:
        """Reference clamps outer grads to ±10 for imagenet datasets
        (few_shot_learning_system.py:332-335)."""
        return "imagenet" in self.dataset_name

    def _tuned(self, knob: str):
        """Measured value for ``knob`` from the device-kind-keyed tuning
        table (``cli tune`` writes it — analysis/autotune.py), or None when
        no table / no entry for this device kind + compute dtype exists.
        Measured defaults beat heuristics: the PR-4 auto rules left
        baseline-shaped TPU runs on the 'lax' conv path in practice
        (BENCH_BASELINE recorded conv_impl='lax' at 2.5% MFU)."""
        from .analysis import autotune

        try:
            import jax

            device_kind = jax.devices()[0].device_kind
        except Exception:  # noqa: BLE001 - no backend => no tuned entry
            return None
        entry = autotune.tuned_entry(device_kind, self.compute_dtype)
        if entry is None:
            return None
        return entry.get(knob)

    @property
    def resolved_conv_impl(self) -> str:
        """'auto' resolved through the tuning table first (a ``cli tune``
        sweep measured the fastest lowering for this device kind + compute
        dtype), then the backend/task-axis heuristic.

        Heuristic fallback — CPU: im2col (every AD order is a GEMM —
        sidesteps XLA:CPU's ~40x kernel-gradient conv). Accelerators: when
        ``task_axis_mode='vmap'`` the inner loop carries per-task adapted
        weights, so every conv is a batched-*weights* conv — the native
        lowering is a ``feature_group_count=tasks`` grouped conv that XLA
        runs an order of magnitude below MXU peak, while the 'gemm'
        lowering folds each layer into one large batched GEMM; with
        ``task_axis_mode='map'`` weights stay unbatched and the native conv
        is what the MXU tiles best.
        """
        if self.conv_impl != "auto":
            return self.conv_impl
        tuned = self._tuned("conv_impl")
        if tuned in ("lax", "im2col", "gemm"):
            return tuned
        import jax

        if jax.default_backend() == "cpu":
            return "im2col"
        return "gemm" if self.task_axis_mode == "vmap" else "lax"

    @property
    def resolved_pad_channels(self) -> Union[str, int]:
        """'auto' resolved through the tuning table first (see
        ``resolved_conv_impl``), then the backend heuristic: compute-only
        channel padding pays off where the MXU tiles GEMM operands in
        (sublane, 128-lane) blocks; on CPU it is pure overhead, so 'auto'
        disables it. Explicit 'off' / 'tile' / int values apply
        everywhere."""
        if self.pad_channels != "auto":
            return self.pad_channels
        tuned = self._tuned("pad_channels")
        if tuned == "off" or tuned == "tile" or (
            isinstance(tuned, int) and not isinstance(tuned, bool)
            and tuned > 0
        ):
            return tuned
        import jax

        return "off" if jax.default_backend() == "cpu" else "tile"

    @property
    def resolved_matmul_precision(self) -> str:
        """'auto' resolved from compute_dtype: fp32 configs get true fp32
        MXU multiplies ('highest' — second-order meta-gradients measurably
        need the mantissa bits); bf16 configs keep the native bf16 pass."""
        if self.matmul_precision != "auto":
            return self.matmul_precision
        return "highest" if self.compute_dtype == "float32" else "default"

    @property
    def resolved_pool_impl(self) -> str:
        """'auto' resolved through the tuning table first (``cli tune``
        sweeps pool_impl since PR 16), then the backend heuristic: the
        reshape pool's mask gradient wins on CPU; reduce_window avoids
        the tile-padded (.., 2, .., 2, ..) intermediate that bloats HBM
        on TPU."""
        if self.pool_impl != "auto":
            return self.pool_impl
        tuned = self._tuned("pool_impl")
        if tuned in ("reshape", "reduce_window"):
            return tuned
        import jax

        return "reshape" if jax.default_backend() == "cpu" else "reduce_window"

    @property
    def resolved_bn_stats_impl(self) -> str:
        """'auto' resolved through the tuning table first (``cli tune``
        sweeps bn_stats_impl since PR 16), then the backend heuristic:
        'fused' on CPU — the inner scan's BN statistics reductions are
        the top non-GEMM contributor in the roofline decomposition there,
        and one concatenated sum/sum-of-squares pass halves them at a
        pinned ULP tolerance — 'twopass' on accelerators (the bit-pinned
        historical lowering stays the default until a sweep measures the
        fused win on that hardware)."""
        if self.bn_stats_impl != "auto":
            return self.bn_stats_impl
        tuned = self._tuned("bn_stats_impl")
        if tuned in ("twopass", "fused"):
            return tuned
        import jax

        return "fused" if jax.default_backend() == "cpu" else "twopass"

    @property
    def resolved_im2col_hoist(self) -> bool:
        """Whether the inner loop hoists layer 1's patch extraction out of
        the scan (``core.maml._task_learner`` / ``models.vgg
        .layer1_patches``). 'on'/'off' are forced (the 'on' x 'lax' and
        'on' x norm-first contradictions are rejected at config build);
        'auto' enables it exactly when it applies — a patch-based conv
        lowering (the hoisted tensor is what the conv would extract
        inline, so this is bit-exact, strictly-less-work: no sweep axis
        needed) and the conv-first block order (the alternate block's
        conv input changes every inner step)."""
        if self.im2col_hoist == "off":
            return False
        if self.im2col_hoist == "on":
            return True
        return (
            self.block_order == "conv_norm_relu"
            and self.resolved_conv_impl in ("im2col", "gemm")
        )

    @property
    def global_tasks_per_batch(self) -> int:
        """Tasks the loader stacks per global batch
        (``num_of_gpus * batch_size * samples_per_iter``, ref data.py:580) —
        the single definition used by the loader AND by mesh sizing, so the
        task axis the mesh shards always matches what the loader produces.
        """
        return (
            max(1, self.num_of_gpus)
            * self.batch_size
            * max(1, self.samples_per_iter)
        )

    @property
    def bn_num_steps(self) -> int:
        """Size of the per-step BN arrays.

        The reference sizes them by the *training* step count
        (meta_neural_network_architectures.py:178-185); we size by the max of
        train/eval step counts so eval with more steps than train cannot index
        out of bounds (SURVEY.md §7 hazard), and clamp at apply time.
        """
        return max(
            self.number_of_training_steps_per_iter,
            self.number_of_evaluation_steps_per_iter,
        )

    # -- construction ------------------------------------------------------

    @classmethod
    def known_keys(cls) -> set:
        return {f.name for f in dataclasses.fields(cls)}

    @classmethod
    def from_json_file(cls, path: str, **overrides: Any) -> "MAMLConfig":
        """Load a reference-style experiment JSON, with keyword overrides.

        Mirrors ``extract_args_from_json`` (parser_utils.py:96-106): every key
        in the file overrides the defaults, except ``continue_from*`` and
        ``gpu_to_use`` which are resume/device controls owned by the caller.
        Unknown keys are ignored with a warning (the reference would silently
        carry them on the args object).
        """
        with open(path) as f:
            raw = json.load(f)
        kwargs: Dict[str, Any] = {}
        known = cls.known_keys()
        for key, value in raw.items():
            if "continue_from" in key or "gpu_to_use" in key:
                continue
            if key not in known:
                print(f"[config] ignoring unknown key {key!r} from {path}")
                continue
            kwargs[key] = value
        kwargs.update(overrides)
        return cls(**kwargs)

    def to_json(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(dataclasses.asdict(self), f, indent=2, sort_keys=True)

    def replace(self, **changes: Any) -> "MAMLConfig":
        return dataclasses.replace(self, **changes)
