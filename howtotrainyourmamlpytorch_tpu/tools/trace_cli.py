"""``cli trace`` — render a run's span records as a loadable timeline.

Reads the telemetry JSONL (schema v10 ``span`` records from
``telemetry/tracing.py``), writes a Chrome/Perfetto trace-event JSON
(load it at ``ui.perfetto.dev`` or ``chrome://tracing``) and prints the
critical-path summary:

* the serving latency decomposition per (program, bucket, shots) —
  mean milliseconds in queue wait vs. batch assembly vs. device
  dispatch vs. sync/readback, against the mean end-to-end request
  latency (queue+assemble+dispatch+sync ≈ e2e is the decomposition's
  acceptance identity);
* the flat per-span-name profile (train dispatch / eval chunk / epoch
  summary / checkpoint, data producer sample/stack/queue_put and
  consumer_wait);
* any on-demand device-profile windows (``trace`` records) captured
  during the run, linked by trace id to the host spans.

``--fleet`` merges a gateway log with its per-host shards
(``log.hostNN.jsonl`` siblings auto-discovered exactly like ``cli slo
--fleet``) into ONE Perfetto export: one process track per emitting
process (gateway + each host), host timestamps shifted onto the
gateway clock by the health sweep's Cristian offset estimate (the LAST
``gateway``/``clock`` record per host is the tightest bound), and a
fleet critical-path summary attributing mean e2e into
gateway_queue / wire / host_queue / assemble / dispatch / sync.
Passing several logs without ``--fleet`` is refused (exit 2) — a
single-run timeline over unrelated logs would be meaningless.

.. code-block:: console

   python -m howtotrainyourmamlpytorch_tpu.cli trace LOG
   python -m howtotrainyourmamlpytorch_tpu.cli trace LOG --out run.trace.json
   python -m howtotrainyourmamlpytorch_tpu.cli trace LOG --json
   python -m howtotrainyourmamlpytorch_tpu.cli trace GATEWAY_LOG --fleet

Pure stdlib + ``telemetry`` (no jax, no numpy) — dispatched jax-free by
``cli.py`` like ``inspect``, so a scp'd log renders on a laptop. Exit 0
even on a span-free log (the artifact is then an empty-but-loadable
trace); exit 2 on a missing/unparseable log.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

from ..telemetry.schema import iter_records
from ..telemetry.tracing import (
    FLEET_STAGES,
    SERVING_STAGES,
    critical_path_summary,
    fleet_critical_path,
    span_records,
    to_chrome_trace,
)
from .slo_cli import _expand_fleet_logs, _host_label


def _profile_windows(records: List[dict]) -> List[Dict[str, Any]]:
    """The run's device-profile windows (``trace`` records): start/stop
    pairs with their trace dirs — the on-demand captures an operator
    triggered, linked to the host spans by ``trace_id``."""
    out: List[Dict[str, Any]] = []
    for rec in records:
        if rec.get("kind") != "trace":
            continue
        out.append({
            k: rec.get(k)
            for k in ("action", "trace_dir", "steps", "trace_id",
                      "on_demand")
            if rec.get(k) is not None
        })
    return out


def clock_offsets(records: List[dict]) -> Dict[str, float]:
    """Per-host clock offsets from the gateway's ``event='clock'``
    records (Cristian estimates emitted by the health sweep). Records
    are emitted only when the min-RTT sample improves, so the LAST one
    per host carries the tightest ``clock_skew_bound_ms`` — later
    records simply overwrite earlier ones here."""
    offsets: Dict[str, float] = {}
    for rec in records:
        if rec.get("kind") != "gateway" or rec.get("event") != "clock":
            continue
        host = rec.get("host")
        off = rec.get("clock_offset_ms")
        if isinstance(host, str) and isinstance(off, (int, float)):
            offsets[host] = float(off)
    return offsets


def default_out_path(log: str) -> str:
    base = log[:-6] if log.endswith(".jsonl") else log
    return base + ".trace.json"


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="trace",
        description="Render span telemetry as a Chrome/Perfetto trace + "
                    "critical-path summary (jax-free)",
    )
    parser.add_argument("log", nargs="+",
                        help="telemetry JSONL path (with --fleet: the "
                             "gateway log; its log.hostNN.jsonl shards "
                             "are auto-discovered)")
    parser.add_argument("--fleet", action="store_true",
                        help="merge the gateway log with its per-host "
                             "shards into one clock-aligned Perfetto "
                             "export + fleet critical-path summary")
    parser.add_argument("--out", default=None, metavar="PATH",
                        help="Chrome trace-event JSON output path "
                             "(default: <log>.trace.json); '-' skips the "
                             "artifact")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable summary on stdout")
    args = parser.parse_args(argv)

    if not args.fleet and len(args.log) > 1:
        print("error: several logs need --fleet (a single timeline over "
              "unrelated logs would be meaningless)", file=sys.stderr)
        return 2

    logs = _expand_fleet_logs(args.log) if args.fleet else args.log
    records: List[dict] = []
    per_log_spans: Dict[str, int] = {}
    try:
        for path in logs:
            recs = list(iter_records(path))
            per_log_spans[_host_label(path)] = sum(
                1 for r in recs if r.get("kind") == "span"
            )
            records.extend(recs)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    spans = span_records(records)
    summary = critical_path_summary(spans)
    windows = _profile_windows(records)
    offsets = clock_offsets(records) if args.fleet else {}
    trace = to_chrome_trace(spans, offsets_ms=offsets or None)
    fleet = fleet_critical_path(spans) if args.fleet else None

    out_path = None
    if args.out != "-":
        out_path = args.out or default_out_path(logs[0])
        tmp = out_path + ".tmp"
        os.makedirs(
            os.path.dirname(os.path.abspath(out_path)), exist_ok=True
        )
        with open(tmp, "w") as f:
            json.dump(trace, f)
        os.replace(tmp, out_path)

    payload: Dict[str, Any] = {
        "log": logs if args.fleet else logs[0],
        "spans": len(spans),
        "trace_events": len(trace["traceEvents"]),
        "out": out_path,
        "serving": summary["serving"],
        "by_name": summary["by_name"],
        "profile_windows": windows,
    }
    if args.fleet:
        payload["clock_offsets_ms"] = offsets
        payload["fleet"] = fleet
    if args.json:
        print(json.dumps(payload, indent=2, sort_keys=True))
        return 0

    label = f"fleet[{len(logs)} log(s)]" if args.fleet else logs[0]
    lines = [f"{label}: {len(spans)} span(s)"]
    if args.fleet:
        for tag in sorted(per_log_spans):
            lines.append(f"    {tag}: {per_log_spans[tag]} span(s)")
    if out_path:
        lines.append(
            f"  chrome trace: {out_path} "
            f"({len(trace['traceEvents'])} events — load at "
            "ui.perfetto.dev or chrome://tracing)"
        )
    if not spans:
        lines.append(
            "  no span records: enable tracing_level='on' (train) or "
            "serve-bench --trace (serving)"
        )
    if fleet is not None:
        lines.append(
            f"  fleet: {fleet['requests']} request(s), "
            f"{fleet['sheds']} shed(s), {fleet['spanning_traces']} "
            f"spanning >=2 processes, {fleet['complete']} complete; "
            f"clock offsets for {len(offsets)} host(s)"
        )
        parts = []
        for stage in FLEET_STAGES:
            mean = fleet["stages"][f"{stage}_ms_mean"]
            if mean is not None:
                parts.append(f"{stage} {mean:.2f}")
        if parts:
            lines.append(
                "  fleet critical path (mean ms): " + ", ".join(parts)
            )
        if fleet["e2e_ms_mean"] is not None:
            lines.append(
                f"    stage sum {fleet['stage_sum_ms_mean']:.2f} vs "
                f"e2e {fleet['e2e_ms_mean']:.2f} "
                f"(coverage {fleet['coverage']:.2f})"
            )
    if summary["serving"]:
        lines.append("  serving critical path (mean ms per dispatch):")
        for key, row in summary["serving"].items():
            parts = []
            for stage in SERVING_STAGES:
                mean = row.get(f"{stage}_ms_mean")
                if mean is not None:
                    parts.append(f"{stage} {mean:.2f}")
            line = f"    {key}: " + ", ".join(parts or ["no stage spans"])
            line += f"  (stages {row['stages_ms']:.2f}"
            if row.get("request_ms_mean") is not None:
                line += f" vs e2e {row['request_ms_mean']:.2f}"
            line += ")"
            lines.append(line)
    train_names = [
        n for n in ("train_dispatch", "eval_chunk", "epoch_summary",
                    "eval_sync", "checkpoint", "sample", "stack",
                    "queue_put", "consumer_wait")
        if n in summary["by_name"]
    ]
    if train_names:
        lines.append("  spans by name (count / mean ms / total ms):")
        for name in train_names:
            agg = summary["by_name"][name]
            lines.append(
                f"    {name}: {agg['count']} / {agg['mean_ms']:.2f} / "
                f"{agg['total_ms']:.1f}"
            )
    if windows:
        lines.append(f"  device-profile windows: {len(windows)} event(s)")
        for win in windows:
            lines.append(
                f"    {win.get('action')}: {win.get('trace_dir')}"
                + (f" ({win.get('steps')} steps)" if win.get("steps")
                   else "")
            )
    print("\n".join(lines))
    return 0


if __name__ == "__main__":
    sys.exit(main())
