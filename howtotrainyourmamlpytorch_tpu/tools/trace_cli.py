"""``cli trace`` — render a run's span records as a loadable timeline.

Reads the telemetry JSONL (schema v10 ``span`` records from
``telemetry/tracing.py``), writes a Chrome/Perfetto trace-event JSON
(load it at ``ui.perfetto.dev`` or ``chrome://tracing``) and prints the
critical-path summary:

* the serving latency decomposition per (program, bucket, shots) —
  mean milliseconds in queue wait vs. batch assembly vs. device
  dispatch vs. sync/readback, against the mean end-to-end request
  latency (queue+assemble+dispatch+sync ≈ e2e is the decomposition's
  acceptance identity);
* the flat per-span-name profile (train dispatch / eval chunk / epoch
  summary / checkpoint, data producer sample/stack/queue_put and
  consumer_wait);
* any on-demand device-profile windows (``trace`` records) captured
  during the run, linked by trace id to the host spans.

.. code-block:: console

   python -m howtotrainyourmamlpytorch_tpu.cli trace LOG
   python -m howtotrainyourmamlpytorch_tpu.cli trace LOG --out run.trace.json
   python -m howtotrainyourmamlpytorch_tpu.cli trace LOG --json

Pure stdlib + ``telemetry`` (no jax, no numpy) — dispatched jax-free by
``cli.py`` like ``inspect``, so a scp'd log renders on a laptop. Exit 0
even on a span-free log (the artifact is then an empty-but-loadable
trace); exit 2 on a missing/unparseable log.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

from ..telemetry.schema import iter_records
from ..telemetry.tracing import (
    SERVING_STAGES,
    critical_path_summary,
    span_records,
    to_chrome_trace,
)


def _profile_windows(records: List[dict]) -> List[Dict[str, Any]]:
    """The run's device-profile windows (``trace`` records): start/stop
    pairs with their trace dirs — the on-demand captures an operator
    triggered, linked to the host spans by ``trace_id``."""
    out: List[Dict[str, Any]] = []
    for rec in records:
        if rec.get("kind") != "trace":
            continue
        out.append({
            k: rec.get(k)
            for k in ("action", "trace_dir", "steps", "trace_id",
                      "on_demand")
            if rec.get(k) is not None
        })
    return out


def default_out_path(log: str) -> str:
    base = log[:-6] if log.endswith(".jsonl") else log
    return base + ".trace.json"


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="trace",
        description="Render span telemetry as a Chrome/Perfetto trace + "
                    "critical-path summary (jax-free)",
    )
    parser.add_argument("log", help="telemetry JSONL (logs/telemetry.jsonl)")
    parser.add_argument("--out", default=None, metavar="PATH",
                        help="Chrome trace-event JSON output path "
                             "(default: <log>.trace.json); '-' skips the "
                             "artifact")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable summary on stdout")
    args = parser.parse_args(argv)

    try:
        records = list(iter_records(args.log))
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    spans = span_records(records)
    summary = critical_path_summary(spans)
    windows = _profile_windows(records)
    trace = to_chrome_trace(spans)

    out_path = None
    if args.out != "-":
        out_path = args.out or default_out_path(args.log)
        tmp = out_path + ".tmp"
        os.makedirs(
            os.path.dirname(os.path.abspath(out_path)), exist_ok=True
        )
        with open(tmp, "w") as f:
            json.dump(trace, f)
        os.replace(tmp, out_path)

    payload: Dict[str, Any] = {
        "log": args.log,
        "spans": len(spans),
        "trace_events": len(trace["traceEvents"]),
        "out": out_path,
        "serving": summary["serving"],
        "by_name": summary["by_name"],
        "profile_windows": windows,
    }
    if args.json:
        print(json.dumps(payload, indent=2, sort_keys=True))
        return 0

    lines = [f"{args.log}: {len(spans)} span(s)"]
    if out_path:
        lines.append(
            f"  chrome trace: {out_path} "
            f"({len(trace['traceEvents'])} events — load at "
            "ui.perfetto.dev or chrome://tracing)"
        )
    if not spans:
        lines.append(
            "  no span records: enable tracing_level='on' (train) or "
            "serve-bench --trace (serving)"
        )
    if summary["serving"]:
        lines.append("  serving critical path (mean ms per dispatch):")
        for key, row in summary["serving"].items():
            parts = []
            for stage in SERVING_STAGES:
                mean = row.get(f"{stage}_ms_mean")
                if mean is not None:
                    parts.append(f"{stage} {mean:.2f}")
            line = f"    {key}: " + ", ".join(parts or ["no stage spans"])
            line += f"  (stages {row['stages_ms']:.2f}"
            if row.get("request_ms_mean") is not None:
                line += f" vs e2e {row['request_ms_mean']:.2f}"
            line += ")"
            lines.append(line)
    train_names = [
        n for n in ("train_dispatch", "eval_chunk", "epoch_summary",
                    "eval_sync", "checkpoint", "sample", "stack",
                    "queue_put", "consumer_wait")
        if n in summary["by_name"]
    ]
    if train_names:
        lines.append("  spans by name (count / mean ms / total ms):")
        for name in train_names:
            agg = summary["by_name"][name]
            lines.append(
                f"    {name}: {agg['count']} / {agg['mean_ms']:.2f} / "
                f"{agg['total_ms']:.1f}"
            )
    if windows:
        lines.append(f"  device-profile windows: {len(windows)} event(s)")
        for win in windows:
            lines.append(
                f"    {win.get('action')}: {win.get('trace_dir')}"
                + (f" ({win.get('steps')} steps)" if win.get("steps")
                   else "")
            )
    print("\n".join(lines))
    return 0


if __name__ == "__main__":
    sys.exit(main())
