"""``cli slo`` — offline SLO report from a serving telemetry log.

Replays a JSONL log's ``serving``/``event="deadline"`` records (schema
v12, emitted once per deadline-carrying request by the micro-batcher)
through the SAME ``SLOTracker`` the live ``/metrics`` endpoint runs, so
the offline report, the scrape, and the end-of-run ``slo`` telemetry
record agree by construction — they are three renderings of one record
stream:

.. code-block:: console

   python -m howtotrainyourmamlpytorch_tpu.cli slo LOG
   python -m howtotrainyourmamlpytorch_tpu.cli slo LOG --json
   python -m howtotrainyourmamlpytorch_tpu.cli slo LOG --target-ms 50
   python -m howtotrainyourmamlpytorch_tpu.cli slo --fleet GATEWAY_LOG
   python -m howtotrainyourmamlpytorch_tpu.cli slo --fleet LOG LOG ...

The report: request/miss totals and miss rate, the error budget implied
by the availability objective, burn rate per window (how many budgets
per unit time the run was spending — 1.0 exhausts the budget exactly at
the objective; the windows anchor to the NEWEST record's timestamp, so
a replay reads the same "now" the live endpoint saw at shutdown), the
worst window, and a per-replica breakdown. When the log carries an
end-of-run ``slo`` record the replay is cross-checked against it and
any disagreement on request/miss counts is reported (exit 1) — the
pinned-summary-vs-raw-records consistency gate.

Target/availability/windows default to the log's own ``slo`` record
when present, else to the deadline records' budget; flags override.
A log with no deadline data reports that plainly and exits 0 (pre-v12
logs are data-free, never a crash). Exit codes: 0 ok, 1 replay/pinned
mismatch, 2 unreadable log or unusable flags.

``--fleet`` reports over a serve-bench ``--fleet`` run: the per-HOST
telemetry logs (``root.hostNN.ext``, one per fleet-host process) are
merged into ONE record stream, sorted by timestamp, and replayed
through a single ``SLOTracker`` — the fleet-wide SLO is a property of
the merged stream, not an average of per-host reports. Given a single
path, sibling ``.hostNN.`` logs are auto-discovered next to it (so the
gateway's own log path is enough); given several paths they are merged
as-is. The per-replica breakdown becomes a per-HOST one (replica ids
are host-local and would collide across hosts), and the pinned-record
cross-check is skipped — host logs pin no fleet-wide summary.

Pure stdlib + ``telemetry.schema`` + ``serving.metrics`` (both jax-free)
— dispatched by the training CLI before anything jax-heavy loads.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

from ..serving.metrics import SLOTracker
from ..telemetry.schema import iter_records


def _deadline_records(records: List[dict]) -> List[dict]:
    return [
        r for r in records
        if r.get("kind") == "serving" and r.get("event") == "deadline"
    ]


def _host_label(path: str) -> str:
    """A host label for a fleet shard: the ``.hostNN.`` filename segment
    serve-bench's ``_host_log_path`` writes, else the bare stem."""
    base = os.path.basename(path)
    m = re.search(r"\.(host[^.]+)\.", base)
    if m:
        return m.group(1)
    return os.path.splitext(base)[0]


def _expand_fleet_logs(paths: List[str]) -> List[str]:
    """Given one path, auto-discover its ``root.host*.ext`` siblings
    (the serve-bench ``--fleet`` layout); given several, keep them."""
    if len(paths) != 1:
        return list(paths)
    root, ext = os.path.splitext(paths[0])
    siblings = sorted(glob.glob(glob.escape(root) + ".host*" + ext))
    out = list(paths) if os.path.exists(paths[0]) else []
    out.extend(p for p in siblings if p not in out)
    return out or list(paths)


def _merge_fleet_records(
    per_log: List[Tuple[str, List[dict]]],
) -> Tuple[List[dict], Dict[str, Dict[str, int]]]:
    """Merge per-host record lists into one ts-sorted stream for a
    single-tracker replay, plus a per-host requests/missed table.

    Deadline records are shallow-copied with ``replica_id`` dropped:
    replica ids are host-local (every host numbers its replicas from
    0), so the tracker's per-replica series would silently merge
    replica 0 of every host. The per-HOST breakdown is computed here
    instead, keyed by the log's host label.
    """
    merged: List[dict] = []
    per_host: Dict[str, Dict[str, int]] = {}
    for label, records in per_log:
        for r in records:
            if r.get("kind") == "serving" and r.get("event") == "deadline":
                row = per_host.setdefault(
                    label, {"requests": 0, "missed": 0}
                )
                row["requests"] += 1
                if r.get("missed"):
                    row["missed"] += 1
                r = {k: v for k, v in r.items() if k != "replica_id"}
            merged.append(r)
    merged.sort(
        key=lambda r: r["ts"]
        if isinstance(r.get("ts"), (int, float))
        and not isinstance(r.get("ts"), bool)
        else float("-inf")
    )
    return merged, per_host


def _pinned_slo(records: List[dict]) -> Optional[dict]:
    """The log's LAST end-of-run ``slo`` record, if any."""
    return next(
        (r for r in reversed(records) if r.get("kind") == "slo"), None
    )


def _resolve_target_ms(args, pinned: Optional[dict],
                       deadlines: List[dict]) -> Optional[float]:
    """Flag > pinned slo record > the deadline records' own budget
    (the last one wins — within a run it is a constant)."""
    if args.target_ms is not None:
        return float(args.target_ms)
    if pinned is not None and isinstance(
        pinned.get("target_ms"), (int, float)
    ):
        return float(pinned["target_ms"])
    for r in reversed(deadlines):
        v = r.get("deadline_ms")
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            return float(v)
    return None


def _replay(records: List[dict], target_ms: float, availability: float,
            windows: List[float]) -> Dict[str, Any]:
    tracker = SLOTracker(
        target_ms=target_ms, availability=availability,
        burn_windows_s=tuple(windows),
    )
    for r in records:
        tracker.write(r)
    return tracker.summary()


def _render(log: str, summary: Dict[str, Any],
            mismatch: Optional[str],
            per_host: Optional[Dict[str, Dict[str, int]]] = None
            ) -> List[str]:
    lines = [f"{log}: SLO report"]
    lines.append(
        f"  objective: p(on-time) >= {summary['availability']:g} at "
        f"{summary['target_ms']:g}ms (error budget "
        f"{summary['error_budget']:g})"
    )
    miss_rate = summary.get("miss_rate")
    lines.append(
        f"  requests: {summary['requests']}, missed {summary['missed']}"
        + (
            f" (miss rate {miss_rate:.4f})" if miss_rate is not None
            else ""
        )
    )
    burn = summary.get("burn_rates") or {}
    parts = []
    for window, rate in burn.items():
        parts.append(
            f"{window}s={rate:.2f}" if rate is not None
            else f"{window}s=-"
        )
    if parts:
        line = "  burn rate: " + ", ".join(parts)
        if summary.get("worst_burn_rate") is not None:
            line += (
                f"  (worst: {summary['worst_burn_rate']:.2f} over "
                f"{summary['worst_burn_window_s']:g}s"
            )
            line += ", OVER BUDGET)" if summary[
                "worst_burn_rate"
            ] > 1.0 else ")"
        lines.append(line)
    if per_host is not None:
        for label, row in sorted(per_host.items()):
            lines.append(
                f"    host {label}: {row['requests']} request(s), "
                f"{row['missed']} missed"
            )
    else:
        for label, row in sorted(
            (summary.get("per_replica") or {}).items()
        ):
            lines.append(
                f"    replica {label}: {row['requests']} request(s), "
                f"{row['missed']} missed"
            )
    if mismatch:
        lines.append(f"  MISMATCH: {mismatch}")
    return lines


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="slo",
        description="Offline SLO report: replay a serving telemetry "
                    "log's deadline records (error budget, multi-window "
                    "burn rates, per-replica misses)",
    )
    parser.add_argument("log", nargs="+",
                        help="telemetry JSONL path (with --fleet: the "
                             "gateway log — sibling .hostNN. logs are "
                             "auto-discovered — or several host logs)")
    parser.add_argument("--fleet", action="store_true",
                        help="fleet mode: merge per-host logs into one "
                             "ts-sorted stream, replay through a single "
                             "tracker, report per HOST")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable JSON output")
    parser.add_argument("--target-ms", type=float, default=None,
                        help="SLO latency target override (default: the "
                             "log's slo record, else its deadline "
                             "records' budget)")
    parser.add_argument("--availability", type=float, default=None,
                        help="availability objective override, in (0,1) "
                             "(default: the log's slo record, else 0.99)")
    parser.add_argument("--window", action="append", type=float,
                        default=None, metavar="S",
                        help="burn-rate window in seconds (repeatable; "
                             "default: the log's slo record's windows, "
                             "else 60/300/3600)")
    args = parser.parse_args(argv)

    if not args.fleet and len(args.log) > 1:
        print("error: several logs need --fleet (a single-run report "
              "over many logs would be meaningless)", file=sys.stderr)
        return 2

    logs = _expand_fleet_logs(args.log) if args.fleet else args.log
    per_host: Optional[Dict[str, Dict[str, int]]] = None
    if args.fleet:
        per_log: List[Tuple[str, List[dict]]] = []
        try:
            for path in logs:
                per_log.append((_host_label(path),
                                list(iter_records(path))))
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        records, per_host = _merge_fleet_records(per_log)
        label = f"fleet[{len(logs)} log(s)]"
    else:
        try:
            records = list(iter_records(logs[0]))
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        label = logs[0]

    deadlines = _deadline_records(records)
    # host logs pin no fleet-wide summary, and a per-host slo record
    # (if one ever appears) must not be cross-checked against the
    # merged fleet replay — fleet mode skips the pin entirely
    pinned = None if args.fleet else _pinned_slo(records)
    if not deadlines and pinned is None:
        # a pre-v12 log, or a run without deadline accounting: there is
        # nothing to report, which is an answer, not an error
        msg = (
            f"{label}: no deadline records and no slo record — "
            "deadline accounting was not armed (run serve-bench with "
            "--deadline-ms or serving_slo_target_ms > 0)"
        )
        if args.json:
            print(json.dumps({"log": logs, "slo": None,
                              "note": msg}))
        else:
            print(msg)
        return 0

    target_ms = _resolve_target_ms(args, pinned, deadlines)
    if target_ms is None:
        print("error: no --target-ms given and the log's records carry "
              "no deadline budget to infer one from", file=sys.stderr)
        return 2
    availability = (
        args.availability if args.availability is not None
        else (
            float(pinned["availability"])
            if pinned is not None
            and isinstance(pinned.get("availability"), (int, float))
            and not isinstance(pinned.get("availability"), bool)
            else 0.99
        )
    )
    windows = args.window
    if windows is None:
        pinned_burn = (pinned or {}).get("burn_rates")
        if isinstance(pinned_burn, dict) and pinned_burn:
            try:
                windows = sorted(float(w) for w in pinned_burn)
            except (TypeError, ValueError):
                windows = None
    if windows is None:
        windows = [60.0, 300.0, 3600.0]
    try:
        summary = _replay(records, target_ms, availability, windows)
    except ValueError as e:  # bad flag combos (tracker validation)
        print(f"error: {e}", file=sys.stderr)
        return 2

    # cross-check the replay against the pinned end-of-run summary:
    # both derive from the same deadline records, so a count mismatch
    # means a truncated log or a writer bug — surface it loudly
    mismatch = None
    if pinned is not None:
        for key in ("requests", "missed"):
            if (
                isinstance(pinned.get(key), int)
                and pinned[key] != summary[key]
            ):
                mismatch = (
                    f"log's slo record says {key}={pinned[key]}, "
                    f"replaying its deadline records gives "
                    f"{summary[key]}"
                )
                break

    if args.json:
        payload = {
            "log": logs if args.fleet else logs[0],
            "slo": summary,
            "pinned": {
                k: pinned.get(k) for k in ("requests", "missed")
            } if pinned is not None else None,
            "mismatch": mismatch,
        }
        if per_host is not None:
            payload["per_host"] = per_host
        print(json.dumps(payload, sort_keys=True))
    else:
        print("\n".join(_render(label, summary, mismatch, per_host)))
    return 1 if mismatch else 0


if __name__ == "__main__":
    sys.exit(main())
