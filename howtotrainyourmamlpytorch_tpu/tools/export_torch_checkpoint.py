"""Export a checkpoint of this framework to the reference (PyTorch) format.

The inverse of ``import_torch_checkpoint``: converts a ``MetaState`` orbax
checkpoint into the ``torch.save`` payload the reference's ``load_model``
(few_shot_learning_system.py:410-424) consumes — so experiments can migrate
in BOTH directions (e.g. validate a TPU-trained model inside the reference's
evaluation harness). Layouts are transposed back NHWC/HWIO -> NCHW/OIHW,
including the row-major -> channel-major flatten permutation of the linear
head; LSLR vectors are re-mangled to the reference's key scheme. The torch
Adam state is synthesized empty with the correct param-group arity (the
reference's ``load_model`` unconditionally restores it,
few_shot_learning_system.py:422); the moments themselves restart, as they are
not translatable between optax and torch.

CLI:
    python -m howtotrainyourmamlpytorch_tpu.tools.export_torch_checkpoint \\
        --config experiment_config/omniglot_maml++-....json \\
        --checkpoint_dir <exp>/saved_models --model_idx latest \\
        --output <file for torch.save>
"""

from __future__ import annotations

import argparse
from typing import Any, Dict

import numpy as np

from ..config import MAMLConfig
from ..core import maml
from ..models import vgg


def convert_to_reference_state(
    cfg: MAMLConfig,
    net: Dict[str, Any],
    bn: Dict[str, Any],
    lslr: Dict[str, Any],
) -> Dict[str, np.ndarray]:
    """Build the reference system state_dict (numpy) from our pytrees."""
    out: Dict[str, np.ndarray] = {}
    fh, fw = vgg._feature_hw(cfg)
    train_steps = cfg.number_of_training_steps_per_iter

    def _truncate_steps(v: np.ndarray) -> np.ndarray:
        # inverse of the import-side padding: this framework sizes per-step
        # arrays by max(train, eval) steps, the reference by train steps
        if v.ndim == 2 and v.shape[0] > train_steps:
            return v[:train_steps]
        return v

    for key, value in net.items():
        v = np.asarray(value, np.float32)
        if key.endswith(".conv.weight"):
            # HWIO -> OIHW
            out[f"classifier.layer_dict.{key}"] = np.transpose(v, (3, 2, 0, 1))
        elif key.endswith(".conv.bias"):
            out[f"classifier.layer_dict.{key}"] = v
        elif ".norm." in key:
            stage, leaf = key.split(".norm.")
            if cfg.norm_layer == "layer_norm" and v.ndim == 3:
                v = np.transpose(v, (2, 0, 1))  # (h,w,c) -> (c,h,w)
            ref_leaf = {"gamma": "weight", "beta": "bias"}[leaf]
            out[f"classifier.layer_dict.{stage}.norm_layer.{ref_leaf}"] = (
                _truncate_steps(v)
            )
        elif key == "linear.weight":
            feat, way = v.shape
            if cfg.max_pooling and fh * fw > 1:
                # (h*w*c, way) -> (way, c*h*w)
                v = v.reshape(fh, fw, cfg.cnn_num_filters, way)
                v = np.transpose(v, (3, 2, 0, 1)).reshape(way, feat)
            else:
                v = v.T
            out["classifier.layer_dict.linear.weights"] = v
        elif key == "linear.bias":
            out["classifier.layer_dict.linear.bias"] = v

    for key, value in bn.items():
        stage, leaf = key.split(".norm.")
        ref_leaf = {"mean": "running_mean", "var": "running_var"}[leaf]
        out[f"classifier.layer_dict.{stage}.norm_layer.{ref_leaf}"] = (
            _truncate_steps(np.asarray(value, np.float32))
        )

    if cfg.norm_layer == "batch_norm" and not cfg.per_step_bn_statistics:
        # plain-BN: this framework tracks no running stats (they never
        # normalize anything), but the reference's layer registers them —
        # emit the never-used init values so strict load_state_dict passes
        f = cfg.cnn_num_filters
        for i in range(cfg.num_stages):
            prefix = f"classifier.layer_dict.conv{i}.norm_layer"
            out[f"{prefix}.running_mean"] = np.zeros((f,), np.float32)
            # the reference inits plain-mode running_var to ZEROS too
            # (meta_...py:188 — a quirk; the stats never normalize anything)
            out[f"{prefix}.running_var"] = np.zeros((f,), np.float32)

    for key, value in lslr.items():
        name = key
        if name == "linear.weight":  # reference's plural quirk
            name = "linear.weights"
        name = name.replace(".norm.gamma", ".norm_layer.weight")
        name = name.replace(".norm.beta", ".norm_layer.bias")
        ref_key = ("layer_dict." + name).replace(".", "-")
        out[
            f"inner_loop_optimizer.names_learning_rates_dict.{ref_key}"
        ] = np.asarray(value, np.float32)

    return out


def _fresh_adam_state_dict(cfg: MAMLConfig, state) -> Dict[str, Any]:
    """An empty torch Adam state_dict whose single param group matches the
    reference system's trainable-parameter count, so the reference's
    unconditional ``optimizer.load_state_dict(state['optimizer'])``
    (few_shot_learning_system.py:422) succeeds. Adam moments restart — the
    moments themselves are not translatable between optax and torch.
    """
    import torch

    from ..core import partition

    n_trainable = sum(
        1 for k in state.net if partition.is_trainable(cfg, k)
    )
    if cfg.learnable_per_layer_per_step_inner_loop_learning_rate:
        n_trainable += len(state.lslr)
    dummies = [torch.nn.Parameter(torch.zeros(1)) for _ in range(n_trainable)]
    opt = torch.optim.Adam(
        dummies, lr=cfg.meta_learning_rate, amsgrad=False
    )
    return opt.state_dict()


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--config", required=True, help="experiment config JSON")
    ap.add_argument("--checkpoint_dir", required=True, help="saved_models dir")
    ap.add_argument("--model_idx", default="latest")
    ap.add_argument("--output", required=True, help="torch checkpoint file to write")
    args = ap.parse_args(argv)

    import torch

    from ..experiment import checkpoint as ckpt

    cfg = MAMLConfig.from_json_file(args.config)
    idx = args.model_idx if args.model_idx == "latest" else int(args.model_idx)
    state, experiment_state = ckpt.load_checkpoint(
        args.checkpoint_dir, "train_model", idx, maml.init_state(cfg)
    )
    ref_sd = convert_to_reference_state(cfg, state.net, state.bn, state.lslr)
    payload = dict(experiment_state)
    payload["network"] = {
        k: torch.from_numpy(np.ascontiguousarray(v)) for k, v in ref_sd.items()
    }
    payload["optimizer"] = _fresh_adam_state_dict(cfg, state)
    torch.save(payload, args.output)
    print(f"exported {args.checkpoint_dir}/train_model_{idx} -> {args.output}")


if __name__ == "__main__":
    main()
