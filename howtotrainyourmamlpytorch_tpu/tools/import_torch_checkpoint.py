"""Import a reference (PyTorch) checkpoint into this framework's format.

A user of ``AntreasAntoniou/HowToTrainYourMAMLPytorch`` can migrate a trained
experiment mid-flight: this converts the reference's ``torch.save`` payload
(``few_shot_learning_system.py:399-408`` — the system ``state_dict`` plus the
experiment-state scalars) into a ``MetaState`` and writes an orbax checkpoint
this framework resumes from.

Layout conversions (reference NCHW/OIHW -> TPU-native NHWC/HWIO):

* conv weights ``(out, in, kh, kw)`` -> ``(kh, kw, in, out)``;
* the linear head ``(way, c*h*w)`` -> ``(h*w*c, way)`` — NOT a plain
  transpose: the reference flattens channel-major NCHW feature maps, we
  flatten NHWC, so the input axis is permuted per (h, w, c) position;
* layer-norm affine params ``(c, h, w)`` -> ``(h, w, c)``;
* per-step BN gamma/beta/stats ``(steps, features)`` carry over unchanged;
* LSLR per-step learning rates: keys ``layer_dict-conv0-conv-weight`` ->
  ``conv0.conv.weight``, values unchanged.

The Adam moments are NOT imported (torch and optax Adam states are not
interchangeable); the outer optimizer restarts fresh, which the reference
itself survives routinely (kill-safe design). Experiment-state scalars
(current_iter, best_val_acc, ...) carry over so resume arithmetic holds.

CLI:
    python -m howtotrainyourmamlpytorch_tpu.tools.import_torch_checkpoint \\
        --config experiment_config/omniglot_maml++-....json \\
        --torch_checkpoint <ref_exp>/saved_models/train_model_latest \\
        --output_dir <new_exp>/saved_models --model_idx latest
"""

from __future__ import annotations

import argparse
import pickle
import sys
from typing import Any, Dict, Tuple

import numpy as np

from ..config import MAMLConfig
from ..core import maml
from ..models import vgg

_NET_PREFIXES = ("classifier.layer_dict.", "layer_dict.")
_LSLR_PREFIXES = (
    "inner_loop_optimizer.names_learning_rates_dict.",
    "names_learning_rates_dict.",
)


def _strip_prefix(key: str, prefixes) -> str:
    for p in prefixes:
        if key.startswith(p):
            return key[len(p):]
    return ""


def convert_network_state(
    cfg: MAMLConfig, state_dict: Dict[str, np.ndarray]
) -> Tuple[Dict[str, np.ndarray], Dict[str, np.ndarray], Dict[str, np.ndarray]]:
    """Map the reference system/classifier ``state_dict`` (as numpy arrays)
    to (net params, bn state, lslr params) in this framework's naming/layout.
    """
    params: Dict[str, np.ndarray] = {}
    bn_state: Dict[str, np.ndarray] = {}
    lslr: Dict[str, np.ndarray] = {}
    fh, fw = vgg._feature_hw(cfg)

    for key, value in state_dict.items():
        v = np.asarray(value, np.float32)
        net_key = _strip_prefix(key, _NET_PREFIXES)
        lslr_key = _strip_prefix(key, _LSLR_PREFIXES)
        if lslr_key:
            # layer_dict-conv0-conv-weight -> conv0.conv.weight
            # (inner_loop_optimizers.py:89 replaces '.' with '-')
            name = lslr_key.replace("-", ".")
            if name.startswith("layer_dict."):
                name = name[len("layer_dict."):]
            if name == "linear.weights":  # reference's plural quirk
                name = "linear.weight"
            # inner-adaptable norm params (enable_inner_loop_optimizable_bn_
            # params=True): norm_layer.weight/bias -> norm.gamma/beta
            name = name.replace(".norm_layer.weight", ".norm.gamma")
            name = name.replace(".norm_layer.bias", ".norm.beta")
            lslr[name] = v
            continue
        if not net_key:
            continue
        if net_key.endswith(".conv.weight"):
            # OIHW -> HWIO
            params[net_key] = np.transpose(v, (2, 3, 1, 0))
        elif net_key.endswith(".conv.bias"):
            params[net_key] = v
        elif ".norm_layer." in net_key:
            stage, leaf = net_key.split(".norm_layer.")
            if cfg.norm_layer == "layer_norm" and v.ndim == 3:
                v = np.transpose(v, (1, 2, 0))  # (c,h,w) -> (h,w,c)
            if leaf == "weight":
                params[f"{stage}.norm.gamma"] = v
            elif leaf == "bias":
                params[f"{stage}.norm.beta"] = v
            elif leaf == "running_mean":
                if cfg.per_step_bn_statistics:
                    bn_state[f"{stage}.norm.mean"] = v
            elif leaf == "running_var":
                if cfg.per_step_bn_statistics:
                    bn_state[f"{stage}.norm.var"] = v
        elif net_key == "linear.weights":
            way = v.shape[0]
            if cfg.max_pooling and fh * fw > 1:
                # (way, c*h*w) channel-major -> (h*w*c, way) row-major NHWC
                v = v.reshape(way, cfg.cnn_num_filters, fh, fw)
                v = np.transpose(v, (2, 3, 1, 0)).reshape(fh * fw * cfg.cnn_num_filters, way)
            else:
                v = v.T
            params["linear.weight"] = v
        elif net_key == "linear.bias":
            params["linear.bias"] = v

    # this framework sizes per-step BN arrays by max(train, eval) steps
    # (config.bn_num_steps, the SURVEY §7 out-of-bounds fix); reference
    # checkpoints size them by the training step count — pad by repeating
    # the final step's values (what step-clamping would have used)
    def _pad_steps(v: np.ndarray) -> np.ndarray:
        if v.ndim == 2 and v.shape[0] < cfg.bn_num_steps:
            pad = np.repeat(v[-1:], cfg.bn_num_steps - v.shape[0], axis=0)
            return np.concatenate([v, pad], axis=0)
        return v

    if cfg.per_step_bn_statistics:
        for key in list(params):
            if ".norm." in key:
                params[key] = _pad_steps(params[key])
        for key in list(bn_state):
            bn_state[key] = _pad_steps(bn_state[key])
    return params, bn_state, lslr


def import_torch_checkpoint(cfg: MAMLConfig, torch_ckpt_path: str):
    """Load a reference checkpoint file and build a full MetaState (fresh
    Adam moments) plus the carried-over experiment-state scalars."""
    import torch

    try:
        # safe path first: tensors-only unpickling, no arbitrary-code objects
        payload = torch.load(
            torch_ckpt_path, map_location="cpu", weights_only=True
        )
    except (pickle.UnpicklingError, RuntimeError, TypeError):
        # TypeError: torch < 1.13 has no weights_only kwarg at all
        # reference checkpoints store the experiment-state scalars alongside
        # the tensors (experiment_builder.py:190-206) and may need the full
        # unpickler; only fall back for files the user chose to import —
        # and say so, since the full unpickler executes code in the file
        print(
            f"import_torch_checkpoint: weights_only load failed for "
            f"{torch_ckpt_path!r}; falling back to the UNSAFE full "
            f"unpickler (only do this for files you trust)",
            file=sys.stderr,
        )
        payload = torch.load(
            torch_ckpt_path, map_location="cpu", weights_only=False
        )
    network = payload["network"] if "network" in payload else payload
    state_dict = {k: v.detach().cpu().numpy() for k, v in network.items()}
    params, bn_state, lslr = convert_network_state(cfg, state_dict)

    ref_state = maml.init_state(cfg)  # shapes/structure + fresh opt state
    _check_tree("net", ref_state.net, params)
    _check_tree("bn", ref_state.bn, bn_state)
    _check_tree("lslr", ref_state.lslr, lslr)
    import jax.numpy as jnp

    state = maml.MetaState(
        net={k: jnp.asarray(v) for k, v in params.items()},
        lslr={k: jnp.asarray(v) for k, v in lslr.items()},
        bn={k: jnp.asarray(v) for k, v in bn_state.items()},
        opt=ref_state.opt,
    )
    experiment_state = {
        k: v for k, v in payload.items()
        if k not in ("network", "optimizer")
        and isinstance(v, (int, float, str, bool, list, dict))
    }
    return state, experiment_state


def _check_tree(name: str, expected: Dict[str, Any], got: Dict[str, Any]):
    missing = set(expected) - set(got)
    extra = set(got) - set(expected)
    if missing or extra:
        raise ValueError(
            f"{name} keys mismatch: missing {sorted(missing)}, "
            f"unexpected {sorted(extra)} — does the --config match the "
            f"checkpoint's architecture?"
        )
    for k in expected:
        if tuple(np.shape(expected[k])) != tuple(np.shape(got[k])):
            raise ValueError(
                f"{name}[{k}]: shape {np.shape(got[k])} != expected "
                f"{np.shape(expected[k])}"
            )


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--config", required=True, help="experiment config JSON")
    ap.add_argument("--torch_checkpoint", required=True)
    ap.add_argument("--output_dir", required=True, help="saved_models dir to write into")
    ap.add_argument("--model_idx", default="latest", help="checkpoint index to write (epoch int or 'latest')")
    args = ap.parse_args(argv)

    from ..experiment import checkpoint as ckpt

    cfg = MAMLConfig.from_json_file(args.config)
    state, experiment_state = import_torch_checkpoint(cfg, args.torch_checkpoint)
    idx = args.model_idx if args.model_idx == "latest" else int(args.model_idx)
    path = ckpt.save_checkpoint(
        args.output_dir, "train_model", idx, state, experiment_state
    )
    print(f"imported {args.torch_checkpoint} -> {path}")


if __name__ == "__main__":
    main()
