"""Inspect / diff telemetry JSONL logs from the command line.

The structured event log (``logs/telemetry.jsonl``, one schema-versioned
JSON record per line — see ``telemetry/schema.py``) is the run's flight
data; this CLI is the reader, so a diverging TPU run can be diagnosed
from any shell with the repo checked out and nothing else:

.. code-block:: console

   python -m howtotrainyourmamlpytorch_tpu.tools.telemetry_cli summary LOG
   python -m howtotrainyourmamlpytorch_tpu.tools.telemetry_cli epochs LOG
   python -m howtotrainyourmamlpytorch_tpu.tools.telemetry_cli anomalies LOG
   python -m howtotrainyourmamlpytorch_tpu.tools.telemetry_cli tail LOG -n 20 --kind epoch
   python -m howtotrainyourmamlpytorch_tpu.tools.telemetry_cli diff LOG_A LOG_B
   python -m howtotrainyourmamlpytorch_tpu.tools.telemetry_cli validate LOG

(also reachable as ``python -m howtotrainyourmamlpytorch_tpu.cli
inspect <subcommand> ...`` — the training CLI dispatches ``inspect``
here before importing anything jax-heavy)

* ``summary``   — run overview: record counts by kind, wall-clock span,
  epoch range, final/best validation accuracy, dispatch-timing
  percentiles, loader stream-stall stats, HBM usage,
  anomaly/incident/stall/retry/preemption/retrace counts, the
  elastic drain/resume line (schema v6: drain protocol progress plus the
  last old->new process-count resume with its episode cursor), the
  serving SLO line (schema v12: deadline-miss rate, worst burn-rate
  window, per-replica misses), and the fleet line (schema v13: gateway
  host membership, admitted/shed totals, re-home events — absent,
  never a crash, on older logs);
* ``epochs``    — the per-epoch scalar table (loss/accuracy/step-time
  columns), the epoch CSV's queryable twin;
* ``anomalies`` — every ``anomaly`` / ``incident`` / ``watchdog_stall`` /
  ``preemption`` / ``retrace`` record, one line each (the postmortem
  index / anomaly timeline);
* ``tail``      — the last N records, optionally filtered by kind;
* ``diff``      — align two runs' per-epoch scalars, report per-metric
  deltas and the first epoch where a watched metric diverges beyond
  tolerance, plus the config-key diff from the ``run_start`` snapshots
  ("what changed between these two runs, and when did it start
  mattering");
* ``validate``  — schema-validate every record (exit 1 on the first
  offender; what the CI telemetry-smoke job runs).

Every subcommand takes ``--json`` for machine-readable output. Pure
stdlib + ``telemetry.schema`` — importable without jax, so it runs on a
laptop against a log scp'd off a pod.
"""

from __future__ import annotations

import argparse
import json
import math
import sys
from typing import Any, Dict, Iterable, List, Optional, Tuple

from ..telemetry.schema import iter_records, validate_file

#: metrics `diff` watches for the divergence epoch unless --metric is given
DEFAULT_WATCH_METRICS = ("train_loss_mean", "val_accuracy_mean")

ANOMALY_KINDS = ("anomaly", "incident", "watchdog_stall", "preemption",
                 "retrace")


def _load(path: str) -> List[dict]:
    return list(iter_records(path))


def _fmt_ts_span(records: List[dict]) -> Optional[float]:
    ts = [r["ts"] for r in records if isinstance(r.get("ts"), (int, float))]
    return (max(ts) - min(ts)) if ts else None


def _epoch_scalars(records: Iterable[dict]) -> Dict[int, Dict[str, float]]:
    """epoch -> scalars from the ``epoch`` records (last write wins, so a
    resumed run's re-trained epoch reads as its final numbers)."""
    out: Dict[int, Dict[str, float]] = {}
    for r in records:
        if (
            r.get("kind") == "epoch"
            and isinstance(r.get("scalars"), dict)
            and isinstance(r.get("epoch"), (int, float))
        ):
            out[int(r["epoch"])] = {
                k: v for k, v in r["scalars"].items()
                if isinstance(v, (int, float)) and not isinstance(v, bool)
            }
    return out


def _emit(payload: Dict[str, Any], as_json: bool, text_lines: List[str]) -> None:
    if as_json:
        print(json.dumps(payload, indent=2, sort_keys=True))
    else:
        print("\n".join(text_lines))


def _mean_of(records: List[dict], kind: str, keys: Tuple[str, ...]) -> Dict[str, float]:
    """Per-key mean over every record of ``kind`` that carries the key
    (numeric, finite)."""
    out: Dict[str, float] = {}
    for key in keys:
        vals = [
            r[key] for r in records
            if r.get("kind") == kind
            and isinstance(r.get(key), (int, float))
            and not isinstance(r.get(key), bool)
            and math.isfinite(r[key])
        ]
        if vals:
            out[key] = sum(vals) / len(vals)
    return out


def _elastic_summary(records: List[dict]) -> Optional[Dict[str, Any]]:
    """Condense the ``elastic`` records (schema v6): drain-protocol event
    counts plus the LAST topology-change resume marker (old/new process
    count and the episode-cursor re-entry point). None when the run has no
    elastic records at all."""
    ev = [r for r in records if r.get("kind") == "elastic"]
    if not ev:
        return None
    out: Dict[str, Any] = {
        "drain_requests": sum(
            1 for r in ev if r.get("event") == "drain_request"
        ),
        "drain_commits": sum(
            1 for r in ev if r.get("event") == "drain_commit"
        ),
        "drain_acks": sum(1 for r in ev if r.get("event") == "drain_ack"),
        "resumes": sum(1 for r in ev if r.get("event") == "resume"),
        "last_resume": None,
    }
    last = next(
        (r for r in reversed(ev) if r.get("event") == "resume"), None
    )
    if last is not None:
        out["last_resume"] = {
            k: last.get(k)
            for k in ("old_process_count", "new_process_count", "iter",
                      "episode_cursor")
        }
    return out


def _percentile(values: List[float], q: float) -> float:
    """Linear-interpolation percentile (numpy's default), pure stdlib —
    this module must run on a laptop with nothing but the repo."""
    vals = sorted(values)
    if len(vals) == 1:
        return vals[0]
    pos = (len(vals) - 1) * q / 100.0
    lo = int(math.floor(pos))
    hi = min(lo + 1, len(vals) - 1)
    return vals[lo] + (vals[hi] - vals[lo]) * (pos - lo)


def _serving_summary(records: List[dict]) -> Optional[Dict[str, Any]]:
    """Condense the ``serving`` records (schema v8): dispatch/tenant
    counts and adapt-latency p50/p95 recomputed from the per-dispatch
    records, plus the LAST rollup record's tenants_per_sec / retraces.
    Since v11 also the multi-replica grain: a per-``replica_id``
    breakdown (records without the field — every pre-v11 log — simply
    produce no per-replica rows) and the checkpoint-rollover count.
    None when the run has no serving records at all (every pre-v8 log),
    so the summary line simply doesn't render — old logs never crash."""
    sv = [r for r in records if r.get("kind") == "serving"]
    if not sv:
        return None

    def _finite(key: str) -> List[float]:
        return [
            r[key] for r in sv
            if r.get("event") == "dispatch"
            and isinstance(r.get(key), (int, float))
            and not isinstance(r.get(key), bool)
            and math.isfinite(r[key])
        ]

    adapt = _finite("adapt_ms")
    queue = _finite("queue_ms")
    tenants = [
        int(r["tenants"]) for r in sv
        if r.get("event") == "dispatch"
        and isinstance(r.get("tenants"), int)
        and not isinstance(r.get("tenants"), bool)
    ]
    rollup = next(
        (r for r in reversed(sv) if r.get("event") == "rollup"), None
    )
    # per-(program, bucket, shots) breakdown (the compiled-program grain):
    # p50/p95 latency + cache-hit rate per dispatch signature instead of
    # one aggregate line. Records missing the v9 `program` field (v8-era
    # logs) group under 'adapt'; non-dispatch and malformed records are
    # simply skipped — pre-v10 logs must render, never crash.
    per_bucket: Dict[str, Dict[str, Any]] = {}
    groups: Dict[str, Dict[str, list]] = {}
    for r in sv:
        if r.get("event") != "dispatch":
            continue
        key = (
            f"{r.get('program', 'adapt')}"
            f"/b{r.get('bucket', '?')}/s{r.get('shots', '?')}"
        )
        g = groups.setdefault(
            key, {"adapt": [], "tenants": [], "hits": []}
        )
        adapt_v = r.get("adapt_ms")
        if isinstance(adapt_v, (int, float)) and not isinstance(
            adapt_v, bool
        ) and math.isfinite(adapt_v):
            g["adapt"].append(float(adapt_v))
        n_tenants = r.get("tenants")
        if isinstance(n_tenants, int) and not isinstance(n_tenants, bool):
            g["tenants"].append(n_tenants)
        hits = r.get("cache_hits")
        if isinstance(hits, int) and not isinstance(hits, bool):
            g["hits"].append(hits)
    for key, g in sorted(groups.items()):
        tenants_total = sum(g["tenants"])
        per_bucket[key] = {
            "dispatches": len(g["tenants"]) or len(g["adapt"]),
            "tenants": tenants_total,
            "adapt_ms_p50": (
                round(_percentile(g["adapt"], 50), 3) if g["adapt"]
                else None
            ),
            "adapt_ms_p95": (
                round(_percentile(g["adapt"], 95), 3) if g["adapt"]
                else None
            ),
            "cache_hit_rate": (
                round(sum(g["hits"]) / tenants_total, 4)
                if g["hits"] and tenants_total else None
            ),
        }
    # per-replica breakdown (schema v11, serving/replica.py): dispatch/
    # tenant counts, latency p50 and cache-hit rate per replica_id —
    # how evenly the affinity router spread the pool's traffic. Records
    # without a replica_id (single-engine runs, pre-v11 logs) yield no
    # rows; malformed ids are skipped, never a crash.
    per_replica: Dict[str, Dict[str, Any]] = {}
    rgroups: Dict[int, Dict[str, list]] = {}
    for r in sv:
        if r.get("event") != "dispatch":
            continue
        rid = r.get("replica_id")
        if not isinstance(rid, int) or isinstance(rid, bool):
            continue
        g = rgroups.setdefault(rid, {"adapt": [], "tenants": [], "hits": []})
        adapt_v = r.get("adapt_ms")
        if isinstance(adapt_v, (int, float)) and not isinstance(
            adapt_v, bool
        ) and math.isfinite(adapt_v):
            g["adapt"].append(float(adapt_v))
        n_tenants = r.get("tenants")
        if isinstance(n_tenants, int) and not isinstance(n_tenants, bool):
            g["tenants"].append(n_tenants)
        hits = r.get("cache_hits")
        if isinstance(hits, int) and not isinstance(hits, bool):
            g["hits"].append(hits)
    for rid in sorted(rgroups):
        g = rgroups[rid]
        tenants_total = sum(g["tenants"])
        per_replica[str(rid)] = {
            "dispatches": len(g["tenants"]) or len(g["adapt"]),
            "tenants": tenants_total,
            "adapt_ms_p50": (
                round(_percentile(g["adapt"], 50), 3) if g["adapt"]
                else None
            ),
            "cache_hit_rate": (
                round(sum(g["hits"]) / tenants_total, 4)
                if g["hits"] and tenants_total else None
            ),
        }
    # one pool rollover emits ONE record per replica swap: count
    # distinct target markers so the summary agrees with
    # RefreshDaemon.rollovers and the bench line's rollover block
    # (records without a new_iter degrade to one group)
    roll_recs = [r for r in sv if r.get("event") == "rollover"]
    out: Dict[str, Any] = {
        "dispatches": sum(1 for r in sv if r.get("event") == "dispatch"),
        "tenants": sum(tenants),
        # v11 pool fields (0 / {} on single-engine and pre-v11 logs)
        "rollovers": (
            len({r.get("new_iter") for r in roll_recs}) if roll_recs
            else 0
        ),
        "per_replica": per_replica,
        "tenants_per_dispatch_mean": (
            round(sum(tenants) / len(tenants), 3) if tenants else None
        ),
        "adapt_ms_p50": (
            round(_percentile(adapt, 50), 3) if adapt else None
        ),
        "adapt_ms_p95": (
            round(_percentile(adapt, 95), 3) if adapt else None
        ),
        "queue_ms_mean": (
            round(sum(queue) / len(queue), 3) if queue else None
        ),
        "tenants_per_sec": (rollup or {}).get("tenants_per_sec"),
        "retraces": (rollup or {}).get("retraces"),
        # v12 rollup honesty: dispatches whose samples aged out of the
        # windowed percentile deques (the merged histograms kept them);
        # None on pre-v12 logs — the line simply omits it
        "window_dropped": (rollup or {}).get("window_dropped"),
        # the v9 fast-path fields (None on v8-era logs — the line simply
        # omits them)
        "ingest": (rollup or {}).get("ingest"),
        "h2d_bytes_per_dispatch": (
            (rollup or {}).get("h2d_bytes_per_dispatch")
        ),
        "cache_hit_rate": (rollup or {}).get("cache_hit_rate"),
        "per_bucket": per_bucket,
    }
    return out


def _slo_summary(records: List[dict]) -> Optional[Dict[str, Any]]:
    """Condense the SLO surface (schema v12): deadline-miss totals and a
    per-replica breakdown recomputed from the per-request ``deadline``
    records, plus the end-of-run ``slo`` record's target and worst
    burn-rate window. A log with deadline records but no ``slo`` record
    (killed mid-run) still reports its counts; a log with neither —
    every pre-v12 log — returns None and the line simply doesn't
    render. Malformed fields are skipped, never a crash."""
    dl = [
        r for r in records
        if r.get("kind") == "serving" and r.get("event") == "deadline"
    ]
    pinned = next(
        (r for r in reversed(records) if r.get("kind") == "slo"), None
    )
    if not dl and pinned is None:
        return None
    requests = len(dl)
    missed = sum(1 for r in dl if r.get("missed") is True)
    per_replica: Dict[str, Dict[str, int]] = {}
    for r in dl:
        rid = r.get("replica_id")
        label = (
            str(rid)
            if isinstance(rid, int) and not isinstance(rid, bool)
            else "-"
        )
        row = per_replica.setdefault(label, {"requests": 0, "missed": 0})
        row["requests"] += 1
        if r.get("missed") is True:
            row["missed"] += 1
    if not dl and pinned is not None:
        # summary-only log (deadline records rotated away): fall back to
        # the pinned totals, guarded — a malformed record yields zeros
        if isinstance(pinned.get("requests"), int) and not isinstance(
            pinned.get("requests"), bool
        ):
            requests = pinned["requests"]
        if isinstance(pinned.get("missed"), int) and not isinstance(
            pinned.get("missed"), bool
        ):
            missed = pinned["missed"]
        if isinstance(pinned.get("per_replica"), dict):
            per_replica = {
                str(k): {
                    "requests": int(v.get("requests", 0)),
                    "missed": int(v.get("missed", 0)),
                }
                for k, v in pinned["per_replica"].items()
                if isinstance(v, dict)
            }

    def _num(key: str) -> Optional[float]:
        v = (pinned or {}).get(key)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            return float(v)
        return None

    return {
        "requests": requests,
        "missed": missed,
        "miss_rate": (
            round(missed / requests, 6) if requests else None
        ),
        "per_replica": per_replica,
        "target_ms": _num("target_ms"),
        "worst_burn_rate": _num("worst_burn_rate"),
        "worst_burn_window_s": _num("worst_burn_window_s"),
    }


def _fleet_summary(records: List[dict]) -> Optional[Dict[str, Any]]:
    """Condense the ``gateway`` records (schema v13, serving/gateway.py):
    host membership and admitted/shed totals from the LAST fleet rollup
    record, shed counts by reason recounted from the per-request ``shed``
    records, and every ``rehome`` event (which host tripped, why, how
    many in-flight requests it stranded). Returns None when the log has
    no gateway records at all — every pre-v13 log — so the line simply
    doesn't render; malformed fields are skipped, never a crash."""
    gw = [r for r in records if r.get("kind") == "gateway"]
    if not gw:
        return None

    def _count(v: Any) -> int:
        return v if isinstance(v, int) and not isinstance(v, bool) else 0

    rollup = next(
        (r for r in reversed(gw) if r.get("event") == "rollup"), None
    )
    shed_by_reason: Dict[str, int] = {}
    for r in gw:
        if r.get("event") == "shed":
            reason = str(r.get("reason", "?"))
            shed_by_reason[reason] = shed_by_reason.get(reason, 0) + 1
    # the rollup's shed counters are authoritative (per-request shed
    # records may be absent at low telemetry levels); recounted records
    # fill in when no rollup landed (gateway killed mid-run)
    pinned_shed = (rollup or {}).get("shed")
    if isinstance(pinned_shed, dict):
        shed = {str(k): _count(v) for k, v in pinned_shed.items()}
    else:
        shed = shed_by_reason
    rehomes = [r for r in gw if r.get("event") == "rehome"]
    tripped = (rollup or {}).get("tripped_hosts")
    return {
        "hosts": (rollup or {}).get("hosts"),
        "ready_hosts": (rollup or {}).get("ready_hosts"),
        "tripped_hosts": tripped if isinstance(tripped, list) else [],
        "admitted": (rollup or {}).get("admitted"),
        "shed": shed,
        "shed_total": sum(shed.values()),
        "rehomes": len(rehomes) or _count((rollup or {}).get("rehomes")),
        "rehomed_hosts": [
            {k: r.get(k) for k in ("host", "cause", "in_flight")}
            for r in rehomes
        ],
        "adapt_ms_p99": (rollup or {}).get("adapt_ms_p99"),
    }


def _dispatch_stats(records: List[dict]) -> Optional[Dict[str, float]]:
    """Step-time stats averaged over the run's ``dispatch`` records (the
    per-epoch StepTimer summaries: mean/p50/p95/p99 dispatch latency)."""
    return _mean_of(records, "dispatch", (
        "train_step_time_ms", "train_step_time_p50_ms",
        "train_step_time_p95_ms", "train_step_time_p99_ms",
        "train_iters_per_sec",
    )) or None


def _overlap_stats(records: List[dict]) -> Optional[Dict[str, Any]]:
    """Epoch-boundary overlap utilization from the ``dispatch`` records
    (schema v7): mean/total overlapped milliseconds (train-summary host
    work hidden under the in-flight fused eval tail), total skipped
    phase-transition lag blocks, and the run's accumulation setting (the
    last record wins — it is a config constant within a run). None when
    no dispatch record carries the v7 fields (an older log)."""
    disp = [r for r in records if r.get("kind") == "dispatch"]
    overlaps = [
        r["overlap_ms"] for r in disp
        if isinstance(r.get("overlap_ms"), (int, float))
        and not isinstance(r.get("overlap_ms"), bool)
        and math.isfinite(r["overlap_ms"])
    ]
    boundary = [
        r["boundary_overlaps"] for r in disp
        if isinstance(r.get("boundary_overlaps"), int)
        and not isinstance(r.get("boundary_overlaps"), bool)
    ]
    accum = next(
        (
            r["accum_steps"] for r in reversed(disp)
            if isinstance(r.get("accum_steps"), int)
            and not isinstance(r.get("accum_steps"), bool)
        ),
        None,
    )
    if not overlaps and not boundary and accum is None:
        return None
    return {
        "overlap_ms_mean": (
            sum(overlaps) / len(overlaps) if overlaps else None
        ),
        "overlap_ms_total": sum(overlaps) if overlaps else None,
        "boundary_overlaps_total": sum(boundary) if boundary else 0,
        "accum_steps": accum,
    }


def _stream_stats(records: List[dict]) -> Optional[Dict[str, float]]:
    return _mean_of(records, "stream", (
        "assembly_ms_per_batch", "stall_ms_per_batch", "queue_depth_mean",
    )) or None


def _memory_stats(records: List[dict]) -> Optional[Dict[str, Any]]:
    """The LAST ``device_memory`` record's HBM numbers (current state
    matters more than history for leak triage)."""
    mem = [r for r in records if r.get("kind") == "device_memory"]
    if not mem:
        return None
    last = mem[-1]
    return {
        k: last[k]
        for k in ("epoch", "bytes_in_use", "peak_bytes_in_use",
                  "bytes_limit", "store_bytes_expected")
        if k in last
    }


# -- summary ----------------------------------------------------------------


def cmd_summary(args) -> int:
    records = _load(args.log)
    counts: Dict[str, int] = {}
    for r in records:
        counts[r.get("kind", "?")] = counts.get(r.get("kind", "?"), 0) + 1
    epochs = _epoch_scalars(records)
    run_start = next((r for r in records if r.get("kind") == "run_start"), None)
    val_acc = {
        e: s["val_accuracy_mean"]
        for e, s in epochs.items() if "val_accuracy_mean" in s
    }
    best = max(val_acc.items(), key=lambda kv: kv[1]) if val_acc else None
    final = max(val_acc) if val_acc else None
    span = _fmt_ts_span(records)
    payload: Dict[str, Any] = {
        "log": args.log,
        "records": len(records),
        "counts_by_kind": counts,
        "experiment_name": (run_start or {}).get("experiment_name"),
        "telemetry_level": (run_start or {}).get("telemetry_level"),
        "epochs": sorted(epochs) and [min(epochs), max(epochs)] or None,
        "wall_clock_seconds": round(span, 3) if span is not None else None,
        "final_val_accuracy": val_acc.get(final) if final is not None else None,
        "best_val_accuracy": best[1] if best else None,
        "best_val_epoch": best[0] if best else None,
        "dispatch_timing": _dispatch_stats(records),
        # epoch-boundary overlap utilization (schema v7 dispatch fields)
        "overlap": _overlap_stats(records),
        "stream": _stream_stats(records),
        "device_memory": _memory_stats(records),
        "anomalies": counts.get("anomaly", 0),
        "incidents": counts.get("incident", 0),
        "watchdog_stalls": counts.get("watchdog_stall", 0),
        # resilience (schema v3): how many transient I/O faults the run
        # retried through, and whether it exited on a preemption drain
        "retries": counts.get("retry", 0),
        "preemptions": counts.get("preemption", 0),
        # static analysis (schema v4): mid-run recompiles the retrace
        # detector caught — every one is 20-40s of TPU compile the shape
        # discipline should have prevented
        "retraces": counts.get("retrace", 0),
        # build-time program audit summary (schema v5): the last
        # `analysis` record — program/violation counts, the SPMD audit
        # mesh and the flagship roofline prediction
        "audit": next(
            (
                {
                    k: r.get(k)
                    for k in ("programs", "violations", "mesh", "roofline")
                }
                for r in reversed(records)
                if r.get("kind") == "analysis"
            ),
            None,
        ),
        # elastic multi-host coordination (schema v6): drain protocol
        # progress + the last topology-change resume marker
        "elastic": _elastic_summary(records),
        # adapt-on-request serving (schema v8): dispatch/tenant counts,
        # adapt-latency percentiles, throughput, strict-retrace count
        "serving": _serving_summary(records),
        # deadline/SLO accounting (schema v12): miss totals recomputed
        # from the per-request deadline records + the end-of-run slo
        # record's burn-rate verdict
        "slo": _slo_summary(records),
        # fleet gateway (schema v13): host membership, admitted/shed
        # totals, re-home events — absent, never a crash, on older logs
        "fleet": _fleet_summary(records),
        "clean_shutdown": counts.get("run_end", 0) > 0,
    }
    lines = [
        f"{args.log}: {len(records)} records"
        + (f" over {span:.1f}s" if span is not None else ""),
        "  kinds: " + ", ".join(
            f"{k}={v}" for k, v in sorted(counts.items())
        ),
    ]
    if run_start:
        lines.append(
            f"  run: {run_start.get('experiment_name')!r} "
            f"(telemetry_level={run_start.get('telemetry_level')}, "
            f"resume_iter={run_start.get('resume_iter')})"
        )
    if epochs:
        lines.append(f"  epochs: {min(epochs)}..{max(epochs)}")
    if best:
        lines.append(
            f"  val accuracy: best {best[1]:.4f} @ epoch {best[0]}, "
            f"final {val_acc[final]:.4f} @ epoch {final}"
        )
    disp = payload["dispatch_timing"]
    if disp:
        parts = [f"mean {disp['train_step_time_ms']:.1f}ms"] if (
            "train_step_time_ms" in disp
        ) else []
        for q in ("p50", "p95", "p99"):
            key = f"train_step_time_{q}_ms"
            if key in disp:
                parts.append(f"{q} {disp[key]:.1f}ms")
        lines.append("  dispatch: " + ", ".join(parts))
    ov = payload["overlap"]
    if ov:
        parts = []
        if ov.get("overlap_ms_mean") is not None:
            parts.append(
                f"boundary overlap {ov['overlap_ms_mean']:.1f}ms/epoch "
                f"({ov['overlap_ms_total']:.1f}ms total hidden)"
            )
        parts.append(
            f"{ov.get('boundary_overlaps_total', 0)} phase-transition "
            "block(s) skipped"
        )
        if ov.get("accum_steps") is not None:
            parts.append(f"accum_steps={ov['accum_steps']}")
        lines.append("  overlap: " + ", ".join(parts))
    stream = payload["stream"]
    if stream:
        lines.append(
            "  stream: "
            + ", ".join(f"{k}={v:.2f}" for k, v in stream.items())
        )
    mem = payload["device_memory"]
    if mem and "bytes_in_use" in mem:
        lines.append(
            f"  hbm: {mem['bytes_in_use'] / 2**20:.1f} MiB in use"
            + (
                f" (peak {mem['peak_bytes_in_use'] / 2**20:.1f} MiB)"
                if "peak_bytes_in_use" in mem else ""
            )
            + f", stores expect {mem.get('store_bytes_expected', 0) / 2**20:.1f} MiB"
        )
    health = (
        f"  health: {payload['anomalies']} anomalies, "
        f"{payload['incidents']} incidents, "
        f"{payload['watchdog_stalls']} watchdog stalls"
    )
    if not payload["clean_shutdown"]:
        health += "  [no run_end marker: crashed, killed, or still running]"
    lines.append(health)
    if payload["retries"] or payload["preemptions"]:
        lines.append(
            f"  resilience: {payload['retries']} I/O retries, "
            f"{payload['preemptions']} preemption exits"
        )
    if payload["retraces"]:
        lines.append(
            f"  analysis: {payload['retraces']} mid-run retrace(s) — "
            "dispatch sites recompiled (see the anomalies timeline)"
        )
    el = payload["elastic"]
    if el:
        line = (
            f"  elastic: {el['drain_requests']} drain request(s), "
            f"{el['drain_commits']} commit(s), {el['drain_acks']} ack(s), "
            f"{el['resumes']} elastic resume(s)"
        )
        lr = el.get("last_resume")
        if lr and lr.get("old_process_count") is not None:
            line += (
                f"; last resume {lr['old_process_count']} -> "
                f"{lr['new_process_count']} process(es) @ iter "
                f"{lr.get('iter')} (episode cursor "
                f"{lr.get('episode_cursor')})"
            )
        lines.append(line)
    sv = payload["serving"]
    if sv:
        parts = [
            f"{sv['dispatches']} dispatch(es), {sv['tenants']} tenant(s)"
        ]
        if sv.get("tenants_per_dispatch_mean") is not None:
            parts.append(
                f"{sv['tenants_per_dispatch_mean']:.2f} tenants/dispatch"
            )
        if sv.get("adapt_ms_p50") is not None:
            line = f"adapt p50 {sv['adapt_ms_p50']:.2f}ms"
            if sv.get("adapt_ms_p95") is not None:
                line += f" p95 {sv['adapt_ms_p95']:.2f}ms"
            parts.append(line)
        if sv.get("queue_ms_mean") is not None:
            parts.append(f"queue {sv['queue_ms_mean']:.2f}ms")
        if sv.get("tenants_per_sec") is not None:
            parts.append(f"{sv['tenants_per_sec']:.1f} tenants/s")
        if sv.get("ingest") is not None:
            parts.append(f"ingest {sv['ingest']}")
        if sv.get("h2d_bytes_per_dispatch") is not None:
            parts.append(f"{sv['h2d_bytes_per_dispatch']:.0f} B/dispatch")
        if sv.get("cache_hit_rate") is not None:
            parts.append(f"cache hit {sv['cache_hit_rate']:.0%}")
        if sv.get("per_replica"):
            parts.append(f"{len(sv['per_replica'])} replica(s)")
        if sv.get("rollovers"):
            parts.append(f"{sv['rollovers']} rollover(s)")
        if sv.get("window_dropped") is not None:
            parts.append(
                f"{sv['window_dropped']} aged out of percentile window"
            )
        if sv.get("retraces"):
            parts.append(f"{sv['retraces']} RETRACE(S)")
        lines.append("  serving: " + ", ".join(parts))
        # the per-replica grain (schema v11, multi-replica pools): how
        # evenly the affinity router spread traffic + per-replica cache
        # locality; absent on single-engine and pre-v11 logs
        for rid, row in (sv.get("per_replica") or {}).items():
            sub = [
                f"{row['dispatches']} dispatch(es)",
                f"{row['tenants']} tenant(s)",
            ]
            if row.get("adapt_ms_p50") is not None:
                sub.append(f"p50 {row['adapt_ms_p50']:.2f}ms")
            if row.get("cache_hit_rate") is not None:
                sub.append(f"cache hit {row['cache_hit_rate']:.0%}")
            lines.append(f"    serving[replica {rid}]: " + ", ".join(sub))
        # the per-(program, bucket, shots) grain: one line per compiled
        # dispatch signature — where the aggregate p50 actually comes from
        for key, row in (sv.get("per_bucket") or {}).items():
            sub = [
                f"{row['dispatches']} dispatch(es)",
                f"{row['tenants']} tenant(s)",
            ]
            if row.get("adapt_ms_p50") is not None:
                part = f"p50 {row['adapt_ms_p50']:.2f}ms"
                if row.get("adapt_ms_p95") is not None:
                    part += f" p95 {row['adapt_ms_p95']:.2f}ms"
                sub.append(part)
            if row.get("cache_hit_rate") is not None:
                sub.append(f"cache hit {row['cache_hit_rate']:.0%}")
            lines.append(f"    serving[{key}]: " + ", ".join(sub))
    slo = payload["slo"]
    if slo:
        parts = [
            f"{slo['requests']} deadline(s), {slo['missed']} missed"
        ]
        if slo.get("miss_rate") is not None:
            parts.append(f"miss rate {slo['miss_rate']:.2%}")
        if slo.get("target_ms") is not None:
            parts.append(f"target {slo['target_ms']:g}ms")
        if (
            slo.get("worst_burn_rate") is not None
            and slo.get("worst_burn_window_s") is not None
        ):
            parts.append(
                f"worst burn {slo['worst_burn_rate']:.2f} over "
                f"{slo['worst_burn_window_s']:g}s"
            )
        lines.append("  slo: " + ", ".join(parts))
        for label, row in sorted((slo.get("per_replica") or {}).items()):
            lines.append(
                f"    slo[replica {label}]: {row['requests']} "
                f"deadline(s), {row['missed']} missed"
            )
    fl = payload["fleet"]
    if fl:
        parts = []
        if fl.get("hosts") is not None:
            part = f"{fl['hosts']} host(s)"
            if fl.get("ready_hosts") is not None:
                part += f" ({fl['ready_hosts']} ready)"
            parts.append(part)
        if fl.get("admitted") is not None:
            parts.append(f"{fl['admitted']} admitted")
        shed_parts = ", ".join(
            f"{n} {reason}" for reason, n in sorted(fl["shed"].items())
            if n
        )
        parts.append(
            f"{fl['shed_total']} shed"
            + (f" ({shed_parts})" if shed_parts else "")
        )
        parts.append(f"{fl['rehomes']} re-home(s)")
        if isinstance(fl.get("adapt_ms_p99"), (int, float)):
            parts.append(f"adapt p99 {fl['adapt_ms_p99']:.2f}ms")
        lines.append("  fleet: " + ", ".join(parts))
        if fl["tripped_hosts"]:
            lines.append(
                "    fleet[tripped]: "
                + ", ".join(str(h) for h in fl["tripped_hosts"])
            )
        for row in fl["rehomed_hosts"]:
            lines.append(
                f"    fleet[rehome]: {row.get('host')} "
                f"({row.get('in_flight')} in flight): {row.get('cause')}"
            )
    audit = payload["audit"]
    if audit:
        line = (
            f"  audit: {audit.get('programs')} program(s), "
            f"{audit.get('violations')} violation(s)"
        )
        if audit.get("mesh"):
            line += f" on mesh {audit['mesh']}"
        roof = audit.get("roofline") or {}
        if roof.get("bound"):
            line += (
                f"; roofline[{roof.get('program')}]: "
                f"{roof['bound']}-bound"
            )
            if roof.get("predicted_mfu") is not None:
                line += f", predicted mfu {roof['predicted_mfu']}"
            elif roof.get("predicted_hfu") is not None:
                line += f", predicted hfu {roof['predicted_hfu']}"
        lines.append(line)
    _emit(payload, args.json, lines)
    return 0


# -- epochs -----------------------------------------------------------------

#: columns the `epochs` table shows by default (when present in the log)
DEFAULT_EPOCH_COLUMNS = (
    "train_loss_mean", "train_accuracy_mean",
    "val_loss_mean", "val_accuracy_mean", "train_step_time_ms",
)


def cmd_epochs(args) -> int:
    epochs = _epoch_scalars(_load(args.log))
    if not epochs:
        _emit({"log": args.log, "epochs": {}}, args.json, ["no epoch records"])
        return 0
    cols = tuple(args.column) if args.column else tuple(
        c for c in DEFAULT_EPOCH_COLUMNS
        if any(c in s for s in epochs.values())
    )
    payload = {
        "log": args.log,
        "columns": list(cols),
        "epochs": {
            str(e): {c: epochs[e].get(c) for c in cols}
            for e in sorted(epochs)
        },
    }
    width = max(12, *(len(c) for c in cols)) if cols else 12
    lines = ["epoch  " + "  ".join(c.rjust(width) for c in cols)]
    for e in sorted(epochs):
        cells = []
        for c in cols:
            v = epochs[e].get(c)
            cells.append(
                (f"{v:.4f}" if isinstance(v, float) else str(v)).rjust(width)
            )
        lines.append(f"{e:>5}  " + "  ".join(cells))
    _emit(payload, args.json, lines)
    return 0


# -- anomalies --------------------------------------------------------------


def cmd_anomalies(args) -> int:
    records = [r for r in _load(args.log) if r.get("kind") in ANOMALY_KINDS]
    lines = []
    for r in records:
        kind = r["kind"]
        # a newer-schema record may omit fields we print (forward-compat:
        # the reader renders what it recognizes, never crashes) — str() the
        # iter rather than assume an int is present
        it = str(r.get("iter", "?"))
        if kind == "anomaly":
            lines.append(
                f"anomaly   iter {it:>8}  {r.get('reason')}"
                f"  value={r.get('value')}  threshold={r.get('threshold')}"
            )
        elif kind == "incident":
            lines.append(
                f"incident  iter {it:>8}  {r.get('reason')}"
                f"  -> {r.get('path')}"
            )
        elif kind == "preemption":
            lines.append(
                f"preempt   iter {it:>8}  signal {r.get('signal')}"
                f"  -> {r.get('checkpoint')}"
            )
        elif kind == "retrace":
            lines.append(
                f"retrace   iter {it:>8}  {r.get('site')}"
                f"  sig={r.get('signature')}"
                f"  n={r.get('n_signatures')}"
            )
        else:
            lines.append(
                f"stall     stage={r.get('stage')!r}  "
                f"{r.get('seconds_since_progress')}s without progress"
            )
    if not lines:
        lines = ["no anomalies, incidents, or watchdog stalls recorded"]
    _emit({"log": args.log, "events": records}, args.json, lines)
    return 0


# -- tail -------------------------------------------------------------------


def cmd_tail(args) -> int:
    if args.n <= 0:
        print(f"tail: -n must be positive, got {args.n}", file=sys.stderr)
        return 2
    records = _load(args.log)
    if args.kind:
        records = [r for r in records if r.get("kind") == args.kind]
    records = records[-args.n:]
    lines = [json.dumps(r, sort_keys=True) for r in records]
    if not lines:
        lines = [
            "no records"
            + (f" of kind {args.kind!r}" if args.kind else "")
        ]
    _emit({"log": args.log, "records": records}, args.json, lines)
    return 0


# -- diff -------------------------------------------------------------------


def _config_diff(a: List[dict], b: List[dict]) -> Optional[Dict[str, Any]]:
    """Changed config keys between the two runs' ``run_start`` snapshots
    (None when either log predates the snapshot field)."""
    ca = next((r.get("config") for r in a if r.get("kind") == "run_start"), None)
    cb = next((r.get("config") for r in b if r.get("kind") == "run_start"), None)
    if not isinstance(ca, dict) or not isinstance(cb, dict):
        return None
    changed = {
        k: {"a": ca.get(k), "b": cb.get(k)}
        for k in sorted(set(ca) | set(cb))
        if ca.get(k) != cb.get(k)
    }
    return changed


def _divergence_epoch(
    epochs_a: Dict[int, Dict[str, float]],
    epochs_b: Dict[int, Dict[str, float]],
    metrics: Tuple[str, ...],
    rtol: float,
    atol: float,
) -> Optional[Tuple[int, str, float, float]]:
    """First common epoch where a watched metric differs beyond
    ``atol + rtol * |a|`` -> (epoch, metric, value_a, value_b)."""
    for epoch in sorted(set(epochs_a) & set(epochs_b)):
        for metric in metrics:
            va = epochs_a[epoch].get(metric)
            vb = epochs_b[epoch].get(metric)
            if va is None or vb is None:
                continue
            if not (math.isfinite(va) and math.isfinite(vb)):
                if va != vb and not (
                    math.isnan(va) and math.isnan(vb)
                ):
                    return epoch, metric, va, vb
                continue
            if abs(va - vb) > atol + rtol * abs(va):
                return epoch, metric, va, vb
    return None


def cmd_diff(args) -> int:
    rec_a, rec_b = _load(args.log_a), _load(args.log_b)
    epochs_a, epochs_b = _epoch_scalars(rec_a), _epoch_scalars(rec_b)
    common = sorted(set(epochs_a) & set(epochs_b))
    watch = tuple(args.metric) if args.metric else DEFAULT_WATCH_METRICS
    deltas: Dict[str, Dict[str, float]] = {}
    if common:
        shared_keys = sorted(
            set.intersection(
                *(set(epochs_a[e]) & set(epochs_b[e]) for e in common)
            )
        )
        for key in shared_keys:
            dv = [epochs_a[e][key] - epochs_b[e][key] for e in common]
            finite = [d for d in dv if math.isfinite(d)]
            deltas[key] = {
                "max_abs_delta": max(abs(d) for d in finite) if finite else None,
                "final_delta": dv[-1] if math.isfinite(dv[-1]) else None,
                "nonfinite_epochs": sum(1 for d in dv if not math.isfinite(d)),
            }
    div = _divergence_epoch(epochs_a, epochs_b, watch, args.rtol, args.atol)
    cfg_diff = _config_diff(rec_a, rec_b)
    anomalies = {
        "a": sum(1 for r in rec_a if r.get("kind") == "anomaly"),
        "b": sum(1 for r in rec_b if r.get("kind") == "anomaly"),
    }
    payload = {
        "log_a": args.log_a,
        "log_b": args.log_b,
        "common_epochs": common and [common[0], common[-1]] or None,
        "watched_metrics": list(watch),
        "divergence": (
            {"epoch": div[0], "metric": div[1], "a": div[2], "b": div[3]}
            if div else None
        ),
        "scalar_deltas": deltas,
        "config_changes": cfg_diff,
        "anomaly_counts": anomalies,
    }
    lines = [f"diff {args.log_a} vs {args.log_b}"]
    if cfg_diff is None:
        lines.append("  config: no run_start snapshot in one of the logs")
    elif not cfg_diff:
        lines.append("  config: identical")
    else:
        lines.append(f"  config: {len(cfg_diff)} key(s) differ")
        for k, v in cfg_diff.items():
            lines.append(f"    {k}: {v['a']!r} -> {v['b']!r}")
    if not common:
        lines.append("  no common epochs to compare")
    else:
        lines.append(f"  common epochs: {common[0]}..{common[-1]}")
        if div:
            lines.append(
                f"  DIVERGED at epoch {div[0]} on {div[1]}: "
                f"{div[2]:.6g} vs {div[3]:.6g}"
            )
        else:
            lines.append(
                "  watched metrics agree within tolerance "
                f"(rtol={args.rtol}, atol={args.atol}): "
                + ", ".join(watch)
            )
        ranked = sorted(
            (
                (k, d) for k, d in deltas.items()
                if d["max_abs_delta"] is not None
            ),
            key=lambda kd: -kd[1]["max_abs_delta"],
        )[:args.top]
        for k, d in ranked:
            lines.append(
                f"    {k}: max|Δ|={d['max_abs_delta']:.6g} "
                f"finalΔ={d['final_delta'] if d['final_delta'] is not None else 'nan'}"
            )
    if anomalies["a"] or anomalies["b"]:
        lines.append(
            f"  anomalies: {anomalies['a']} (a) vs {anomalies['b']} (b)"
        )
    _emit(payload, args.json, lines)
    return 1 if (div and args.fail_on_divergence) else 0


# -- validate ---------------------------------------------------------------


def cmd_validate(args) -> int:
    try:
        n = validate_file(args.log)
    except (ValueError, OSError) as e:
        print(f"INVALID: {e}", file=sys.stderr)
        return 1
    print(f"{args.log}: {n} records, all schema-valid")
    return 0


# -- entry ------------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="telemetry_cli",
        description="Inspect / diff telemetry JSONL logs "
                    "(logs/telemetry.jsonl)",
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    def add(name, fn, **kwargs):
        sp = sub.add_parser(name, **kwargs)
        sp.set_defaults(fn=fn)
        sp.add_argument("--json", action="store_true",
                        help="machine-readable JSON output")
        return sp

    sp = add("summary", cmd_summary, help="run overview")
    sp.add_argument("log")
    sp = add("epochs", cmd_epochs, help="per-epoch scalar table")
    sp.add_argument("log")
    sp.add_argument("--column", action="append", default=None,
                    help="scalar column to show (repeatable; default: "
                         "loss/accuracy/step-time columns present)")
    sp = add("anomalies", cmd_anomalies,
             help="anomaly / incident / watchdog_stall records")
    sp.add_argument("log")
    sp = add("tail", cmd_tail, help="last N records")
    sp.add_argument("log")
    sp.add_argument("-n", type=int, default=10)
    sp.add_argument("--kind", default=None,
                    help="only records of this kind")
    sp = add("diff", cmd_diff, help="compare two runs' logs")
    sp.add_argument("log_a")
    sp.add_argument("log_b")
    sp.add_argument("--metric", action="append", default=None,
                    help="watched metric for the divergence epoch "
                         "(repeatable; default: "
                         + ", ".join(DEFAULT_WATCH_METRICS) + ")")
    sp.add_argument("--rtol", type=float, default=1e-3)
    sp.add_argument("--atol", type=float, default=1e-6)
    sp.add_argument("--top", type=int, default=8,
                    help="largest-delta metrics to print")
    sp.add_argument("--fail-on-divergence", action="store_true",
                    help="exit 1 when a watched metric diverges")
    sp = add("validate", cmd_validate, help="schema-validate every record")
    sp.add_argument("log")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        return args.fn(args)
    except FileNotFoundError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    except ValueError as e:  # iter_records on a non-JSONL file
        print(f"error: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
