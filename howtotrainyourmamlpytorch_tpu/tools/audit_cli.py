"""``cli audit`` — run the program-contract auditor from the command line.

Audits the canonical program family (the four donating train-step jits,
the fused eval multi-step, the device-pipeline index expander — see
``analysis.auditor.audit_system_programs``) on the current backend and
reports per-program contract results. With ``--pin`` it re-pins the
``CONTRACTS.json`` op-census baseline from this run instead of comparing
against it — the re-pinning workflow after an *intentional* lowering
change (see README "Static analysis & program contracts").

With ``--mesh RxC`` the same family is compiled under a real hybrid
``(data, task)`` mesh (R data rows x C task columns) and verified against
the SPMD performance contracts instead (``analysis.spmd``): sharding
(batch over ``(data, task)``, state/stores replicated), the per-axis
collective census against the mesh-keyed ``program@backend@RxC`` baseline
entries, the static per-device HBM budget (``--hbm-budget-gb`` /
``cfg.hbm_budget_gb``), and the roofline model (``analysis.roofline``) —
whose per-program report the CLI prints, decomposing the predicted MFU
into its top opcode contributors. On a CPU host the devices are virtual:
``--mesh 1x8`` forces ``--xla_force_host_platform_device_count=8`` before
jax loads (harmless on real hardware — the flag only affects the host
platform).

.. code-block:: console

   python -m howtotrainyourmamlpytorch_tpu.cli audit
   python -m howtotrainyourmamlpytorch_tpu.cli audit --json
   python -m howtotrainyourmamlpytorch_tpu.cli audit --pin
   python -m howtotrainyourmamlpytorch_tpu.cli audit --mesh 1x8
   python -m howtotrainyourmamlpytorch_tpu.cli audit --mesh 2x4 --pin
   python -m howtotrainyourmamlpytorch_tpu.cli audit --config cfg.json \
       --mesh 1x8 --hbm-budget-gb 16

Without ``--config`` the audit runs the pinned *audit config* (a small
deterministic MAML++ config with every mechanism on — the one the
baseline is fingerprinted against). A custom ``--config`` audits that
config's programs against the invariant contracts only: the census
baseline is fingerprint-guarded, so shapes from another config can never
produce phantom regressions.

Exit code: 0 when every contract holds (or after a successful ``--pin``),
1 when any program violated a contract, 2 on usage errors.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
from typing import List, Optional


def audit_config():
    """The pinned audit config: small, deterministic, every MAML++
    mechanism on (second order, MSL, LSLR, per-step BN), so the audited
    programs exercise the same structure as the flagship step while
    compiling in seconds on any backend."""
    from ..config import MAMLConfig

    return MAMLConfig(
        dataset_name="omniglot_dataset",
        image_height=14,
        image_width=14,
        image_channels=1,
        num_classes_per_set=4,
        num_samples_per_class=1,
        num_target_samples=2,
        batch_size=4,
        cnn_num_filters=6,
        num_stages=2,
        max_pooling=False,
        conv_padding=True,
        per_step_bn_statistics=True,
        learnable_per_layer_per_step_inner_loop_learning_rate=True,
        use_multi_step_loss_optimization=True,
        second_order=True,
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        multi_step_loss_num_epochs=3,
        total_epochs=5,
        total_iter_per_epoch=4,
        use_remat=False,
    )


def _report_payload(r) -> dict:
    payload = {
        "ok": r.ok,
        "violations": [
            {"contract": v.contract, "detail": v.detail}
            for v in r.violations
        ],
        "census": r.census,
        "donation": r.donation,
    }
    for extra in ("mesh_spec", "collectives", "hbm", "roofline"):
        value = getattr(r, extra, None)
        if value is not None and value != "":
            payload[extra] = value
    return payload


def _print_roofline(roofline: dict) -> None:
    mfu = roofline.get("predicted_mfu")
    hfu = roofline.get("predicted_hfu")
    bound = roofline.get("bound")
    if bound is None:
        return
    print(
        f"       roofline: {bound}-bound, predicted hfu "
        f"{hfu if hfu is not None else '?'}"
        + (f", mfu {mfu}" if mfu is not None else "")
        + (
            f", flops/task {roofline['flops_per_task']:.3e}"
            if roofline.get("flops_per_task") else ""
        )
    )
    for c in roofline.get("top_contributors", [])[:3]:
        print(
            f"         {c['op']:<14s} {c['time_share']:>6.1%} of predicted "
            f"time ({c['bound']}-bound, {c['bytes']:.3g} B, "
            f"{c['flops']:.3g} flops)"
        )


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="audit",
        description="Statically verify the program contracts (donation, "
                    "no-transfer, dtype policy, op census — or, with "
                    "--mesh, the SPMD contracts: sharding, collective "
                    "census, HBM budget, roofline) on the jitted program "
                    "family",
    )
    parser.add_argument("--config", default=None,
                        help="experiment JSON to audit (default: the "
                             "pinned audit config)")
    parser.add_argument("--contracts", default=None,
                        help="baseline path (default: CONTRACTS.json at "
                             "the repo root)")
    parser.add_argument("--pin", action="store_true",
                        help="re-pin the census baseline from this run "
                             "instead of comparing against it")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable JSON output")
    parser.add_argument("--mesh", default=None, metavar="RxC",
                        help="audit under a hybrid (data, task) mesh of "
                             "R x C devices against the SPMD contracts "
                             "(e.g. 1x8)")
    parser.add_argument("--hbm-budget-gb", type=float, default=None,
                        help="static per-device HBM budget in GiB for the "
                             "--mesh audit (overrides cfg.hbm_budget_gb; "
                             "0 disables)")
    args = parser.parse_args(argv)

    mesh_shape = None
    if args.mesh is not None:
        from ..analysis.spmd import parse_mesh_spec

        try:
            mesh_shape = parse_mesh_spec(args.mesh)
        except ValueError as e:
            print(f"audit: {e}", file=sys.stderr)
            return 2
        # must happen BEFORE jax first loads: give the host platform
        # enough virtual devices for the requested mesh (no effect on a
        # backend whose real chips already exist)
        need = mesh_shape[0] * mesh_shape[1]
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={need}"
            ).strip()

    import jax

    from ..analysis import auditor as audit_lib
    from ..analysis import contracts as contracts_lib
    from ..config import MAMLConfig

    if args.config:
        cfg = MAMLConfig.from_json_file(args.config)
    else:
        cfg = audit_config()
    # the fingerprint guards the census compare against SHAPE drift; the
    # budget override is an audit knob that never changes the compiled
    # program, so it is passed to the auditor directly — folding it into
    # cfg before fingerprinting would silently disarm the compare (and
    # make --pin treat the on-disk baseline as foreign)
    fingerprint = contracts_lib.config_fingerprint(dataclasses.asdict(cfg))
    baseline_path = args.contracts or contracts_lib.default_baseline_path()
    baseline = None if args.pin else contracts_lib.load_baseline(baseline_path)
    if baseline is not None and not contracts_lib.baseline_comparable(
        baseline, jax_version=jax.__version__, config_fingerprint=fingerprint
    ):
        print(
            "audit: pinned baseline is not comparable to this run "
            f"(pinned jax={baseline.get('jax')} fingerprint="
            f"{baseline.get('config_fingerprint')}, current "
            f"jax={jax.__version__} fingerprint={fingerprint}); "
            "census regression check skipped — re-pin with --pin",
            file=sys.stderr,
        )

    mesh_spec = None
    if mesh_shape is not None:
        from ..analysis import spmd as spmd_lib

        try:
            mesh = spmd_lib.build_audit_mesh(*mesh_shape)
        except ValueError as e:
            print(f"audit: {e}", file=sys.stderr)
            return 2
        auditor = spmd_lib.SpmdAuditor(
            cfg, mesh, baseline=baseline, config_fingerprint=fingerprint,
            hbm_budget_gb=args.hbm_budget_gb,
        )
        mesh_spec = auditor.mesh_spec
        reports = spmd_lib.audit_spmd_programs(cfg, mesh=mesh, auditor=auditor)
    else:
        auditor = audit_lib.ProgramAuditor(
            cfg, baseline=baseline, config_fingerprint=fingerprint
        )
        reports = audit_lib.audit_system_programs(cfg, auditor=auditor)
    violations = [v for r in reports for v in r.violations]

    if args.pin:
        data = contracts_lib.save_baseline(
            baseline_path,
            jax_version=jax.__version__,
            backend=jax.default_backend(),
            config_fingerprint=fingerprint,
            reports=reports,
            mesh_spec=mesh_spec,
        )
        print(
            f"audit: pinned {len(reports)} program census(es) "
            + (f"for mesh {mesh_spec} " if mesh_spec else "")
            + f"to {baseline_path} ({len(data['programs'])} entries total, "
            f"jax {jax.__version__}, backend {jax.default_backend()})",
            file=sys.stderr,
        )

    if args.json:
        print(json.dumps(
            {
                "backend": jax.default_backend(),
                "jax": jax.__version__,
                "config_fingerprint": fingerprint,
                "mesh": mesh_spec,
                "programs": {
                    r.program: _report_payload(r) for r in reports
                },
            },
            indent=2,
            sort_keys=True,
        ))
    else:
        for r in reports:
            status = "ok" if r.ok else "FAIL"
            alias = (r.donation or {}).get("alias_size_bytes")
            extra = f"  alias={alias}B" if alias is not None else ""
            hbm = getattr(r, "hbm", None)
            if hbm and "peak_bytes" in hbm:
                extra += f"  hbm_peak={hbm['peak_bytes'] / 2**30:.4f}GiB"
            colls = getattr(r, "collectives", None)
            if colls:
                parts = [
                    f"{op}@{axis}x{stats['count']}"
                    for op, by_axis in sorted(colls.items())
                    for axis, stats in sorted(by_axis.items())
                ]
                extra += "  coll=" + ",".join(parts)
            print(f"{status:4s} {r.program}{extra}")
            for v in r.violations:
                print(f"     {v}")
            roofline = getattr(r, "roofline", None)
            if roofline:
                _print_roofline(roofline)
        print(
            f"audit: {len(reports)} program(s)"
            + (f" on mesh {mesh_spec}" if mesh_spec else "")
            + f", {len(violations)} contract violation(s)",
            file=sys.stderr,
        )
    if args.pin:
        return 0
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
