"""``cli audit`` — run the program-contract auditor from the command line.

Audits the canonical program family (the four donating train-step jits,
the fused eval multi-step, the device-pipeline index expander — see
``analysis.auditor.audit_system_programs``) on the current backend and
reports per-program contract results. With ``--pin`` it re-pins the
``CONTRACTS.json`` op-census baseline from this run instead of comparing
against it — the re-pinning workflow after an *intentional* lowering
change (see README "Static analysis & program contracts").

.. code-block:: console

   python -m howtotrainyourmamlpytorch_tpu.cli audit
   python -m howtotrainyourmamlpytorch_tpu.cli audit --json
   python -m howtotrainyourmamlpytorch_tpu.cli audit --pin
   python -m howtotrainyourmamlpytorch_tpu.cli audit --config cfg.json

Without ``--config`` the audit runs the pinned *audit config* (a small
deterministic MAML++ config with every mechanism on — the one the
baseline is fingerprinted against). A custom ``--config`` audits that
config's programs against the invariant contracts only: the census
baseline is fingerprint-guarded, so shapes from another config can never
produce phantom regressions.

Exit code: 0 when every contract holds (or after a successful ``--pin``),
1 when any program violated a contract, 2 on usage errors.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
from typing import List, Optional


def audit_config():
    """The pinned audit config: small, deterministic, every MAML++
    mechanism on (second order, MSL, LSLR, per-step BN), so the audited
    programs exercise the same structure as the flagship step while
    compiling in seconds on any backend."""
    from ..config import MAMLConfig

    return MAMLConfig(
        dataset_name="omniglot_dataset",
        image_height=14,
        image_width=14,
        image_channels=1,
        num_classes_per_set=4,
        num_samples_per_class=1,
        num_target_samples=2,
        batch_size=4,
        cnn_num_filters=6,
        num_stages=2,
        max_pooling=False,
        conv_padding=True,
        per_step_bn_statistics=True,
        learnable_per_layer_per_step_inner_loop_learning_rate=True,
        use_multi_step_loss_optimization=True,
        second_order=True,
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        multi_step_loss_num_epochs=3,
        total_epochs=5,
        total_iter_per_epoch=4,
        use_remat=False,
    )


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="audit",
        description="Statically verify the program contracts (donation, "
                    "no-transfer, dtype policy, op census) on the jitted "
                    "program family",
    )
    parser.add_argument("--config", default=None,
                        help="experiment JSON to audit (default: the "
                             "pinned audit config)")
    parser.add_argument("--contracts", default=None,
                        help="baseline path (default: CONTRACTS.json at "
                             "the repo root)")
    parser.add_argument("--pin", action="store_true",
                        help="re-pin the op-census baseline from this run "
                             "instead of comparing against it")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable JSON output")
    args = parser.parse_args(argv)

    import jax

    from ..analysis import auditor as audit_lib
    from ..analysis import contracts as contracts_lib
    from ..config import MAMLConfig

    if args.config:
        cfg = MAMLConfig.from_json_file(args.config)
    else:
        cfg = audit_config()
    fingerprint = contracts_lib.config_fingerprint(dataclasses.asdict(cfg))
    baseline_path = args.contracts or contracts_lib.default_baseline_path()
    baseline = None if args.pin else contracts_lib.load_baseline(baseline_path)
    if baseline is not None and not contracts_lib.baseline_comparable(
        baseline, jax_version=jax.__version__, config_fingerprint=fingerprint
    ):
        print(
            "audit: pinned baseline is not comparable to this run "
            f"(pinned jax={baseline.get('jax')} fingerprint="
            f"{baseline.get('config_fingerprint')}, current "
            f"jax={jax.__version__} fingerprint={fingerprint}); "
            "op-census regression check skipped — re-pin with --pin",
            file=sys.stderr,
        )
    auditor = audit_lib.ProgramAuditor(
        cfg, baseline=baseline, config_fingerprint=fingerprint
    )
    reports = audit_lib.audit_system_programs(cfg, auditor=auditor)
    violations = [v for r in reports for v in r.violations]

    if args.pin:
        data = contracts_lib.save_baseline(
            baseline_path,
            jax_version=jax.__version__,
            backend=jax.default_backend(),
            config_fingerprint=fingerprint,
            reports=reports,
        )
        print(
            f"audit: pinned {len(data['programs'])} program census(es) to "
            f"{baseline_path} (jax {jax.__version__}, backend "
            f"{jax.default_backend()})",
            file=sys.stderr,
        )

    if args.json:
        print(json.dumps(
            {
                "backend": jax.default_backend(),
                "jax": jax.__version__,
                "config_fingerprint": fingerprint,
                "programs": {
                    r.program: {
                        "ok": r.ok,
                        "violations": [
                            {"contract": v.contract, "detail": v.detail}
                            for v in r.violations
                        ],
                        "census": r.census,
                        "donation": r.donation,
                    }
                    for r in reports
                },
            },
            indent=2,
            sort_keys=True,
        ))
    else:
        for r in reports:
            status = "ok" if r.ok else "FAIL"
            alias = (r.donation or {}).get("alias_size_bytes")
            extra = f"  alias={alias}B" if alias is not None else ""
            print(f"{status:4s} {r.program}{extra}")
            for v in r.violations:
                print(f"     {v}")
        print(
            f"audit: {len(reports)} program(s), {len(violations)} "
            f"contract violation(s)",
            file=sys.stderr,
        )
    if args.pin:
        return 0
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
