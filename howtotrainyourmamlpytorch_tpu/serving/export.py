"""AOT export artifacts for the serving engine (``cli serve-export``).

``ServingEngine.warmup()`` is the whole compile bill of a serving
replica: every (bucket, shots) program — multi-second XLA compiles on a
TPU, per process, per restart. The persistent compilation cache already
amortizes the *XLA* half across processes, but the engine still pays the
trace/lower path and the cache is best-effort. This module makes cold
starts a **deserialize**: the warmed program ladder is serialized with
``jax.experimental.serialize_executable`` (the loaded-executable form of
``jax.export`` — the compiled artifact itself, not just StableHLO, which
is what makes a zero-XLA-compile warmup possible) into a versioned
artifact directory, and ``warmup()`` loads it back before falling back
to compile-then-save.

Artifact layout::

    <root>/<device_kind>-<dtype>-<config_fingerprint[:12]>/
        MANIFEST.json          # the compatibility key (see below)
        adapt_b2_s1.bin        # one serialized executable per program
        predict_b2.bin         # (cache-enabled engines only)

Compatibility is FINGERPRINTED, not assumed: the manifest records the
jax version, backend, device kind, compute dtype, the config
fingerprint (``analysis.contracts.config_fingerprint`` — any geometry or
lowering knob change invalidates), the ingest mode, the cache flag and
the (bucket, shots) ladder. ``load_artifacts`` returns None on ANY
mismatch — a stale or foreign artifact dir silently degrades to the
compile path, never to a wrong program. Executables are device-kind
specific by nature (the key encodes it); artifacts are local build
products like the XLA cache, not a portable interchange format (the
``.bin`` payload embeds pickled pytree metadata — load only artifact
dirs you wrote).
"""

from __future__ import annotations

import dataclasses
import json
import os
import pickle
from typing import Any, Callable, Dict, Optional

#: bump when the artifact layout or payload format changes
ARTIFACT_VERSION = 1

_compile_events = [0]
_listener_installed = [False]


def install_compile_counter() -> None:
    """Count XLA backend compiles process-wide (idempotent).

    Registers a ``jax.monitoring`` duration listener on the
    ``backend_compile`` event — the hook every XLA compile fires — so the
    engine can assert its warmup-from-artifacts path really performed
    zero compiles (the acceptance surface of the export tier).
    """
    if _listener_installed[0]:
        return
    import jax

    def _listener(event: str, duration: float, **kw: Any) -> None:
        if "backend_compile" in event:
            _compile_events[0] += 1

    jax.monitoring.register_event_duration_secs_listener(_listener)
    _listener_installed[0] = True


def xla_compile_count() -> int:
    """XLA backend compiles observed since ``install_compile_counter``."""
    return _compile_events[0]


def config_fingerprint(cfg) -> str:
    """The serving config's compatibility fingerprint (the same
    ``analysis.contracts`` digest the program-contract baseline pins)."""
    from ..analysis.contracts import config_fingerprint as fp

    return fp(dataclasses.asdict(cfg))


def artifact_dir_for(cfg, root: str, ingest: str = "f32",
                     cache: bool = False) -> str:
    """The versioned artifact subdirectory for this (device kind, dtype,
    config, ingest, cache-flag) point under ``root``. Ingest and the
    cache flag are ENGINE-level settings that select different program
    families without changing the config fingerprint, so they key the
    directory too — engines in different modes sharing one export root
    must coexist, not clobber each other's artifacts."""
    import jax

    device_kind = jax.devices()[0].device_kind.replace(" ", "_")
    suffix = f"-{ingest}" + ("-cache" if cache else "")
    return os.path.join(
        root,
        f"{device_kind}-{cfg.compute_dtype}-"
        f"{config_fingerprint(cfg)[:12]}{suffix}",
    )


def _manifest_expectation(cfg, ingest: str, cache: bool,
                          buckets, shots_buckets,
                          extra: Optional[Dict[str, Any]] = None
                          ) -> Dict[str, Any]:
    import jax

    out = {
        "artifact_version": ARTIFACT_VERSION,
        "jax": jax.__version__,
        "backend": jax.default_backend(),
        "device_kind": jax.devices()[0].device_kind,
        "compute_dtype": cfg.compute_dtype,
        "config_fingerprint": config_fingerprint(cfg),
        "ingest": ingest,
        "cache": bool(cache),
        "bucket_ladder": [int(b) for b in buckets],
        "shots_buckets": [int(s) for s in shots_buckets],
        # RESOLVED kernel-lowering knobs, not the raw config values: the
        # fingerprint above hashes 'auto', but 'auto' resolves through the
        # mutable tuning table (TUNING.json) at trace time — a `cli tune`
        # run that flips a winner changes the program an engine would
        # compile TODAY, so an artifact exported before the flip must
        # mismatch and fall back to compile, never load the stale lowering
        "conv_impl": cfg.resolved_conv_impl,
        "pad_channels": cfg.resolved_pad_channels,
        "pool_impl": cfg.resolved_pool_impl,
        "bn_stats_impl": cfg.resolved_bn_stats_impl,
        "im2col_hoist": cfg.resolved_im2col_hoist,
    }
    # ingest-specific compatibility keys (e.g. the index ingest's resident
    # store row count — baked into the gather program's shapes)
    out.update(extra or {})
    return out


def save_artifacts(
    cfg,
    root: str,
    ingest: str,
    cache: bool,
    buckets,
    shots_buckets,
    programs: Dict[str, Any],
    extra: Optional[Dict[str, Any]] = None,
) -> str:
    """Serialize every compiled program in ``programs`` (name ->
    ``jax.stages.Compiled``) under the versioned artifact dir; returns
    the dir. Writes are temp + ``os.replace`` (the repo's crash-safe
    file discipline), the manifest last — a killed export is rebuilt,
    never half-loaded."""
    from jax.experimental import serialize_executable

    out_dir = artifact_dir_for(cfg, root, ingest, cache)
    os.makedirs(out_dir, exist_ok=True)
    manifest = _manifest_expectation(
        cfg, ingest, cache, buckets, shots_buckets, extra
    )
    manifest["programs"] = {}
    for name, compiled in programs.items():
        payload, in_tree, out_tree = serialize_executable.serialize(compiled)
        fname = f"{name}.bin"
        path = os.path.join(out_dir, fname)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            pickle.dump((ARTIFACT_VERSION, payload, in_tree, out_tree), f)
        os.replace(tmp, path)
        manifest["programs"][name] = fname
    mpath = os.path.join(out_dir, "MANIFEST.json")
    mtmp = f"{mpath}.tmp.{os.getpid()}"
    with open(mtmp, "w") as f:
        json.dump(manifest, f, indent=2, sort_keys=True)
    os.replace(mtmp, mpath)
    return out_dir


def load_artifacts(
    cfg,
    root: str,
    ingest: str,
    cache: bool,
    buckets,
    shots_buckets,
    extra: Optional[Dict[str, Any]] = None,
) -> Optional[Dict[str, Callable[..., Any]]]:
    """Load the program ladder from ``root`` when (and only when) the
    manifest matches this engine exactly; returns name -> loaded
    executable, or None on any mismatch/absence (the caller falls back
    to compile-then-save). Loading performs ZERO XLA compilations — the
    payload is the compiled executable."""
    from jax.experimental import serialize_executable

    out_dir = artifact_dir_for(cfg, root, ingest, cache)
    mpath = os.path.join(out_dir, "MANIFEST.json")
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    expected = _manifest_expectation(
        cfg, ingest, cache, buckets, shots_buckets, extra
    )
    if any(manifest.get(k) != v for k, v in expected.items()):
        return None
    programs: Dict[str, Callable[..., Any]] = {}
    for name, fname in manifest.get("programs", {}).items():
        try:
            with open(os.path.join(out_dir, fname), "rb") as f:
                version, payload, in_tree, out_tree = pickle.load(f)
        except (OSError, pickle.PickleError, ValueError, EOFError):
            return None
        if version != ARTIFACT_VERSION:
            return None
        programs[name] = serialize_executable.deserialize_and_load(
            payload, in_tree, out_tree
        )
    return programs or None


# -- cli serve-export ---------------------------------------------------------


def main(argv=None) -> int:
    """``cli serve-export`` — write the warmed serving program ladder as
    AOT artifacts a later engine start deserializes instead of compiling.

    Shares ``serve-bench``'s config construction (``--fast`` /
    ``--config`` / ``--checkpoint``) so an exported ladder's fingerprint
    matches the engine the bench (or a production replica with the same
    experiment JSON) builds.
    """
    import argparse
    import time

    parser = argparse.ArgumentParser(
        prog="serve-export",
        description="AOT-export the serving engine's warmed (bucket x "
                    "shots) program ladder to a versioned artifact dir "
                    "ServingEngine.warmup() loads without compiling",
    )
    parser.add_argument("--out", required=True, metavar="DIR",
                        help="artifact root directory (the versioned "
                             "device-kind/dtype/fingerprint subdir is "
                             "created under it)")
    parser.add_argument("--fast", action="store_true",
                        help="the serve-bench --fast config (the CI gate)")
    parser.add_argument("--config", default=None,
                        help="experiment JSON supplying the geometry and "
                             "serving_* knobs")
    parser.add_argument("--checkpoint", default=None, metavar="DIR",
                        help="export against this saved_models "
                             "checkpoint's snapshot (read-only restore; "
                             "requires --config, like serve-bench)")
    parser.add_argument("--model-idx", default="latest")
    parser.add_argument("--ingest", default=None,
                        choices=["f32", "uint8"],
                        help="ingest tier to export programs for "
                             "(default: the config's serving_ingest). "
                             "The index ingest's programs bake the "
                             "resident store's row count into their "
                             "shapes, so those artifacts are written by "
                             "the ENGINE's compile-then-save fallback at "
                             "first warmup against the real store, not "
                             "by this store-less CLI")
    parser.add_argument("--cache", action="store_true",
                        help="also export the adapted-params-cache "
                             "family (return-adapted serve + predict "
                             "programs)")
    args = parser.parse_args(argv)
    if args.checkpoint and not args.config:
        parser.error("--checkpoint requires --config (see serve-bench)")

    from ..core import maml
    from .bench import _bench_cfg, bench_shots_buckets
    from .engine import ServingEngine, load_servable_snapshot

    cfg = _bench_cfg(args)
    if args.checkpoint:
        state, _ = load_servable_snapshot(cfg, args.checkpoint, args.model_idx)
    else:
        state = maml.init_state(cfg)
    ingest = args.ingest or cfg.serving_ingest
    if ingest == "index":
        parser.error(
            "serve-export cannot export index-ingest programs: their "
            "shapes bake in the resident store's row count; point the "
            "engine at the artifact dir instead (warmup falls back to "
            "compile-then-save against the real store)"
        )
    cache_size = cfg.serving_adapted_cache_size
    if args.cache and cache_size == 0:
        cache_size = cfg.serving_max_tenants_per_dispatch
    engine = ServingEngine(
        cfg, state, shots_buckets=bench_shots_buckets(cfg),
        ingest=ingest, cache_size=cache_size,
    )
    start = time.perf_counter()
    engine.warmup(artifact_dir=args.out)
    stats = dict(engine.warmup_stats)
    out_dir = artifact_dir_for(cfg, args.out, ingest, cache_size > 0)
    line = {
        "artifact_dir": out_dir,
        "programs": stats.get("programs"),
        "mode": stats.get("mode"),
        "warmup_seconds": round(time.perf_counter() - start, 3),
        "xla_compiles": stats.get("xla_compiles"),
        "ingest": ingest,
        "cache": cache_size > 0,
    }
    print(json.dumps(line))
    return 0 if os.path.exists(os.path.join(out_dir, "MANIFEST.json")) else 1


if __name__ == "__main__":
    import sys

    sys.exit(main())
