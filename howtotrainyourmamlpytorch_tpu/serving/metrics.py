"""Live serving metrics: a Prometheus text-format endpoint (stdlib only).

Production serving needs a scrape surface, not just a JSONL log. This
module aggregates the SAME schema-v11 ``serving`` telemetry records the
engine already emits — ``ServingMetrics`` is itself a telemetry sink, so
it tees off the record stream (``FanoutSink``) with zero new
instrumentation in the hot path and by construction can never disagree
with the JSONL rollup — and serves them over a background
``http.server`` thread in Prometheus exposition text format (0.0.4):

* ``serving_requests_total`` (tenants served), ``serving_dispatches_total``
  (labelled by ``program``), ``serving_retraces_total``;
* ``serving_cache_hits_total`` / ``serving_cache_lookups_total`` (hit
  rate = the quotient, consistent with the rollup's ``cache_hit_rate``);
* ``serving_h2d_bytes_total`` — cumulative actual H2D payload;
* ``serving_rollovers_total`` — checkpoint-rollover swaps observed
  (serving/refresh.py);
* ``serving_adapt_latency_ms`` / ``serving_queue_latency_ms`` histograms
  (cumulative ``_bucket{le=...}`` / ``_sum`` / ``_count`` series — the
  p50/p95 the rollup quotes are recoverable from the same buckets);
* ``serving_queue_depth`` gauge (the micro-batcher's last observed
  backlog, when a batcher reports it).

**Per-replica labels** (schema v11): records emitted by a pooled engine
carry a ``replica_id``, and every counter/gauge above keeps one series
per replica (``{replica="0"}``); records without the field render
unlabelled, so single-engine deployments scrape exactly what they
always did. Pool aggregates are label sums — the Prometheus way.

``/healthz`` reports pool readiness: constructed with a ``readiness``
callable (``ReplicaSet.readiness``), the endpoint answers **503 until
every replica's warmup completed** (body: one ``replica <id>: ready|
not-ready`` line each); without one it stays the unconditional 200 of
the single-engine shape.

Usage (what ``cli serve-bench --metrics-port`` wires)::

    metrics = ServingMetrics()
    sink = FanoutSink(JsonlSink(path), metrics)
    pool = ReplicaSet(cfg, state, sink=sink, metrics=metrics)
    server = MetricsServer(metrics, port=9090,
                           readiness=pool.readiness)  # port=0: ephemeral
    ...
    server.close()

Pure stdlib — importable (and scrapeable) without jax or numpy.
"""

from __future__ import annotations

import math
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence, Tuple

__all__ = [
    "DEFAULT_LATENCY_BUCKETS_MS",
    "Histogram",
    "LogHistogram",
    "SLOTracker",
    "ServingMetrics",
    "FanoutSink",
    "MetricsServer",
]

#: latency histogram upper bounds (milliseconds) — spanning sub-ms CPU
#: predict dispatches to multi-second cold compiles
DEFAULT_LATENCY_BUCKETS_MS: Tuple[float, ...] = (
    1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0, 5000.0, 10000.0,
)


def _fmt(value: float) -> str:
    """Prometheus number formatting: integral floats without the dot."""
    if value == float("inf"):
        return "+Inf"
    if float(value).is_integer():
        return str(int(value))
    return repr(float(value))


def _replica_label(record_or_id: Any) -> str:
    """The label blob for a record's ``replica_id`` ('' when absent —
    the single-engine unlabelled series)."""
    if isinstance(record_or_id, dict):
        rid = record_or_id.get("replica_id")
    else:
        rid = record_or_id
    if rid is None or isinstance(rid, bool) or not isinstance(rid, int):
        return ""
    return f'replica="{rid}"'


def _render_labeled(
    name: str, help_text: str, kind: str, series: Mapping[str, float],
    scalar: bool = True,
) -> List[str]:
    """Render one metric family: one line per label blob, '' rendering
    unlabelled. ``scalar`` families (everything that was a single
    unlabelled sample pre-pool) ALWAYS emit the unlabelled sample —
    defaulting to 0 — so the '' series never appears/vanishes across
    scrapes (a Prometheus counter that disappears breaks rate()
    continuity) and the single-engine exposition stays byte-identical
    to the pre-pool output. Non-scalar families (the program-labelled
    dispatch counter, which pre-pool emitted no sample when empty)
    render labelled entries only."""
    lines = [f"# HELP {name} {help_text}", f"# TYPE {name} {kind}"]
    if scalar:
        lines.append(f"{name} {_fmt(series.get('', 0))}")
    for labels in sorted(series):
        value = series[labels]
        if labels:
            lines.append(f"{name}{{{labels}}} {_fmt(value)}")
        elif not scalar:
            lines.append(f"{name} {_fmt(value)}")
    return lines


class Histogram:
    """A cumulative Prometheus histogram (counts per le-bucket + sum)."""

    def __init__(self, buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS_MS):
        self.bounds: Tuple[float, ...] = tuple(sorted(buckets))
        self.counts: List[int] = [0] * (len(self.bounds) + 1)  # +Inf last
        self.total = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        self.count += 1
        self.total += float(value)
        for i, bound in enumerate(self.bounds):
            if value <= bound:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def render(self, name: str, help_text: str) -> List[str]:
        lines = [
            f"# HELP {name} {help_text}",
            f"# TYPE {name} histogram",
        ]
        cumulative = 0
        for bound, n in zip(
            self.bounds + (float("inf"),), self.counts
        ):
            cumulative += n
            lines.append(
                f'{name}_bucket{{le="{_fmt(bound)}"}} {cumulative}'
            )
        lines.append(f"{name}_sum {_fmt(round(self.total, 6))}")
        lines.append(f"{name}_count {self.count}")
        return lines


#: LogHistogram ladder: 4 buckets per latency doubling (growth 2^0.25),
#: floor 1e-3 ms — quantiles read off the ladder carry at most ~19%
#: relative error, and the FIXED ladder is what makes two histograms
#: mergeable bucket-by-bucket with no loss.
LOG_HISTOGRAM_LOW_MS: float = 1e-3
LOG_HISTOGRAM_GROWTH: float = 2.0 ** 0.25
#: ladder length: bucket 128's upper bound is 1e-3 * 2^32 ms ≈ 71 min;
#: anything slower lands in the single overflow bucket above it.
LOG_HISTOGRAM_BUCKETS: int = 128


class LogHistogram:
    """A mergeable log-bucketed latency histogram (stdlib only).

    Unlike the last-N sample windows the rollup percentiles used to be
    quoted from, a histogram never drops history: every observation
    lands in a bucket of the FIXED geometric ladder
    ``low * growth**i``, so two histograms over the same ladder merge
    EXACTLY (bucket-by-bucket count addition) — across replicas, and
    across engine swaps via ``adopt_serving_history``. Quantiles are
    read off the ladder as the upper bound of the bucket holding the
    q-th observation (clamped to the observed min/max), so any quantile
    is within one bucket's relative error (growth-1 ≈ 19%) of the true
    sample quantile.

    Counts are kept sparse (``{bucket_index: count}``) — a run whose
    latencies span three decades touches ~40 of the 129 buckets — which
    also keeps the ``to_dict`` payload embedded in rollup telemetry
    records compact.
    """

    def __init__(
        self,
        low: float = LOG_HISTOGRAM_LOW_MS,
        growth: float = LOG_HISTOGRAM_GROWTH,
        n_buckets: int = LOG_HISTOGRAM_BUCKETS,
    ):
        if low <= 0 or growth <= 1 or n_buckets < 1:
            raise ValueError(
                f"bad ladder: low={low} growth={growth} n={n_buckets}"
            )
        self.low = float(low)
        self.growth = float(growth)
        self.n_buckets = int(n_buckets)
        self._log_growth = math.log(self.growth)
        self.counts: Dict[int, int] = {}
        self.count = 0
        self.total = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    # -- ladder ------------------------------------------------------------

    def bucket_index(self, value: float) -> int:
        """Index of the bucket whose (lower, upper] interval holds
        ``value``; 0 absorbs everything at or below the floor, index
        ``n_buckets`` is the overflow bucket (upper bound +Inf)."""
        if value <= self.low:
            return 0
        idx = int(math.ceil(math.log(value / self.low) / self._log_growth))
        # float fuzz at an exact bound: log() can land a hair above the
        # integer, pushing an on-the-bound value one bucket up — pull it
        # back when the lower bound still covers the value
        if idx > 0 and self.low * self.growth ** (idx - 1) >= value:
            idx -= 1
        return min(max(idx, 0), self.n_buckets)

    def bucket_upper(self, index: int) -> float:
        """Upper bound of bucket ``index`` (+Inf for the overflow)."""
        if index >= self.n_buckets:
            return float("inf")
        return self.low * self.growth ** index

    # -- observe / merge ---------------------------------------------------

    def observe(self, value: float) -> None:
        v = float(value)
        if math.isnan(v):
            return
        idx = self.bucket_index(v)
        self.counts[idx] = self.counts.get(idx, 0) + 1
        self.count += 1
        self.total += v
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)

    def merge(self, other: "LogHistogram") -> None:
        """Exact bucket-by-bucket merge — the pool/fleet rollup and the
        rollover history-adoption path. Ladders must match (they are a
        module constant; a mismatch means a version skew bug)."""
        if (other.low, other.growth, other.n_buckets) != (
            self.low, self.growth, self.n_buckets
        ):
            raise ValueError(
                "cannot merge histograms over different ladders: "
                f"({self.low}, {self.growth}, {self.n_buckets}) vs "
                f"({other.low}, {other.growth}, {other.n_buckets})"
            )
        for idx, n in other.counts.items():
            self.counts[idx] = self.counts.get(idx, 0) + n
        self.count += other.count
        self.total += other.total
        for bound, pick in (("min", min), ("max", max)):
            theirs = getattr(other, bound)
            if theirs is not None:
                ours = getattr(self, bound)
                setattr(
                    self, bound,
                    theirs if ours is None else pick(ours, theirs),
                )

    # -- reading -----------------------------------------------------------

    def quantile(self, q: float) -> Optional[float]:
        """The q-quantile (0..1) read off the ladder: the upper bound of
        the bucket holding the ceil(q*count)-th observation, clamped to
        the observed [min, max]. None when empty."""
        if self.count == 0 or self.min is None or self.max is None:
            return None
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        rank = max(1, math.ceil(q * self.count))
        seen = 0
        for idx in sorted(self.counts):
            seen += self.counts[idx]
            if seen >= rank:
                return max(self.min, min(self.max, self.bucket_upper(idx)))
        return self.max  # unreachable; counts always sum to count

    @property
    def mean(self) -> Optional[float]:
        return self.total / self.count if self.count else None

    def to_dict(self) -> Dict[str, Any]:
        """JSONL-safe sparse form, embedded in rollup records so the
        jax-free CLI can recompute the same quantiles offline."""
        return {
            "low": self.low,
            "growth": self.growth,
            "n_buckets": self.n_buckets,
            "counts": {str(i): n for i, n in sorted(self.counts.items())},
            "count": self.count,
            "sum": round(self.total, 6),
            "min": self.min,
            "max": self.max,
        }

    @classmethod
    def from_dict(cls, payload: Mapping[str, Any]) -> "LogHistogram":
        hist = cls(
            low=float(payload.get("low", LOG_HISTOGRAM_LOW_MS)),
            growth=float(payload.get("growth", LOG_HISTOGRAM_GROWTH)),
            n_buckets=int(payload.get("n_buckets", LOG_HISTOGRAM_BUCKETS)),
        )
        counts = payload.get("counts", {})
        if isinstance(counts, Mapping):
            for key, n in counts.items():
                hist.counts[int(key)] = int(n)
        hist.count = int(payload.get("count", sum(hist.counts.values())))
        hist.total = float(payload.get("sum", 0.0))
        for bound in ("min", "max"):
            v = payload.get(bound)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                setattr(hist, bound, float(v))
        return hist

    def render(self, name: str, help_text: str) -> List[str]:
        """Prometheus cumulative exposition: one ``_bucket`` line per
        OCCUPIED ladder point (cumulative counts stay exact and monotone
        over any subset of bounds) plus the mandatory ``+Inf``."""
        lines = [
            f"# HELP {name} {help_text}",
            f"# TYPE {name} histogram",
        ]
        cumulative = 0
        for idx in sorted(self.counts):
            cumulative += self.counts[idx]
            upper = self.bucket_upper(idx)
            if upper != float("inf"):
                lines.append(
                    f'{name}_bucket{{le="{_fmt(round(upper, 9))}"}} '
                    f"{cumulative}"
                )
        lines.append(f'{name}_bucket{{le="+Inf"}} {self.count}')
        lines.append(f"{name}_sum {_fmt(round(self.total, 6))}")
        lines.append(f"{name}_count {self.count}")
        return lines


class SLOTracker:
    """Deadline/SLO accounting over the serving record stream.

    Sink-compatible (``write(record)``): consumes the ``serving``
    records with ``event="deadline"`` the micro-batcher emits once per
    deadline-carrying request, and nothing else. Because it reads the
    SAME record stream the JSONL log captures, a live ``/metrics``
    scrape, the end-of-run ``slo`` telemetry record, and an offline
    ``cli slo`` replay of the log all agree by construction.

    The SLO itself: ``target_ms`` is the per-request latency objective
    (a request whose deadline was missed burns budget), ``availability``
    the objective fraction of requests that must meet it, and the error
    budget the ``1 - availability`` remainder. Burn rate over a window
    is the window's miss rate divided by the error budget — burn 1.0
    spends the budget exactly at the objective rate, sustained burn
    above 1.0 exhausts it early (the multi-window alerting form).
    Windows are anchored to record timestamps (newest record = "now"),
    so replaying a log yields the same numbers the live endpoint showed
    at end of run.
    """

    def __init__(
        self,
        target_ms: float,
        availability: float = 0.99,
        burn_windows_s: Sequence[float] = (60.0, 300.0, 3600.0),
    ):
        if target_ms < 0:
            raise ValueError(f"target_ms must be >= 0, got {target_ms}")
        if not 0.0 < availability < 1.0:
            raise ValueError(
                f"availability must be in (0, 1), got {availability}"
            )
        windows = tuple(float(w) for w in burn_windows_s)
        if not windows or any(w <= 0 for w in windows):
            raise ValueError(
                f"burn windows must be positive, got {burn_windows_s}"
            )
        self.target_ms = float(target_ms)
        self.availability = float(availability)
        self.error_budget = 1.0 - self.availability
        self.burn_windows_s = tuple(sorted(windows))
        self._lock = threading.Lock()
        self.requests: Dict[str, int] = {}
        self.missed: Dict[str, int] = {}
        self._slack_ms = LogHistogram()  # |slack|; sign tracked by miss
        # (ts, missed) per deadline record, pruned past the widest window
        self._events: List[Tuple[float, bool]] = []
        self._latest_ts: Optional[float] = None

    # -- the sink face -----------------------------------------------------

    def write(self, record: Dict[str, Any]) -> None:
        if (
            not isinstance(record, dict)
            or record.get("kind") != "serving"
            or record.get("event") != "deadline"
        ):
            return
        missed = bool(record.get("missed"))
        label = _replica_label(record)
        ts = record.get("ts")
        with self._lock:
            self._bump(self.requests, label)
            if missed:
                self._bump(self.missed, label)
            slack = record.get("slack_ms")
            if isinstance(slack, (int, float)) and not isinstance(
                slack, bool
            ):
                self._slack_ms.observe(abs(float(slack)))
            if isinstance(ts, (int, float)) and not isinstance(ts, bool):
                t = float(ts)
                self._events.append((t, missed))
                if self._latest_ts is None or t > self._latest_ts:
                    self._latest_ts = t
                self._prune_locked()

    @staticmethod
    def _bump(series: Dict[str, int], label: str) -> None:
        series[label] = series.get(label, 0) + 1

    def _prune_locked(self) -> None:
        horizon = (self._latest_ts or 0.0) - max(self.burn_windows_s)
        if self._events and self._events[0][0] <= horizon:
            self._events = [e for e in self._events if e[0] > horizon]

    def close(self) -> None:  # sink protocol completeness
        pass

    # -- reading -----------------------------------------------------------

    def summary(self) -> Dict[str, Any]:
        """The SLO report: totals, per-replica breakdown, and per-window
        burn rates — the payload of the ``slo`` telemetry record and of
        ``cli slo``."""
        with self._lock:
            requests = sum(self.requests.values())
            missed = sum(self.missed.values())
            burn: Dict[str, Optional[float]] = {}
            worst_w: Optional[float] = None
            worst_rate: Optional[float] = None
            now = self._latest_ts or 0.0
            # windows are half-open (now - w, now]: a w-second window at
            # one event/second holds exactly w events, not w + 1
            for w in self.burn_windows_s:
                in_window = [m for t, m in self._events if t > now - w]
                if not in_window:
                    burn[f"{w:g}"] = None
                    continue
                rate = (
                    sum(1 for m in in_window if m) / len(in_window)
                ) / self.error_budget
                burn[f"{w:g}"] = round(rate, 6)
                if worst_rate is None or rate > worst_rate:
                    worst_rate, worst_w = rate, w
            # series keys are Prometheus label blobs ('replica="0"' /
            # ''); the summary reports bare replica ids ('0' / '-')
            per_replica = {
                (label[9:-1] if label else "-"): {
                    "requests": n,
                    "missed": self.missed.get(label, 0),
                }
                for label, n in sorted(self.requests.items())
            }
            return {
                "target_ms": self.target_ms,
                "availability": self.availability,
                "error_budget": round(self.error_budget, 9),
                "requests": requests,
                "missed": missed,
                "miss_rate": (
                    round(missed / requests, 6) if requests else None
                ),
                "burn_rates": burn,
                "worst_burn_window_s": worst_w,
                "worst_burn_rate": (
                    round(worst_rate, 6) if worst_rate is not None else None
                ),
                "per_replica": per_replica,
            }

    def render(self) -> List[str]:
        """The Prometheus families ``ServingMetrics.render`` appends."""
        with self._lock:
            met = {
                label: n - self.missed.get(label, 0)
                for label, n in self.requests.items()
            }
            lines = _render_labeled(
                "serving_deadline_met_total",
                "Deadline-carrying requests that met their deadline",
                "counter", met,
            )
            lines += _render_labeled(
                "serving_deadline_missed_total",
                "Deadline-carrying requests that missed their deadline",
                "counter", dict(self.missed),
            )
            lines += [
                "# HELP serving_slo_error_budget "
                "Allowed deadline-miss fraction (1 - availability "
                "objective)",
                "# TYPE serving_slo_error_budget gauge",
                f"serving_slo_error_budget {_fmt(self.error_budget)}",
                "# HELP serving_slo_burn_rate Window deadline-miss rate "
                "over the error budget (1.0 spends the budget exactly at "
                "the objective rate)",
                "# TYPE serving_slo_burn_rate gauge",
            ]
        summary_burn = self.summary()["burn_rates"]
        for window, rate in summary_burn.items():
            if rate is not None:
                lines.append(
                    f'serving_slo_burn_rate{{window_s="{window}"}} '
                    f"{_fmt(rate)}"
                )
        return lines


class ServingMetrics:
    """Aggregates ``serving`` telemetry records into scrapeable series.

    Sink-compatible (``write(record)``): hand it to the engine directly,
    or tee it next to the JSONL sink with ``FanoutSink`` — one record
    stream, two consumers, so the endpoint and the log can never
    disagree. Thread-safe: dispatch threads (one per replica in a pool)
    write while the HTTP thread renders. Counters are keyed by the
    record's ``replica_id`` label ('' for unlabelled single-engine
    records); the latency histograms stay pool-aggregate (log-bucketed
    ``LogHistogram`` families — no sample window, no silent drops).

    Pass an ``SLOTracker`` to surface deadline/burn-rate families on the
    same endpoint: ``write`` forwards every record to it (do NOT also
    register the tracker as a separate fanout sink, or deadlines double-
    count).
    """

    def __init__(self, slo: Optional["SLOTracker"] = None):
        self._lock = threading.Lock()
        self.slo = slo
        self.requests_total: Dict[str, int] = {}
        # (program, replica-label) -> dispatch count
        self.dispatches_by_program: Dict[Tuple[str, str], int] = {}
        self.cache_hits_total: Dict[str, int] = {}
        self.cache_lookups_total: Dict[str, int] = {}
        self.h2d_bytes_total: Dict[str, int] = {}
        self.retraces_total: Dict[str, int] = {}
        self.warmups_total: Dict[str, int] = {}
        self.rollovers_total: Dict[str, int] = {}
        self.queue_depth: Dict[str, int] = {}
        self.adapt_ms = LogHistogram()
        self.queue_ms = LogHistogram()

    @staticmethod
    def _bump(series: Dict[str, int], label: str, by: int) -> None:
        series[label] = series.get(label, 0) + by

    # -- the sink face -----------------------------------------------------

    def write(self, record: Dict[str, Any]) -> None:
        """Consume one telemetry record (non-serving kinds pass through
        untouched — the tee carries the whole stream)."""
        if self.slo is not None:
            self.slo.write(record)
        if not isinstance(record, dict) or record.get("kind") != "serving":
            return
        event = record.get("event")
        label = _replica_label(record)
        with self._lock:
            if event == "dispatch":
                tenants = record.get("tenants")
                if isinstance(tenants, int):
                    self._bump(self.requests_total, label, tenants)
                program = str(record.get("program", "adapt"))
                key = (program, label)
                self.dispatches_by_program[key] = (
                    self.dispatches_by_program.get(key, 0) + 1
                )
                # dispatch records carry cache_hits only when the
                # adapted-params cache is enabled — a cache-less engine
                # must render 0 lookups (rollup: cache_hit_rate=None),
                # not a 0% hit rate
                hits = record.get("cache_hits")
                if isinstance(hits, int):
                    self._bump(self.cache_hits_total, label, hits)
                    if isinstance(tenants, int):
                        self._bump(self.cache_lookups_total, label, tenants)
                nbytes = record.get("ingest_bytes")
                if isinstance(nbytes, int):
                    self._bump(self.h2d_bytes_total, label, nbytes)
                adapt = record.get("adapt_ms")
                if isinstance(adapt, (int, float)):
                    self.adapt_ms.observe(float(adapt))
                queue = record.get("queue_ms")
                if isinstance(queue, (int, float)):
                    self.queue_ms.observe(float(queue))
            elif event == "rollup":
                retraces = record.get("retraces")
                if isinstance(retraces, int):
                    self.retraces_total[label] = retraces
            elif event == "warmup":
                self._bump(self.warmups_total, label, 1)
            elif event == "rollover":
                self._bump(self.rollovers_total, label, 1)

    def observe_queue_depth(self, depth: int, replica=None) -> None:
        with self._lock:
            self.queue_depth[_replica_label(replica)] = int(depth)

    def close(self) -> None:  # sink protocol completeness
        pass

    # -- exposition --------------------------------------------------------

    def render(self) -> str:
        """The Prometheus text-format (0.0.4) payload."""
        with self._lock:
            lines: List[str] = []
            lines += _render_labeled(
                "serving_requests_total",
                "Tenants served (cache hits included)",
                "counter", self.requests_total,
            )
            # program x replica labels: merge into one family
            by_program: Dict[str, int] = {}
            for (program, label), n in self.dispatches_by_program.items():
                blob = f'program="{program}"'
                if label:
                    blob += f",{label}"
                by_program[blob] = n
            lines += _render_labeled(
                "serving_dispatches_total",
                "Device dispatches by program family",
                "counter", by_program, scalar=False,
            )
            lines += _render_labeled(
                "serving_cache_hits_total",
                "Adapted-params cache hits (tenants that skipped the "
                "inner loop)",
                "counter", self.cache_hits_total,
            )
            lines += _render_labeled(
                "serving_cache_lookups_total",
                "Adapted-params cache lookups (tenants through "
                "dispatches)",
                "counter", self.cache_lookups_total,
            )
            lines += _render_labeled(
                "serving_h2d_bytes_total",
                "Actual host-to-device payload bytes uploaded",
                "counter", self.h2d_bytes_total,
            )
            lines += _render_labeled(
                "serving_retraces_total",
                "Mid-run recompiles the strict detector observed "
                "(0 in any healthy run)",
                "counter", self.retraces_total,
            )
            lines += _render_labeled(
                "serving_warmups_total",
                "Engine warmups observed",
                "counter", self.warmups_total,
            )
            lines += _render_labeled(
                "serving_rollovers_total",
                "Checkpoint-rollover engine swaps observed "
                "(serving/refresh.py)",
                "counter", self.rollovers_total,
            )
            lines += _render_labeled(
                "serving_queue_depth",
                "Micro-batcher backlog (requests queued across shots "
                "buckets)",
                "gauge", self.queue_depth,
            )
            lines += self.adapt_ms.render(
                "serving_adapt_latency_ms",
                "End-to-end dispatch latency (upload + device + readback)",
            )
            lines += self.queue_ms.render(
                "serving_queue_latency_ms",
                "Micro-batcher queue wait per dispatch",
            )
        if self.slo is not None:
            # outside self._lock: SLOTracker takes its own lock
            lines += self.slo.render()
        return "\n".join(lines) + "\n"


class FanoutSink:
    """Tee one telemetry record stream into several sinks (JSONL log +
    metrics registry is the serving shape). Write errors in one sink
    must not starve the others."""

    def __init__(self, *sinks):
        self.sinks = [s for s in sinks if s is not None]

    def write(self, record: Dict[str, Any]) -> None:
        # every sink sees every record even when an earlier one raises
        # (a full JSONL disk must not blind the metrics endpoint); the
        # first error still surfaces after delivery, same as a lone sink
        first_error: Optional[BaseException] = None
        for sink in self.sinks:
            try:
                sink.write(record)
            except Exception as e:  # noqa: BLE001 - per-sink isolation
                if first_error is None:
                    first_error = e
        if first_error is not None:
            raise first_error

    def close(self) -> None:
        first_error: Optional[BaseException] = None
        for sink in self.sinks:
            close = getattr(sink, "close", None)
            if close is None:
                continue
            try:
                close()
            except Exception as e:  # noqa: BLE001 - per-sink isolation
                if first_error is None:
                    first_error = e
        if first_error is not None:
            raise first_error


class _Handler(BaseHTTPRequestHandler):
    metrics: ServingMetrics  # set per server class below
    readiness: Optional[Callable[[], Mapping[str, bool]]] = None

    def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler API
        if self.path.split("?")[0] in ("/metrics", "/"):
            body = self.metrics.render().encode()
            self.send_response(200)
            self.send_header(
                "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
            )
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif self.path == "/healthz":
            self._healthz()
        else:
            self.send_response(404)
            self.end_headers()

    def _healthz(self) -> None:
        """Pool readiness: 503 until EVERY replica's warmup completed
        (per-replica status in the body); the readiness-less single-
        engine shape keeps the unconditional 200."""
        if self.readiness is None:
            code, body = 200, "ok\n"
        else:
            try:
                states = dict(self.readiness())
            except Exception as e:  # noqa: BLE001 - a probe must answer,
                # not crash the scrape thread
                states, e_line = {}, f"readiness probe failed: {e!r}\n"
                code, body = 503, e_line
            else:
                all_ready = bool(states) and all(states.values())
                code = 200 if all_ready else 503
                body = ("ok\n" if all_ready else "warming\n") + "".join(
                    f"replica {rid}: "
                    f"{'ready' if ok else 'not-ready'}\n"
                    for rid, ok in sorted(states.items())
                )
        payload = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", "text/plain")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def log_message(self, fmt, *args):  # silence per-scrape stderr spam
        pass


class MetricsServer:
    """Background-thread HTTP server exposing ``/metrics`` (+
    ``/healthz``). ``port=0`` binds an ephemeral port — read ``.port``
    after construction. ``readiness`` (optional; e.g.
    ``ReplicaSet.readiness``) turns ``/healthz`` into a pool-readiness
    probe: 503 until every replica reports ready. ``close()`` shuts the
    server down and joins the thread; the server thread is a daemon
    either way, so a crashed serving process never hangs on it."""

    def __init__(self, metrics: ServingMetrics, port: int = 0,
                 host: str = "127.0.0.1",
                 readiness: Optional[Callable[[], Mapping[str, bool]]] = None):
        self.metrics = metrics

        class _BoundHandler(_Handler):
            pass

        _BoundHandler.metrics = metrics
        _BoundHandler.readiness = staticmethod(readiness) if readiness else None
        self._httpd = ThreadingHTTPServer((host, port), _BoundHandler)
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="serving-metrics",
            daemon=True,
        )
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    def close(self) -> None:
        self._httpd.shutdown()
        self._thread.join(5.0)
        self._httpd.server_close()


def _split_le(labels_blob: str) -> Tuple[Optional[str], str]:
    """Split a ``_bucket`` series' label blob into its ``le`` value and
    the remaining labels (the histogram's own labels, if any)."""
    le: Optional[str] = None
    rest: List[str] = []
    for part in labels_blob.split(","):
        part = part.strip()
        if not part:
            continue
        if part.startswith('le="') and part.endswith('"'):
            le = part[len('le="'):-1]
        else:
            rest.append(part)
    return le, ",".join(rest)


def _validate_histograms(out: Dict[str, Dict[str, float]]) -> None:
    """Histogram-exposition validation over parsed series: every
    ``<base>_bucket`` family must carry parseable ``le`` labels, a
    ``+Inf`` bucket, cumulative counts monotone in ``le`` order, and a
    ``<base>_count``/``<base>_sum`` pair whose count equals the ``+Inf``
    bucket. Raises ValueError naming the offending family."""
    for name, series in out.items():
        if not name.endswith("_bucket"):
            continue
        base = name[:-len("_bucket")]
        # group buckets by the non-le labels (one group per histogram)
        groups: Dict[str, List[Tuple[float, float]]] = {}
        for labels_blob, value in series.items():
            le, rest = _split_le(labels_blob)
            if le is None:
                raise ValueError(
                    f"{name}{{{labels_blob}}} has no le label"
                )
            bound = float("inf") if le == "+Inf" else float(le)
            groups.setdefault(rest, []).append((bound, value))
        for rest, buckets in groups.items():
            where = f"{base}{{{rest}}}" if rest else base
            buckets.sort(key=lambda bv: bv[0])
            if buckets[-1][0] != float("inf"):
                raise ValueError(f"{where} histogram has no +Inf bucket")
            cum = [v for _, v in buckets]
            if any(b > a for b, a in zip(cum, cum[1:])):
                raise ValueError(
                    f"{where} histogram buckets are not cumulative "
                    f"(non-monotone counts {cum})"
                )
            count = out.get(f"{base}_count", {}).get(rest)
            if count is None:
                raise ValueError(f"{where} histogram missing _count")
            if count != buckets[-1][1]:
                raise ValueError(
                    f"{where} histogram _count {count} != +Inf bucket "
                    f"{buckets[-1][1]}"
                )
            if f"{base}_sum" not in out or rest not in out[f"{base}_sum"]:
                raise ValueError(f"{where} histogram missing _sum")


def parse_prometheus_text(text: str) -> Dict[str, Dict[str, float]]:
    """Parse exposition text into ``{metric_name: {labels_blob: value}}``
    (``labels_blob`` '' for unlabelled series). Used by the tests and the
    CI trace-smoke/slo-smoke jobs to assert the endpoint speaks valid
    text format — a parse error raises ValueError naming the line, and
    every ``*_bucket`` histogram family is validated for cumulative
    monotone counts, a ``+Inf`` bucket, and a matching ``_count``/
    ``_sum`` pair."""
    out: Dict[str, Dict[str, float]] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            series, value = line.rsplit(" ", 1)
            if "{" in series:
                name, labels = series.split("{", 1)
                if not labels.endswith("}"):
                    raise ValueError("unterminated label set")
                labels = labels[:-1]
            else:
                name, labels = series, ""
            out.setdefault(name, {})[labels] = float(value)
        except ValueError as e:
            raise ValueError(
                f"prometheus text line {lineno} unparseable: {line!r} ({e})"
            ) from e
    _validate_histograms(out)
    return out
