"""Live serving metrics: a Prometheus text-format endpoint (stdlib only).

Production serving needs a scrape surface, not just a JSONL log. This
module aggregates the SAME schema-v11 ``serving`` telemetry records the
engine already emits — ``ServingMetrics`` is itself a telemetry sink, so
it tees off the record stream (``FanoutSink``) with zero new
instrumentation in the hot path and by construction can never disagree
with the JSONL rollup — and serves them over a background
``http.server`` thread in Prometheus exposition text format (0.0.4):

* ``serving_requests_total`` (tenants served), ``serving_dispatches_total``
  (labelled by ``program``), ``serving_retraces_total``;
* ``serving_cache_hits_total`` / ``serving_cache_lookups_total`` (hit
  rate = the quotient, consistent with the rollup's ``cache_hit_rate``);
* ``serving_h2d_bytes_total`` — cumulative actual H2D payload;
* ``serving_rollovers_total`` — checkpoint-rollover swaps observed
  (serving/refresh.py);
* ``serving_adapt_latency_ms`` / ``serving_queue_latency_ms`` histograms
  (cumulative ``_bucket{le=...}`` / ``_sum`` / ``_count`` series — the
  p50/p95 the rollup quotes are recoverable from the same buckets);
* ``serving_queue_depth`` gauge (the micro-batcher's last observed
  backlog, when a batcher reports it).

**Per-replica labels** (schema v11): records emitted by a pooled engine
carry a ``replica_id``, and every counter/gauge above keeps one series
per replica (``{replica="0"}``); records without the field render
unlabelled, so single-engine deployments scrape exactly what they
always did. Pool aggregates are label sums — the Prometheus way.

``/healthz`` reports pool readiness: constructed with a ``readiness``
callable (``ReplicaSet.readiness``), the endpoint answers **503 until
every replica's warmup completed** (body: one ``replica <id>: ready|
not-ready`` line each); without one it stays the unconditional 200 of
the single-engine shape.

Usage (what ``cli serve-bench --metrics-port`` wires)::

    metrics = ServingMetrics()
    sink = FanoutSink(JsonlSink(path), metrics)
    pool = ReplicaSet(cfg, state, sink=sink, metrics=metrics)
    server = MetricsServer(metrics, port=9090,
                           readiness=pool.readiness)  # port=0: ephemeral
    ...
    server.close()

Pure stdlib — importable (and scrapeable) without jax or numpy.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence, Tuple

__all__ = [
    "DEFAULT_LATENCY_BUCKETS_MS",
    "Histogram",
    "ServingMetrics",
    "FanoutSink",
    "MetricsServer",
]

#: latency histogram upper bounds (milliseconds) — spanning sub-ms CPU
#: predict dispatches to multi-second cold compiles
DEFAULT_LATENCY_BUCKETS_MS: Tuple[float, ...] = (
    1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0, 5000.0, 10000.0,
)


def _fmt(value: float) -> str:
    """Prometheus number formatting: integral floats without the dot."""
    if value == float("inf"):
        return "+Inf"
    if float(value).is_integer():
        return str(int(value))
    return repr(float(value))


def _replica_label(record_or_id: Any) -> str:
    """The label blob for a record's ``replica_id`` ('' when absent —
    the single-engine unlabelled series)."""
    if isinstance(record_or_id, dict):
        rid = record_or_id.get("replica_id")
    else:
        rid = record_or_id
    if rid is None or isinstance(rid, bool) or not isinstance(rid, int):
        return ""
    return f'replica="{rid}"'


def _render_labeled(
    name: str, help_text: str, kind: str, series: Mapping[str, float],
    scalar: bool = True,
) -> List[str]:
    """Render one metric family: one line per label blob, '' rendering
    unlabelled. ``scalar`` families (everything that was a single
    unlabelled sample pre-pool) ALWAYS emit the unlabelled sample —
    defaulting to 0 — so the '' series never appears/vanishes across
    scrapes (a Prometheus counter that disappears breaks rate()
    continuity) and the single-engine exposition stays byte-identical
    to the pre-pool output. Non-scalar families (the program-labelled
    dispatch counter, which pre-pool emitted no sample when empty)
    render labelled entries only."""
    lines = [f"# HELP {name} {help_text}", f"# TYPE {name} {kind}"]
    if scalar:
        lines.append(f"{name} {_fmt(series.get('', 0))}")
    for labels in sorted(series):
        value = series[labels]
        if labels:
            lines.append(f"{name}{{{labels}}} {_fmt(value)}")
        elif not scalar:
            lines.append(f"{name} {_fmt(value)}")
    return lines


class Histogram:
    """A cumulative Prometheus histogram (counts per le-bucket + sum)."""

    def __init__(self, buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS_MS):
        self.bounds: Tuple[float, ...] = tuple(sorted(buckets))
        self.counts: List[int] = [0] * (len(self.bounds) + 1)  # +Inf last
        self.total = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        self.count += 1
        self.total += float(value)
        for i, bound in enumerate(self.bounds):
            if value <= bound:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def render(self, name: str, help_text: str) -> List[str]:
        lines = [
            f"# HELP {name} {help_text}",
            f"# TYPE {name} histogram",
        ]
        cumulative = 0
        for bound, n in zip(
            self.bounds + (float("inf"),), self.counts
        ):
            cumulative += n
            lines.append(
                f'{name}_bucket{{le="{_fmt(bound)}"}} {cumulative}'
            )
        lines.append(f"{name}_sum {_fmt(round(self.total, 6))}")
        lines.append(f"{name}_count {self.count}")
        return lines


class ServingMetrics:
    """Aggregates ``serving`` telemetry records into scrapeable series.

    Sink-compatible (``write(record)``): hand it to the engine directly,
    or tee it next to the JSONL sink with ``FanoutSink`` — one record
    stream, two consumers, so the endpoint and the log can never
    disagree. Thread-safe: dispatch threads (one per replica in a pool)
    write while the HTTP thread renders. Counters are keyed by the
    record's ``replica_id`` label ('' for unlabelled single-engine
    records); the latency histograms stay pool-aggregate.
    """

    def __init__(self,
                 buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS_MS):
        self._lock = threading.Lock()
        self.requests_total: Dict[str, int] = {}
        # (program, replica-label) -> dispatch count
        self.dispatches_by_program: Dict[Tuple[str, str], int] = {}
        self.cache_hits_total: Dict[str, int] = {}
        self.cache_lookups_total: Dict[str, int] = {}
        self.h2d_bytes_total: Dict[str, int] = {}
        self.retraces_total: Dict[str, int] = {}
        self.warmups_total: Dict[str, int] = {}
        self.rollovers_total: Dict[str, int] = {}
        self.queue_depth: Dict[str, int] = {}
        self.adapt_ms = Histogram(buckets)
        self.queue_ms = Histogram(buckets)

    @staticmethod
    def _bump(series: Dict[str, int], label: str, by: int) -> None:
        series[label] = series.get(label, 0) + by

    # -- the sink face -----------------------------------------------------

    def write(self, record: Dict[str, Any]) -> None:
        """Consume one telemetry record (non-serving kinds pass through
        untouched — the tee carries the whole stream)."""
        if not isinstance(record, dict) or record.get("kind") != "serving":
            return
        event = record.get("event")
        label = _replica_label(record)
        with self._lock:
            if event == "dispatch":
                tenants = record.get("tenants")
                if isinstance(tenants, int):
                    self._bump(self.requests_total, label, tenants)
                program = str(record.get("program", "adapt"))
                key = (program, label)
                self.dispatches_by_program[key] = (
                    self.dispatches_by_program.get(key, 0) + 1
                )
                # dispatch records carry cache_hits only when the
                # adapted-params cache is enabled — a cache-less engine
                # must render 0 lookups (rollup: cache_hit_rate=None),
                # not a 0% hit rate
                hits = record.get("cache_hits")
                if isinstance(hits, int):
                    self._bump(self.cache_hits_total, label, hits)
                    if isinstance(tenants, int):
                        self._bump(self.cache_lookups_total, label, tenants)
                nbytes = record.get("ingest_bytes")
                if isinstance(nbytes, int):
                    self._bump(self.h2d_bytes_total, label, nbytes)
                adapt = record.get("adapt_ms")
                if isinstance(adapt, (int, float)):
                    self.adapt_ms.observe(float(adapt))
                queue = record.get("queue_ms")
                if isinstance(queue, (int, float)):
                    self.queue_ms.observe(float(queue))
            elif event == "rollup":
                retraces = record.get("retraces")
                if isinstance(retraces, int):
                    self.retraces_total[label] = retraces
            elif event == "warmup":
                self._bump(self.warmups_total, label, 1)
            elif event == "rollover":
                self._bump(self.rollovers_total, label, 1)

    def observe_queue_depth(self, depth: int, replica=None) -> None:
        with self._lock:
            self.queue_depth[_replica_label(replica)] = int(depth)

    def close(self) -> None:  # sink protocol completeness
        pass

    # -- exposition --------------------------------------------------------

    def render(self) -> str:
        """The Prometheus text-format (0.0.4) payload."""
        with self._lock:
            lines: List[str] = []
            lines += _render_labeled(
                "serving_requests_total",
                "Tenants served (cache hits included)",
                "counter", self.requests_total,
            )
            # program x replica labels: merge into one family
            by_program: Dict[str, int] = {}
            for (program, label), n in self.dispatches_by_program.items():
                blob = f'program="{program}"'
                if label:
                    blob += f",{label}"
                by_program[blob] = n
            lines += _render_labeled(
                "serving_dispatches_total",
                "Device dispatches by program family",
                "counter", by_program, scalar=False,
            )
            lines += _render_labeled(
                "serving_cache_hits_total",
                "Adapted-params cache hits (tenants that skipped the "
                "inner loop)",
                "counter", self.cache_hits_total,
            )
            lines += _render_labeled(
                "serving_cache_lookups_total",
                "Adapted-params cache lookups (tenants through "
                "dispatches)",
                "counter", self.cache_lookups_total,
            )
            lines += _render_labeled(
                "serving_h2d_bytes_total",
                "Actual host-to-device payload bytes uploaded",
                "counter", self.h2d_bytes_total,
            )
            lines += _render_labeled(
                "serving_retraces_total",
                "Mid-run recompiles the strict detector observed "
                "(0 in any healthy run)",
                "counter", self.retraces_total,
            )
            lines += _render_labeled(
                "serving_warmups_total",
                "Engine warmups observed",
                "counter", self.warmups_total,
            )
            lines += _render_labeled(
                "serving_rollovers_total",
                "Checkpoint-rollover engine swaps observed "
                "(serving/refresh.py)",
                "counter", self.rollovers_total,
            )
            lines += _render_labeled(
                "serving_queue_depth",
                "Micro-batcher backlog (requests queued across shots "
                "buckets)",
                "gauge", self.queue_depth,
            )
            lines += self.adapt_ms.render(
                "serving_adapt_latency_ms",
                "End-to-end dispatch latency (upload + device + readback)",
            )
            lines += self.queue_ms.render(
                "serving_queue_latency_ms",
                "Micro-batcher queue wait per dispatch",
            )
            return "\n".join(lines) + "\n"


class FanoutSink:
    """Tee one telemetry record stream into several sinks (JSONL log +
    metrics registry is the serving shape). Write errors in one sink
    must not starve the others."""

    def __init__(self, *sinks):
        self.sinks = [s for s in sinks if s is not None]

    def write(self, record: Dict[str, Any]) -> None:
        # every sink sees every record even when an earlier one raises
        # (a full JSONL disk must not blind the metrics endpoint); the
        # first error still surfaces after delivery, same as a lone sink
        first_error: Optional[BaseException] = None
        for sink in self.sinks:
            try:
                sink.write(record)
            except Exception as e:  # noqa: BLE001 - per-sink isolation
                if first_error is None:
                    first_error = e
        if first_error is not None:
            raise first_error

    def close(self) -> None:
        first_error: Optional[BaseException] = None
        for sink in self.sinks:
            close = getattr(sink, "close", None)
            if close is None:
                continue
            try:
                close()
            except Exception as e:  # noqa: BLE001 - per-sink isolation
                if first_error is None:
                    first_error = e
        if first_error is not None:
            raise first_error


class _Handler(BaseHTTPRequestHandler):
    metrics: ServingMetrics  # set per server class below
    readiness: Optional[Callable[[], Mapping[str, bool]]] = None

    def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler API
        if self.path.split("?")[0] in ("/metrics", "/"):
            body = self.metrics.render().encode()
            self.send_response(200)
            self.send_header(
                "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
            )
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif self.path == "/healthz":
            self._healthz()
        else:
            self.send_response(404)
            self.end_headers()

    def _healthz(self) -> None:
        """Pool readiness: 503 until EVERY replica's warmup completed
        (per-replica status in the body); the readiness-less single-
        engine shape keeps the unconditional 200."""
        if self.readiness is None:
            code, body = 200, "ok\n"
        else:
            try:
                states = dict(self.readiness())
            except Exception as e:  # noqa: BLE001 - a probe must answer,
                # not crash the scrape thread
                states, e_line = {}, f"readiness probe failed: {e!r}\n"
                code, body = 503, e_line
            else:
                all_ready = bool(states) and all(states.values())
                code = 200 if all_ready else 503
                body = ("ok\n" if all_ready else "warming\n") + "".join(
                    f"replica {rid}: "
                    f"{'ready' if ok else 'not-ready'}\n"
                    for rid, ok in sorted(states.items())
                )
        payload = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", "text/plain")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def log_message(self, fmt, *args):  # silence per-scrape stderr spam
        pass


class MetricsServer:
    """Background-thread HTTP server exposing ``/metrics`` (+
    ``/healthz``). ``port=0`` binds an ephemeral port — read ``.port``
    after construction. ``readiness`` (optional; e.g.
    ``ReplicaSet.readiness``) turns ``/healthz`` into a pool-readiness
    probe: 503 until every replica reports ready. ``close()`` shuts the
    server down and joins the thread; the server thread is a daemon
    either way, so a crashed serving process never hangs on it."""

    def __init__(self, metrics: ServingMetrics, port: int = 0,
                 host: str = "127.0.0.1",
                 readiness: Optional[Callable[[], Mapping[str, bool]]] = None):
        self.metrics = metrics

        class _BoundHandler(_Handler):
            pass

        _BoundHandler.metrics = metrics
        _BoundHandler.readiness = staticmethod(readiness) if readiness else None
        self._httpd = ThreadingHTTPServer((host, port), _BoundHandler)
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="serving-metrics",
            daemon=True,
        )
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    def close(self) -> None:
        self._httpd.shutdown()
        self._thread.join(5.0)
        self._httpd.server_close()


def parse_prometheus_text(text: str) -> Dict[str, Dict[str, float]]:
    """Parse exposition text into ``{metric_name: {labels_blob: value}}``
    (``labels_blob`` '' for unlabelled series). Used by the tests and the
    CI trace-smoke job to assert the endpoint speaks valid text format —
    a parse error raises ValueError naming the line."""
    out: Dict[str, Dict[str, float]] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            series, value = line.rsplit(" ", 1)
            if "{" in series:
                name, labels = series.split("{", 1)
                if not labels.endswith("}"):
                    raise ValueError("unterminated label set")
                labels = labels[:-1]
            else:
                name, labels = series, ""
            out.setdefault(name, {})[labels] = float(value)
        except ValueError as e:
            raise ValueError(
                f"prometheus text line {lineno} unparseable: {line!r} ({e})"
            ) from e
    return out
