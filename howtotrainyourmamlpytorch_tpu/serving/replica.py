"""Shared-nothing multi-replica serving: one full engine per device slice.

One ``ServingEngine`` owns one device and one process ceiling caps
``tenants_per_sec``; the pool shape is horizontal: ``ReplicaSet``
partitions the visible devices into DISJOINT slices
(``partition_devices``) and runs one complete serving stack per slice —
own AOT program ladder, own adapted-params LRU, own ``MicroBatcher``
worker thread, own strict ``RetraceDetector`` — with nothing shared but
the telemetry sink (records are attributable anyway: every pooled
engine tags its records with ``replica_id``, schema v11). On CPU/CI the
replicas come from ``--xla_force_host_platform_device_count`` (the
``serve-bench --replicas`` path forces it), so the whole pool is
testable without a TPU.

``Replica`` is the unit the front tier talks to. It PROXIES the engine
face the ``MicroBatcher`` consumes (``serve_group`` / ``_validate`` /
``tracer`` / ``max_tenants`` / ``cfg``) and adds the two things the
engine alone cannot provide:

* **swap atomicity** — ``serve_group`` runs under the replica's swap
  lock, so ``swap_engine`` (the checkpoint-rollover path,
  serving/refresh.py) exchanges the engine BETWEEN dispatches: in-flight
  work completes on the old snapshot, queued requests flow onto the new
  one, and no request is ever dropped. The standby engine must arrive
  warmed — the swap itself is a pointer exchange and performs zero XLA
  compiles (asserted via the process compile counter and reported in
  the swap stats);
* **health + circuit-breaking surface** — ``healthy`` folds the
  engine's dead flag, the batcher worker's liveness and the tripped
  latch; ``trip`` drains the replica immediately (queued futures fail
  with the chained root cause — the PR-13 batcher-crash semantics — and
  the never-warmed/dead engine skips the drain dispatches entirely) so
  the router can re-home its traffic.

``ReplicaSet`` builds and owns the replicas: per-slice device-pinned
engines (the engine AOT-compiles against its device's sharding), a
shared sink, per-replica artifact roots under ``export_root`` (serialized
executables record their device assignment, so replicas must never load
each other's artifacts — the per-replica subdir plus the ``device_id``
manifest key enforce it), and the pool-level ``rollup()`` /
``readiness()`` the bench line and the ``/healthz`` endpoint report.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional, Sequence

from ..config import MAMLConfig
from .batcher import MicroBatcher, engine_ready
from .engine import ServingEngine


def partition_devices(devices: Sequence[Any], n_replicas: int) -> List[List[Any]]:
    """Partition ``devices`` into ``n_replicas`` DISJOINT equal slices
    (size ``len(devices) // n_replicas``; a non-dividing remainder is
    left unassigned with the slices still disjoint). Shared-nothing by
    construction: no device appears in two slices."""
    if n_replicas < 1:
        raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
    if n_replicas > len(devices):
        raise ValueError(
            f"cannot run {n_replicas} shared-nothing replicas on "
            f"{len(devices)} visible device(s) — each replica needs its "
            "own disjoint slice (on CPU, force more virtual devices via "
            "--xla_force_host_platform_device_count / serve-bench "
            "--replicas)"
        )
    per = len(devices) // n_replicas
    return [
        list(devices[k * per:(k + 1) * per]) for k in range(n_replicas)
    ]


class _ReplicaMetricsAdapter:
    """Binds a ``replica_id`` onto the batcher's queue-depth gauge
    reports so the shared ``ServingMetrics`` registry keeps one
    per-replica series (the batcher itself stays replica-agnostic)."""

    def __init__(self, metrics, replica_id: int):
        self._metrics = metrics
        self._replica_id = replica_id

    def observe_queue_depth(self, depth: int) -> None:
        self._metrics.observe_queue_depth(depth, replica=self._replica_id)


class Replica:
    """One shared-nothing serving replica: engine + micro-batcher +
    swap lock + health latch. Implements the engine face the
    ``MicroBatcher`` consumes, so the batcher dispatches through the
    replica (and therefore under the swap lock) without modification."""

    def __init__(
        self,
        replica_id: int,
        devices: Sequence[Any],
        engine: ServingEngine,
        max_wait_ms: Optional[float] = None,
        metrics=None,
    ):
        import threading

        self.replica_id = int(replica_id)
        self.devices = list(devices)
        self.engine = engine
        self._swap_lock = threading.Lock()
        self._trip_lock = threading.Lock()
        self._tripped = False
        self._trip_cause: Optional[BaseException] = None
        self._closed = False
        batcher_metrics = (
            _ReplicaMetricsAdapter(metrics, self.replica_id)
            if metrics is not None else None
        )
        self.batcher = MicroBatcher(
            self, max_wait_ms=max_wait_ms, metrics=batcher_metrics
        )

    # -- the engine face the MicroBatcher consumes -------------------------

    @property
    def cfg(self) -> MAMLConfig:
        return self.engine.cfg

    @property
    def tracer(self):
        return self.engine.tracer

    @property
    def max_tenants(self) -> int:
        return self.engine.max_tenants

    @property
    def warmup_stats(self) -> Dict[str, Any]:
        return self.engine.warmup_stats

    @property
    def _dead(self) -> Optional[BaseException]:
        return self.engine._dead

    @property
    def _tenants_served(self) -> int:
        # proxied for engine_ready's lazily-served-engine drain gate
        return self.engine._tenants_served

    def _validate(self, req) -> int:
        return self.engine._validate(req)

    def _record(self, **fields) -> None:
        # proxied so the micro-batcher's deadline records flow through
        # the engine's sink with this replica's replica_id tag
        self.engine._record(**fields)

    def serve_group(self, requests, queue_ms: float = 0.0):
        # the swap lock is what makes checkpoint rollover dispatch-atomic:
        # swap_engine waits out an in-flight dispatch, and the next
        # dispatch reads the fresh engine reference
        with self._swap_lock:
            return self.engine.serve_group(requests, queue_ms=queue_ms)

    # -- front-tier surface ------------------------------------------------

    def submit(self, request):
        """Enqueue one request into this replica's micro-batcher."""
        if self._tripped:
            raise RuntimeError(
                f"replica {self.replica_id} is circuit-broken "
                "(root cause chained below)"
            ) from self._trip_cause
        return self.batcher.submit(request)

    def queue_depth(self) -> int:
        return self.batcher.queue_depth()

    @property
    def ready(self) -> bool:
        """Warmup completed and the replica can take traffic."""
        return not self._tripped and engine_ready(self.engine)

    @property
    def healthy(self) -> bool:
        """Fit for routing NOW: not tripped, engine alive + warmed,
        batcher worker running. The router skips unhealthy replicas;
        it only TRIPS the ``broken`` subset."""
        return (
            not self._tripped
            and not self._closed
            and engine_ready(self.engine)
            and self.batcher.worker_alive
        )

    @property
    def broken(self) -> bool:
        """Irrecoverably unfit: engine dead, batcher worker dead, or
        closed — what the router's health sweep TRIPS (drains + fails
        the backlog). Deliberately NARROWER than ``not healthy``: a
        merely not-yet-warmed replica (pool warmup still running, or a
        lazily-compiling deployment) is skipped by routing but must
        never be destructively tripped — it becomes healthy the moment
        its warmup completes."""
        return (
            self._closed
            or self.engine._dead is not None
            or not self.batcher.worker_alive
        )

    @property
    def tripped(self) -> bool:
        return self._tripped

    @property
    def trip_cause(self) -> Optional[BaseException]:
        return self._trip_cause

    def trip(self, cause: Optional[BaseException] = None) -> bool:
        """Circuit-break this replica: fail every queued future with the
        chained root cause and shut the batcher down WITHOUT the drain
        dispatches (a dead/never-warmed engine cannot serve them — the
        immediate-shutdown path the batcher close fix added).
        Idempotent; returns True only for the call that actually
        transitioned (latched under a lock, so two concurrent sweeps
        can never both claim — or double-count — one trip)."""
        with self._trip_lock:
            if self._tripped:
                return False
            self._tripped = True
            self._trip_cause = (
                cause if cause is not None else self.engine._dead
            )
        err = RuntimeError(
            f"replica {self.replica_id} circuit-broken: traffic re-homed "
            "(root cause chained below)"
        )
        err.__cause__ = self._trip_cause
        # fail the backlog FIRST with the named cause, then stop the
        # worker on the no-drain path — a dead worker's join is immediate
        self.batcher._fail_pending(err)
        self.batcher.close(drain=False)
        return True

    # -- rollover ----------------------------------------------------------

    def swap_engine(self, standby: ServingEngine) -> Dict[str, Any]:
        """Atomically swap the served engine for a WARMED standby.

        Zero dropped requests by construction (queued requests simply
        dispatch on the new engine; an in-flight dispatch completes on
        the old one first — the swap lock serializes) and zero XLA
        compiles at swap time (the standby compiled/deserialized during
        ITS warmup, off the hot path; the returned stats carry the
        process compile-counter delta across the swap as proof).
        """
        from . import export as export_lib

        if not standby.warmup_stats:
            raise ValueError(
                "standby engine must complete warmup() before the swap — "
                "swapping a cold engine would pay its whole compile bill "
                "on the first live request"
            )
        compiles0 = export_lib.xla_compile_count()
        start = time.perf_counter()
        with self._swap_lock:
            old = self.engine
            # the rollup describes the REPLICA's serving history: carry
            # the retired engine's counters/latency windows/span into
            # the standby so a mid-load rollover doesn't silently drop
            # every pre-swap dispatch from the pool rollup (both
            # engines are quiescent under the lock)
            standby.adopt_serving_history(old)
            # the replica's stall watchdog survives the rollover: the
            # standby beats the SAME watchdog the retired engine did, so
            # a swap never leaves the replica unwatched (and never
            # leaks a second monitor thread)
            dog = getattr(old, "watchdog", None)
            if dog is not None and getattr(standby, "watchdog", None) is None:
                standby.watchdog = dog
            self.engine = standby
        swap_ms = (time.perf_counter() - start) * 1e3
        return {
            "replica_id": self.replica_id,
            "swap_ms": round(swap_ms, 3),
            "xla_compiles_at_swap": (
                export_lib.xla_compile_count() - compiles0
            ),
            "old_snapshot_dead": old._dead is not None,
        }

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if not self._tripped:
            self.batcher.close()


class ReplicaSet:
    """The shared-nothing replica pool: N device-pinned serving stacks.

    :param cfg: the serving config; ``serving_replicas`` is the default
        pool width (overridable via ``n_replicas``).
    :param state: the servable snapshot every replica starts from (each
        engine takes its own private on-device copy).
    :param n_replicas: pool width override.
    :param devices: the device list to partition (default:
        ``jax.devices()``).
    :param sink: ONE telemetry sink shared by every replica — records
        are per-replica attributable via their ``replica_id`` field.
    :param metrics: optional ``ServingMetrics`` registry; queue-depth
        gauges are reported per replica through a bound adapter. (Tee it
        into ``sink`` with ``FanoutSink`` so counters aggregate too —
        the serve-bench wiring.)
    :param export_root: optional AOT-artifact root. Each replica keeps
        its own subdirectory (``replica<k>/``) because serialized
        executables record their device assignment — warmup saves on the
        first cold start and every later warmup (including rollover
        standbys, which reuse the artifact fingerprint: the programs
        depend on shapes, never on snapshot values) deserializes with
        zero XLA compiles.

    Remaining keyword args mirror the ``ServingEngine`` ctor and are
    applied to every replica's engine (and to rollover standbys).
    """

    def __init__(
        self,
        cfg: MAMLConfig,
        state,
        n_replicas: Optional[int] = None,
        devices: Optional[Sequence[Any]] = None,
        shots_buckets: Optional[Sequence[int]] = None,
        sink=None,
        strict_retrace: bool = True,
        ingest: Optional[str] = None,
        store=None,
        cache_size: Optional[int] = None,
        snapshot_id: Optional[str] = None,
        tracer=None,
        metrics=None,
        export_root: Optional[str] = None,
        max_wait_ms: Optional[float] = None,
    ):
        import jax

        self.cfg = cfg
        self.n_replicas = (
            cfg.serving_replicas if n_replicas is None else int(n_replicas)
        )
        devices = list(jax.devices()) if devices is None else list(devices)
        self.slices = partition_devices(devices, self.n_replicas)
        if len(devices) > self.n_replicas:
            import warnings

            # the serving programs are single-device: each replica's
            # engine serves from its slice's LEAD device only, so every
            # device beyond one-per-replica (wider slices AND the
            # non-dividing remainder) is idle — be loud about it
            # instead of silently using n_replicas/len(devices) of the
            # machine
            warnings.warn(
                f"ReplicaSet: {self.n_replicas} replica(s) over "
                f"{len(devices)} devices leaves "
                f"{len(devices) - self.n_replicas} device(s) idle (the "
                "serving engine is single-device; one replica per "
                "device is the full-utilization shape — raise "
                "n_replicas/serving_replicas to the device count)",
                stacklevel=2,
            )
        self.sink = sink
        self.metrics = metrics
        self.export_root = export_root or None
        self._engine_kwargs: Dict[str, Any] = dict(
            shots_buckets=shots_buckets,
            sink=sink,
            strict_retrace=strict_retrace,
            ingest=ingest,
            store=store,
            cache_size=cache_size,
            tracer=tracer,
        )
        self.replicas: List[Replica] = [
            Replica(
                k,
                self.slices[k],
                self._build_engine(k, state, snapshot_id),
                max_wait_ms=max_wait_ms,
                metrics=metrics,
            )
            for k in range(self.n_replicas)
        ]
        self._watchdogs: Dict[int, Any] = {}
        self._watchdog_cfg: Optional[Dict[str, Any]] = None

    def _build_engine(
        self, replica_id: int, state, snapshot_id: Optional[str]
    ) -> ServingEngine:
        return ServingEngine(
            self.cfg,
            state,
            snapshot_id=snapshot_id,
            device=self.slices[replica_id][0],
            replica_id=replica_id,
            **self._engine_kwargs,
        )

    def artifact_dir_for(self, replica_id: int) -> Optional[str]:
        """This replica's private AOT-artifact root (None when the pool
        has no export root). Per-replica because the serialized
        executables are device-pinned."""
        if self.export_root is None:
            return None
        return os.path.join(self.export_root, f"replica{replica_id}")

    def warmup(self) -> float:
        """Warm every replica (serially — compile determinism and one
        readable compile-counter stream); returns total wall seconds."""
        start = time.perf_counter()
        for r in self.replicas:
            r.engine.warmup(
                artifact_dir=self.artifact_dir_for(r.replica_id)
            )
        return time.perf_counter() - start

    # -- watchdogs ---------------------------------------------------------

    def attach_watchdogs(self, timeout_s: float, sink=None,
                         recorder=None) -> List[Any]:
        """One stall watchdog PER replica (the single-engine
        ``attach_serving_watchdog`` shape, pooled): each replica's
        engine beats its own watchdog, so one wedged replica fires one
        replica-tagged ``watchdog_stall`` record (+ flight-recorder
        incident) while the rest of the pool keeps serving silently.
        Watchdogs survive ``swap_engine`` rollovers (the standby
        inherits the retired engine's watchdog under the swap lock) and
        are re-attached automatically by ``restart_replica``. The pool
        owns their lifecycle: ``close()`` stops them."""
        from .engine import attach_serving_watchdog

        self._watchdog_cfg = {
            "timeout_s": float(timeout_s),
            "sink": sink,
            "recorder": recorder,
        }
        for r in self.replicas:
            self._watchdogs[r.replica_id] = attach_serving_watchdog(
                r.engine, timeout_s, sink=sink, recorder=recorder,
                replica_id=r.replica_id,
            )
        return [self._watchdogs[r.replica_id] for r in self.replicas]

    def _rewire_watchdog(self, replica: Replica) -> None:
        """Move ``replica``'s watchdog slot onto its (fresh) engine —
        the restart_replica half of watchdog continuity: the broken
        replica's watchdog is stopped, a new one watches the
        replacement."""
        if self._watchdog_cfg is None:
            return
        from .engine import attach_serving_watchdog

        old = self._watchdogs.pop(replica.replica_id, None)
        if old is not None:
            old.stop()
        self._watchdogs[replica.replica_id] = attach_serving_watchdog(
            replica.engine,
            self._watchdog_cfg["timeout_s"],
            sink=self._watchdog_cfg["sink"],
            recorder=self._watchdog_cfg["recorder"],
            replica_id=replica.replica_id,
        )

    # -- standby / recovery ------------------------------------------------

    def build_standby_engine(
        self, replica_id: int, state, snapshot_id: Optional[str] = None
    ) -> ServingEngine:
        """A fresh engine for ``replica_id``'s device slice over a NEW
        snapshot — the rollover standby slot (serving/refresh.py). The
        caller warms it (off the hot path) and then
        ``Replica.swap_engine``s it in."""
        return self._build_engine(replica_id, state, snapshot_id)

    def restart_replica(
        self, replica_id: int, state, snapshot_id: Optional[str] = None
    ) -> Replica:
        """Replace a (typically circuit-broken) replica with a fresh
        engine + batcher over ``state``; the new replica is warmed and
        immediately routable (the recover half of
        circuit-break -> re-home -> recover)."""
        old = self.replicas[replica_id]
        if not old.tripped:
            old.close()
        engine = self._build_engine(replica_id, state, snapshot_id)
        engine.warmup(artifact_dir=self.artifact_dir_for(replica_id))
        fresh = Replica(
            replica_id,
            self.slices[replica_id],
            engine,
            max_wait_ms=self.replicas[replica_id].batcher.max_wait_ms,
            metrics=self.metrics,
        )
        self.replicas[replica_id] = fresh
        self._rewire_watchdog(fresh)
        return fresh

    # -- pool surfaces -----------------------------------------------------

    def readiness(self) -> Dict[str, bool]:
        """Per-replica readiness — the ``/healthz`` payload (the
        endpoint reports 503 until every value is True)."""
        return {str(r.replica_id): r.ready for r in self.replicas}

    def rollup(self) -> Dict[str, Any]:
        """Per-replica rollups (each emits its own telemetry rollup
        record, ``replica_id``-tagged) plus the pool aggregate:
        ``tenants_per_sec`` over the UNION wall-clock span (first
        dispatch start anywhere to last dispatch end anywhere — the
        honest aggregate: per-replica rates must not be summed, their
        spans overlap) and ``cache_hit_rate`` as pool hits over pool
        lookups."""
        import numpy as np

        from .metrics import LogHistogram

        per = []
        starts, ends = [], []
        adapt_samples: List[float] = []
        queue_samples: List[float] = []
        h2d_samples: List[float] = []
        batch_samples: List[float] = []
        dispatch_samples: List[float] = []
        sync_samples: List[float] = []
        tenants = dispatches = retraces = hits = lookups = 0
        window_dropped = 0
        # the pool-level distributions: EXACT bucket-by-bucket merges of
        # the per-replica log histograms (no sample window in the way)
        pool_hist = {
            "adapt_ms": LogHistogram(), "queue_ms": LogHistogram(),
        }
        any_cache = False
        for r in self.replicas:
            eng = r.engine
            ru = dict(eng.rollup())
            ru["replica_id"] = r.replica_id
            per.append(ru)
            tenants += eng._tenants_served
            dispatches += ru["dispatches"]
            retraces += ru["retraces"]
            window_dropped += ru["window_dropped"]
            for stage, hist in pool_hist.items():
                hist.merge(eng._hist[stage])
            adapt_samples.extend(eng._adapt_ms)
            queue_samples.extend(eng._queue_ms)
            h2d_samples.extend(eng._h2d_bytes)
            batch_samples.extend(eng._batch_ms)
            dispatch_samples.extend(eng._dispatch_ms)
            sync_samples.extend(eng._sync_ms)
            if eng.cache_size > 0:
                any_cache = True
                hits += eng.cache_hits
                lookups += eng.cache_hits + eng.cache_misses
            if eng._span_start is not None and eng._span_end is not None:
                starts.append(eng._span_start)
                ends.append(eng._span_end)
        span_s = (max(ends) - min(starts)) if starts else 0.0
        adapt = np.asarray(adapt_samples, np.float64)
        queue = np.asarray(queue_samples, np.float64)
        h2d = np.asarray(h2d_samples, np.float64)
        batch = np.asarray(batch_samples, np.float64)
        disp = np.asarray(dispatch_samples, np.float64)
        syncs = np.asarray(sync_samples, np.float64)
        return {
            "replicas": self.n_replicas,
            "per_replica": per,
            "tenants": tenants,
            "dispatches": dispatches,
            "retraces": retraces,
            # pooled latency: percentiles over the MERGED per-dispatch
            # samples (each replica contributes its window)
            "adapt_ms_p50": (
                round(float(np.percentile(adapt, 50)), 3) if adapt.size
                else None
            ),
            "adapt_ms_p95": (
                round(float(np.percentile(adapt, 95)), 3) if adapt.size
                else None
            ),
            "queue_ms_p50": (
                round(float(np.percentile(queue, 50)), 3) if queue.size
                else None
            ),
            "batch_ms_mean": (
                round(float(np.mean(batch)), 3) if batch.size else None
            ),
            "dispatch_ms_p50": (
                round(float(np.percentile(disp, 50)), 3) if disp.size
                else None
            ),
            "sync_ms_p50": (
                round(float(np.percentile(syncs, 50)), 3) if syncs.size
                else None
            ),
            "ingest": self.replicas[0].engine.ingest,
            "h2d_bytes_per_dispatch": (
                round(float(np.mean(h2d)), 1) if h2d.size else None
            ),
            "tenants_per_sec": (
                round(tenants / span_s, 3) if span_s > 0 else None
            ),
            "cache_hit_rate": (
                round(hits / lookups, 4) if any_cache and lookups else None
            ),
            "window_dropped": window_dropped,
            "adapt_ms_hist": pool_hist["adapt_ms"].to_dict(),
            "queue_ms_hist": pool_hist["queue_ms"].to_dict(),
        }

    def close(self) -> None:
        for dog in self._watchdogs.values():
            dog.stop()
        self._watchdogs.clear()
        for r in self.replicas:
            # drop the engine's reference too — beats to a stopped dog
            # are harmless but a dangling pointer invites double-stops
            if getattr(r.engine, "watchdog", None) is not None:
                r.engine.watchdog = None
            r.close()
