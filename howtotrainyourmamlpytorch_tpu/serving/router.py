"""Cache-affinity front tier for the shared-nothing replica pool.

Scaling the serving path N ways must not dilute the adapted-params
cache N ways: a repeat tenant only hits if it lands on the replica that
adapted it last time. The router therefore routes by **cache
affinity** — a stable fingerprint of the support-set content, the
content core of the engine's adapted-params cache key (its shots and
snapshot-salt suffixes are deliberately excluded: same-support tenants
co-locate regardless of shots, and a checkpoint rollover changes cache
keys without reshuffling homes) picks each request's HOME replica. The fingerprint is SHA-1-based and therefore
stable across process restarts and machines (never the builtin
``hash()``, whose per-process seed would reshuffle every tenant on
every restart and cold the whole pool).

Two pressure valves sit on top of pure affinity:

* **queue-depth spillover** — when the home replica's micro-batcher
  backlog reaches ``serving_router_spill_depth``, the request goes to
  the least-loaded healthy replica instead: a cold adapt there beats
  queueing behind a saturated home (the miss re-populates that
  replica's cache, so a persistently hot tenant converges to wherever
  it keeps landing);
* **circuit breaking** — every submit sweeps replica health (engine
  dead flag, batcher worker liveness — the signals the existing
  watchdog/health surfaces set). A replica that turns BROKEN is
  TRIPPED: its queued futures fail immediately with the chained root
  cause (the PR-13 batcher-crash semantics, skipping the drain
  dispatches a broken engine cannot serve) and its traffic is
  re-homed deterministically to the next healthy replica on the ring
  (a merely not-yet-warmed replica is skipped by routing, never
  tripped — it becomes routable when its warmup completes) —
  so every live request sees at most one failure and every new request
  sees none. A replacement replica (``ReplicaSet.restart_replica``)
  is picked up automatically: the router reads the pool's live replica
  list on every submit.
"""

from __future__ import annotations

import hashlib
import threading
import time
from typing import Any, Dict, List, Optional


class AllReplicasUnhealthyError(RuntimeError):
    """Every replica in the pool is circuit-broken/dead — there is
    nowhere to route. Carries the per-replica causes."""

    def __init__(self, causes: Dict[int, Optional[BaseException]]):
        self.causes = causes
        detail = "; ".join(
            f"replica {rid}: {cause!r}" for rid, cause in causes.items()
        )
        super().__init__(
            f"no healthy replica to route to ({detail or 'empty pool'})"
        )


def request_fingerprint(request) -> str:
    """Stable content fingerprint of a request's ADAPTATION identity:
    the support-set CONTENT — the content core of the engine's
    adapted-params cache key, deliberately minus its two suffixes: the
    engine-local snapshot salt (homes must survive a checkpoint
    rollover; cache entries must not) and the shots count (same-support
    tenants co-locate regardless of shots, which can only help
    locality; a shots change still misses the cache on its home, same
    as anywhere).

    SHA-1 over the raw bytes: two processes (or two restarts of one)
    always agree, which is what keeps LRU hit rates intact across
    restarts of the front tier. The content recipe is
    ``batcher.update_support_digest`` — the SAME function the engine's
    ``_cache_key`` consumes, so the affinity identity can never
    silently drift from the cache identity.
    """
    from .batcher import update_support_digest

    h = hashlib.sha1()
    update_support_digest(h, request)
    return h.hexdigest()


def home_replica(fingerprint: str, n_replicas: int) -> int:
    """The fingerprint's home replica: the leading 64 fingerprint bits
    mod the pool width. Pure arithmetic on the stable fingerprint —
    restart-invariant by construction."""
    if n_replicas < 1:
        raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
    return int(fingerprint[:16], 16) % n_replicas


class ReplicaRouter:
    """Route ``submit()`` traffic over a ``ReplicaSet`` (or a plain
    replica list) by cache affinity with spillover + circuit breaking.

    :param pool: a ``serving.replica.ReplicaSet`` (live view — replicas
        replaced via ``restart_replica`` are picked up automatically) or
        a fixed replica list.
    :param spill_depth: home-replica backlog at which the request
        spills to the least-loaded healthy replica (default: the
        config's ``serving_router_spill_depth``).
    """

    def __init__(self, pool, spill_depth: Optional[int] = None):
        self._pool = pool
        if spill_depth is None:
            cfg = getattr(pool, "cfg", None)
            spill_depth = (
                cfg.serving_router_spill_depth if cfg is not None else 8
            )
        if spill_depth < 1:
            raise ValueError(
                f"spill_depth must be >= 1, got {spill_depth}"
            )
        self.spill_depth = int(spill_depth)
        self._lock = threading.Lock()
        # routing decision counters (the bench/inspect surface)
        self.routed_total = 0
        self.routed_affinity = 0
        self.routed_spill = 0
        self.routed_rehomed = 0
        self.trips = 0
        self.routed_by_replica: Dict[int, int] = {}

    @property
    def replicas(self) -> List[Any]:
        return list(getattr(self._pool, "replicas", self._pool))

    # -- health ------------------------------------------------------------

    def _sweep_health(self, replicas: List[Any]) -> None:
        """Trip (drain + latch) every replica that turned BROKEN
        (engine dead, worker dead, closed) — its queued futures fail
        NOW with the chained cause instead of hanging until a timeout.
        A merely not-yet-warmed replica is unhealthy-for-routing but
        NOT broken: it is skipped, never destructively tripped (it
        becomes routable the moment its warmup completes)."""
        for r in replicas:
            if getattr(r, "broken", not r.healthy) and not r.tripped:
                # trip() returns True only for the call that actually
                # transitioned (Replica latches it under a lock), so
                # two concurrent sweeps can never double-count one trip
                if r.trip():
                    with self._lock:
                        self.trips += 1

    # -- routing -----------------------------------------------------------

    def _decide(self, request):
        """The routing decision: returns ``(target, kind)`` with kind
        in ``('affinity', 'spill', 'rehomed')`` — no stats recorded."""
        replicas = self.replicas
        n = len(replicas)
        if n == 0:
            raise AllReplicasUnhealthyError({})
        self._sweep_health(replicas)
        home_id = home_replica(request_fingerprint(request), n)
        # deterministic ring walk from the home: a broken home re-homes
        # to the SAME fallback for every request (and every router
        # process), preserving what cache locality can be preserved
        home = None
        for off in range(n):
            cand = replicas[(home_id + off) % n]
            if cand.healthy:
                home = cand
                break
        if home is None:
            raise AllReplicasUnhealthyError(
                {r.replica_id: r.trip_cause for r in replicas}
            )
        rehomed = home.replica_id != replicas[home_id].replica_id
        target, spilled = home, False
        if home.queue_depth() >= self.spill_depth:
            healthy = [r for r in replicas if r.healthy]
            least = min(healthy, key=lambda r: r.queue_depth())
            if (
                least.replica_id != home.replica_id
                and least.queue_depth() < home.queue_depth()
            ):
                target, spilled = least, True
        kind = "spill" if spilled else ("rehomed" if rehomed else
                                        "affinity")
        return target, kind

    def _record_route(self, target, kind: str) -> None:
        with self._lock:
            self.routed_total += 1
            if kind == "spill":
                self.routed_spill += 1
            elif kind == "rehomed":
                self.routed_rehomed += 1
            else:
                self.routed_affinity += 1
            self.routed_by_replica[target.replica_id] = (
                self.routed_by_replica.get(target.replica_id, 0) + 1
            )

    def route(self, request) -> Any:
        """The routing decision only (no submit): returns the target
        replica and records it in the stats. Split out so tests can
        assert placement without dispatching."""
        target, kind = self._decide(request)
        self._record_route(target, kind)
        return target

    def submit(self, request):
        """Route one request and enqueue it on the chosen replica's
        micro-batcher; returns the replica's pending future.

        The decision and Replica.submit() are two steps, so another
        thread's health sweep can trip the chosen replica in between;
        that race re-routes (the next decision sees the trip and walks
        the ring) instead of surfacing a circuit-broken error for a
        request that never had a healthy-home failure — bounded by the
        pool width, since each retry consumes one tripped replica. The
        stats record only the decision that actually ENQUEUED, so
        ``routed_total`` always equals requests accepted (retried
        failed attempts are not double-counted)."""
        route_start = time.perf_counter()
        for _ in range(len(self.replicas) + 1):
            target, kind = self._decide(request)
            try:
                pending = target.submit(request)
            except RuntimeError:
                if target.healthy:
                    raise  # a real submit error, not the trip race
                continue
            self._record_route(target, kind)
            # the 'route' share of the deadline record's stage
            # attribution: decision time (health sweep + ring walk +
            # any trip-race retries) ahead of the batcher enqueue
            pending.route_ms = (time.perf_counter() - route_start) * 1e3
            return pending
        raise AllReplicasUnhealthyError(
            {r.replica_id: r.trip_cause for r in self.replicas}
        )

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "routed_total": self.routed_total,
                "routed_affinity": self.routed_affinity,
                "routed_spill": self.routed_spill,
                "routed_rehomed": self.routed_rehomed,
                "trips": self.trips,
                "routed_by_replica": dict(self.routed_by_replica),
                "spill_depth": self.spill_depth,
            }
