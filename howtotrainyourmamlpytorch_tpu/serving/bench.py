"""``cli serve-bench`` — closed-loop load generator for the serving path.

Drives a ``ServingEngine`` with synthetic adapt-on-request traffic that
cycles through MIXED tenant-group sizes (1..max_tenants) and every
configured shots bucket — the steady-state mixed-bucket pattern the
zero-retrace contract must hold under (the engine's RetraceDetector runs
strict: any mid-run recompile fails the bench). Prints ONE JSON line:

.. code-block:: json

   {"metric": "serving_adaptation_latency_ms", "value": <p50>,
    "unit": "ms", "adaptation_latency_ms_p50": ..., "..._p95": ...,
    "tenants_per_sec": ..., "dispatches": ..., "tenants": ...,
    "warmup_seconds": ..., "retraces": 0, "backend": ...,
    "ingest": "f32|uint8|index", "h2d_bytes_per_dispatch": ...,
    "cache_hit_rate": ..., "warmup_mode": "compile|artifacts",
    "warmup_xla_compiles": ..., "bucket_ladder": [...],
    "shots_buckets": [...]}

With ``--telemetry PATH`` the per-dispatch ``serving`` records plus the
final rollup go to a schema-v9 JSONL log that ``cli inspect summary``
renders and the CI serving-smoke job schema-validates. ``--checkpoint
DIR`` serves a real training checkpoint (restored READ-ONLY) instead of
a fresh ``init_state`` snapshot; ``--fast`` shrinks the workload to a
seconds-scale smoke (the CI gate). ``--ingest`` selects the serving
ingest tier (the H2D bytes land in the JSON line, so the uint8/index
reductions are measurable under the same closed-loop protocol);
``--repeat-tenant-fraction`` mixes repeat tenants in (adapted-params
cache hits — ``cache_hit_rate`` lands in the line); ``--export-dir``
warms the engine from AOT export artifacts (``cli serve-export``),
reporting ``warmup_mode`` and the warmup's XLA compile count.

Exit codes: 0 on success (including the emitted line), nonzero on any
failure — a retrace, a schema-invalid record, a broken engine.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

import numpy as np


def _bench_cfg(args):
    """The generator's config: the user's JSON when given, else a small
    deterministic serving config (``--fast`` shrinks it further)."""
    from ..config import MAMLConfig

    if args.config:
        cfg = MAMLConfig.from_json_file(args.config)
    elif args.fast:
        cfg = MAMLConfig(
            dataset_name="omniglot_dataset",
            image_height=10, image_width=10, image_channels=1,
            num_classes_per_set=3, num_samples_per_class=1,
            num_target_samples=2, batch_size=2, cnn_num_filters=4,
            num_stages=2, number_of_training_steps_per_iter=2,
            number_of_evaluation_steps_per_iter=2, use_remat=False,
            serving_bucket_ladder=[1, 2],
            serving_max_tenants_per_dispatch=2,
            compilation_cache_dir="",
        )
    else:
        cfg = MAMLConfig(
            dataset_name="omniglot_dataset",
            image_height=28, image_width=28, image_channels=1,
            num_classes_per_set=5, num_samples_per_class=1,
            num_target_samples=5, batch_size=8, cnn_num_filters=32,
            num_stages=4, number_of_training_steps_per_iter=3,
            number_of_evaluation_steps_per_iter=3,
            compilation_cache_dir="",
        )
    return cfg


def bench_shots_buckets(cfg) -> List[int]:
    """The bench's shots ladder: two buckets, so even the smoke workload
    proves the mixed-bucket no-retrace contract. Shared with
    ``cli serve-export`` so exported artifact fingerprints match the
    engine serve-bench builds."""
    return sorted({cfg.num_samples_per_class,
                   cfg.num_samples_per_class + 1})


def _synth_store(cfg, rows: int = 256, seed: int = 7) -> np.ndarray:
    """A deterministic synthetic uint8 store for the index ingest."""
    rng = np.random.RandomState(seed)
    h, w, c = cfg.im_shape
    return rng.randint(0, 256, (rows, h, w, c)).astype(np.uint8)


def _synth_request(cfg, rng, shots: int, ingest: str, store_rows: int,
                   tenant_id: str):
    from .batcher import AdaptRequest, IndexRequest

    n, t = cfg.num_classes_per_set, cfg.num_target_samples
    h, w, c = cfg.im_shape
    if ingest == "index":
        return IndexRequest(
            support_idx=rng.randint(
                0, store_rows, (n, shots)
            ).astype(np.int32),
            query_idx=rng.randint(0, store_rows, (n, t)).astype(np.int32),
            labeled=True,
            tenant_id=tenant_id,
        )
    if ingest == "uint8":
        sx = rng.randint(0, 256, (n, shots, h, w, c)).astype(np.uint8)
        qx = rng.randint(0, 256, (n, t, h, w, c)).astype(np.uint8)
    else:
        sx = rng.randn(n, shots, h, w, c).astype(np.float32)
        qx = rng.randn(n, t, h, w, c).astype(np.float32)
    return AdaptRequest(
        support_x=sx,
        support_y=np.tile(np.arange(n, dtype=np.int32)[:, None], (1, shots)),
        query_x=qx,
        query_y=np.tile(np.arange(n, dtype=np.int32)[:, None], (1, t)),
        tenant_id=tenant_id,
    )


def _synth_groups(cfg, shots_buckets, n_requests: int, cap: int,
                  seed: int, ingest: str = "f32", store_rows: int = 0,
                  repeat_fraction: float = 0.0) -> List[List]:
    """Deterministic synthetic traffic as DISPATCH GROUPS: group sizes
    cycle 1..cap (every tenant bucket sees steady traffic) and each
    group's shots bucket cycles the configured ladder (every compiled
    program sees steady traffic) — the mixed-bucket pattern the
    zero-retrace contract must hold under.

    ``repeat_fraction`` > 0 makes that fraction of requests REPEAT
    TENANTS: they reuse a previously generated request's support set
    (same content fingerprint — an adapted-params-cache hit once the
    first occurrence has been adapted), modelling the
    same-tenant-returns traffic the cache fast path exists for."""
    rng = np.random.RandomState(seed)
    groups: List[List] = []
    # repeat pool per shots bucket: a reused tenant must reuse its own
    # shots count or the fingerprints can never collide
    pool: dict = {s: [] for s in shots_buckets}
    size, total, g = 1, 0, 0
    while total < n_requests:
        take = min(size, n_requests - total)
        s = shots_buckets[g % len(shots_buckets)]
        group = []
        for _ in range(take):
            if pool[s] and rng.rand() < repeat_fraction:
                prev = pool[s][rng.randint(len(pool[s]))]
                group.append(prev)
            else:
                req = _synth_request(
                    cfg, rng, s, ingest, store_rows,
                    tenant_id=f"tenant-{total + len(group)}",
                )
                pool[s].append(req)
                group.append(req)
        groups.append(group)
        total += take
        g += 1
        size = size + 1 if size < cap else 1
    return groups


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="serve-bench",
        description="Closed-loop load generator for the adapt-on-request "
                    "serving engine (latency p50/p95, tenants/sec, "
                    "zero-retrace gate)",
    )
    parser.add_argument("--fast", action="store_true",
                        help="seconds-scale smoke workload (the CI gate)")
    parser.add_argument("--config", default=None,
                        help="experiment JSON supplying the geometry and "
                             "serving_* knobs")
    parser.add_argument("--checkpoint", default=None, metavar="DIR",
                        help="serve this saved_models directory's "
                             "checkpoint (read-only restore) instead of a "
                             "fresh init_state snapshot; REQUIRES --config "
                             "with the training run's geometry (the "
                             "restore template and the compiled programs "
                             "are built from it — nothing in the "
                             "checkpoint directory records the config)")
    parser.add_argument("--model-idx", default="latest",
                        help="checkpoint index under --checkpoint "
                             "(default: latest)")
    parser.add_argument("--requests", type=int, default=None,
                        help="synthetic requests to serve (default: 8 "
                             "fast, 64 otherwise)")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--telemetry", default=None, metavar="PATH",
                        help="write serving telemetry records (JSONL, "
                             "schema v9) to this path")
    parser.add_argument("--ingest", default=None,
                        choices=["f32", "uint8", "index"],
                        help="serving ingest tier to drive (default: the "
                             "config's serving_ingest): f32 host pixels, "
                             "uint8 device-decoded pixels (~4x less H2D), "
                             "or index-only dispatch against a synthetic "
                             "resident store (<1KB H2D)")
    parser.add_argument("--repeat-tenant-fraction", type=float, default=0.0,
                        metavar="F",
                        help="fraction of requests that repeat an earlier "
                             "tenant's support set (adapted-params-cache "
                             "hits; enables the cache when > 0)")
    parser.add_argument("--cache-size", type=int, default=None,
                        help="adapted-params LRU capacity (default: the "
                             "config's serving_adapted_cache_size, or "
                             "auto-enabled when --repeat-tenant-fraction "
                             "> 0)")
    parser.add_argument("--export-dir", default=None, metavar="DIR",
                        help="AOT artifact root: warmup loads exported "
                             "executables from here (zero XLA compiles) "
                             "and falls back to compile-then-save — see "
                             "cli serve-export")
    parser.add_argument("--trace", action="store_true",
                        help="emit schema-v10 span records (request/"
                             "queue/assemble/dispatch/sync causal "
                             "timeline) into the --telemetry log; render "
                             "with `cli trace` (requires --telemetry)")
    parser.add_argument("--metrics-port", type=int, default=None,
                        metavar="PORT",
                        help="serve Prometheus text-format metrics on "
                             "127.0.0.1:PORT for the duration of the run "
                             "(0 = ephemeral port; the bound port lands "
                             "in the JSON line as metrics_port)")
    parser.add_argument("--profile-request", default=None, metavar="PATH",
                        help="on-demand device profiling trigger file: "
                             "writing a dispatch count to PATH mid-run "
                             "captures a jax.profiler trace of the next "
                             "N serving dispatches (see utils.profiling."
                             "OnDemandProfiler)")
    args = parser.parse_args(argv)
    if args.trace and not args.telemetry:
        parser.error("--trace requires --telemetry: span records ride "
                     "the telemetry JSONL sink")
    if not 0.0 <= args.repeat_tenant_fraction <= 1.0:
        parser.error("--repeat-tenant-fraction must be in [0, 1]")
    if args.checkpoint and not args.config:
        parser.error(
            "--checkpoint requires --config: the checkpoint directory "
            "records no geometry, so the restore template and compiled "
            "programs must come from the training run's experiment JSON "
            "(a mismatched default config would fail the restore — or, "
            "worse, silently serve with the wrong inner-step count)"
        )

    cfg = _bench_cfg(args)
    n_requests = args.requests or (8 if args.fast else 64)
    shots_buckets = bench_shots_buckets(cfg)

    from ..core import maml
    from .batcher import serve_requests
    from .engine import ServingEngine, load_servable_snapshot

    if args.checkpoint:
        # load_servable_snapshot also points the persistent compilation
        # cache at the training run's xla_cache (warm-started warmup)
        state, _ = load_servable_snapshot(
            cfg, args.checkpoint, args.model_idx
        )
    else:
        state = maml.init_state(cfg)

    sink = None
    metrics = None
    metrics_server = None
    if args.telemetry:
        from ..telemetry.sinks import JsonlSink

        sink = JsonlSink(args.telemetry)
    if args.metrics_port is not None:
        # the metrics registry is a telemetry sink teed off the same
        # record stream the JSONL gets — endpoint and log cannot disagree
        from .metrics import FanoutSink, MetricsServer, ServingMetrics

        metrics = ServingMetrics()
        sink = FanoutSink(sink, metrics) if sink is not None else metrics
        metrics_server = MetricsServer(metrics, port=args.metrics_port)
        print(f"serve-bench: metrics at {metrics_server.url}",
              file=sys.stderr, flush=True)

    tracer = None
    if args.trace:
        from ..telemetry.sinks import make_record
        from ..telemetry.tracing import Tracer

        span_sink = sink

        def _emit(**fields):
            span_sink.write(make_record("span", **fields))

        tracer = Tracer(emit=_emit)

    profiler = None
    if args.profile_request:
        from ..utils.profiling import OnDemandProfiler

        profiler = OnDemandProfiler(
            args.profile_request,
            os.path.dirname(os.path.abspath(args.profile_request))
            or ".",
            trace_id=tracer.trace_id if tracer is not None else None,
        )

    ingest = args.ingest or cfg.serving_ingest
    cache_size = args.cache_size
    if cache_size is None:
        cache_size = cfg.serving_adapted_cache_size
        if args.repeat_tenant_fraction > 0 and cache_size == 0:
            # a repeat-tenant workload without the cache measures
            # nothing; auto-enable it at a capacity the workload fits
            cache_size = max(64, n_requests)
    store = _synth_store(cfg) if ingest == "index" else None

    engine = ServingEngine(
        cfg, state, shots_buckets=shots_buckets, sink=sink,
        strict_retrace=True, ingest=ingest, store=store,
        cache_size=cache_size, tracer=tracer, profiler=profiler,
    )
    watchdog = None
    if cfg.watchdog_timeout_s > 0:
        # a wedged serving dispatch must produce a watchdog_stall record,
        # not a silent hang — same contract as the train loop
        from .engine import attach_serving_watchdog

        watchdog = attach_serving_watchdog(
            engine, cfg.watchdog_timeout_s, sink=sink,
        )
    warmup_s = engine.warmup(artifact_dir=args.export_dir)

    groups = _synth_groups(
        cfg, shots_buckets, n_requests, engine.max_tenants, args.seed,
        ingest=ingest, store_rows=engine._store_rows,
        repeat_fraction=args.repeat_tenant_fraction,
    )
    for group in groups:
        serve_requests(engine, group)

    rollup = engine.rollup()
    if profiler is not None:
        profiler.close()
    if watchdog is not None:
        watchdog.stop()
    if metrics_server is not None:
        metrics_server.close()
    if sink is not None:
        sink.close()
    line = {
        "metric": "serving_adaptation_latency_ms",
        "value": rollup["adapt_ms_p50"],
        "unit": "ms",
        "adaptation_latency_ms_p50": rollup["adapt_ms_p50"],
        "adaptation_latency_ms_p95": rollup["adapt_ms_p95"],
        # the engine's rollup is the ONE definition of this metric — the
        # printed line and the telemetry rollup record can never disagree
        "tenants_per_sec": rollup["tenants_per_sec"],
        "dispatches": rollup["dispatches"],
        "tenants": rollup["tenants"],
        "retraces": rollup["retraces"],
        "warmup_seconds": round(warmup_s, 3),
        # the latency decomposition (schema v10): queue wait + host batch
        # assembly + device dispatch enqueue + blocking sync account for
        # the end-to-end latency (adapt = dispatch + sync by definition)
        "queue_ms_p50": rollup["queue_ms_p50"],
        "batch_ms_mean": rollup["batch_ms_mean"],
        "dispatch_ms_p50": rollup["dispatch_ms_p50"],
        "sync_ms_p50": rollup["sync_ms_p50"],
        "metrics_port": (
            metrics_server.port if metrics_server is not None else None
        ),
        "traced": bool(args.trace),
        # the fast-path acceptance surface: measured H2D per dispatch
        # (the ingest tiers' ratio is the bench's 4x/index claim), cache
        # hit rate, and how warmup materialized its programs (the AOT
        # artifact path reports mode='artifacts' with 0 compiles)
        "ingest": rollup["ingest"],
        "h2d_bytes_per_dispatch": rollup["h2d_bytes_per_dispatch"],
        "cache_hit_rate": rollup["cache_hit_rate"],
        "cache_size": engine.cache_size,
        "repeat_tenant_fraction": float(args.repeat_tenant_fraction),
        "warmup_mode": engine.warmup_stats.get("mode"),
        "warmup_xla_compiles": engine.warmup_stats.get("xla_compiles"),
        "bucket_ladder": list(engine.buckets),
        "shots_buckets": list(engine.shots_buckets),
        "max_tenants_per_dispatch": engine.max_tenants,
        "fast": bool(args.fast),
    }
    import jax

    line["backend"] = jax.default_backend()
    print(json.dumps(line))
    return 0


if __name__ == "__main__":
    sys.exit(main())
