"""``cli serve-bench`` — load generator for the serving path.

Drives a ``ServingEngine`` with synthetic adapt-on-request traffic that
cycles through MIXED tenant-group sizes (1..max_tenants) and every
configured shots bucket — the steady-state mixed-bucket pattern the
zero-retrace contract must hold under (the engine's RetraceDetector runs
strict: any mid-run recompile fails the bench).

Two traffic disciplines:

* **closed-loop** (default) — each dispatch group waits for the previous
  one; the generator can never outpace the service, so it measures
  service latency and peak throughput but CANNOT exhibit queueing
  collapse (the queue never builds past one group);
* **open-loop** (``--arrival poisson|bursty|zipf --rate R``) — a
  fixed-seed arrival schedule is submitted against the WALL CLOCK into
  the micro-batcher(s), whether or not the service keeps up. Above
  capacity the backlog (and queue delay) grows without bound — the
  queueing-collapse regime only an open-loop generator can produce.
  ``--deadline-ms`` (default: the config's ``serving_slo_target_ms``
  when > 0) stamps a per-request deadline: every response lands an
  ``event='deadline'`` telemetry record (slack or miss, stage-
  attributed), the run reports an ``slo`` block (miss rate, error
  budget, multi-window burn rates — ``cli slo`` renders the same from
  the JSONL log), and ``--metrics-port`` exposes the matching
  deadline/burn-rate Prometheus families.

Prints ONE JSON line:

.. code-block:: json

   {"metric": "serving_adaptation_latency_ms", "value": <p50>,
    "unit": "ms", "adaptation_latency_ms_p50": ..., "..._p95": ...,
    "tenants_per_sec": ..., "dispatches": ..., "tenants": ...,
    "warmup_seconds": ..., "retraces": 0, "backend": ...,
    "ingest": "f32|uint8|index", "h2d_bytes_per_dispatch": ...,
    "cache_hit_rate": ..., "warmup_mode": "compile|artifacts",
    "warmup_xla_compiles": ..., "bucket_ladder": [...],
    "shots_buckets": [...]}

With ``--telemetry PATH`` the per-dispatch ``serving`` records plus the
final rollup go to a schema-v9 JSONL log that ``cli inspect summary``
renders and the CI serving-smoke job schema-validates. ``--checkpoint
DIR`` serves a real training checkpoint (restored READ-ONLY) instead of
a fresh ``init_state`` snapshot; ``--fast`` shrinks the workload to a
seconds-scale smoke (the CI gate). ``--ingest`` selects the serving
ingest tier (the H2D bytes land in the JSON line, so the uint8/index
reductions are measurable under the same closed-loop protocol);
``--repeat-tenant-fraction`` mixes repeat tenants in (adapted-params
cache hits — ``cache_hit_rate`` lands in the line); ``--export-dir``
warms the engine from AOT export artifacts (``cli serve-export``),
reporting ``warmup_mode`` and the warmup's XLA compile count.

Exit codes: 0 on success (including the emitted line), nonzero on any
failure — a retrace, a schema-invalid record, a broken engine.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List, Optional

import numpy as np


def _bench_cfg(args):
    """The generator's config: the user's JSON when given, else a small
    deterministic serving config (``--fast`` shrinks it further)."""
    from ..config import MAMLConfig

    if args.config:
        cfg = MAMLConfig.from_json_file(args.config)
    elif args.fast:
        cfg = MAMLConfig(
            dataset_name="omniglot_dataset",
            image_height=10, image_width=10, image_channels=1,
            num_classes_per_set=3, num_samples_per_class=1,
            num_target_samples=2, batch_size=2, cnn_num_filters=4,
            num_stages=2, number_of_training_steps_per_iter=2,
            number_of_evaluation_steps_per_iter=2, use_remat=False,
            serving_bucket_ladder=[1, 2],
            serving_max_tenants_per_dispatch=2,
            compilation_cache_dir="",
        )
    else:
        cfg = MAMLConfig(
            dataset_name="omniglot_dataset",
            image_height=28, image_width=28, image_channels=1,
            num_classes_per_set=5, num_samples_per_class=1,
            num_target_samples=5, batch_size=8, cnn_num_filters=32,
            num_stages=4, number_of_training_steps_per_iter=3,
            number_of_evaluation_steps_per_iter=3,
            compilation_cache_dir="",
        )
    return cfg


def bench_shots_buckets(cfg) -> List[int]:
    """The bench's shots ladder: two buckets, so even the smoke workload
    proves the mixed-bucket no-retrace contract. Shared with
    ``cli serve-export`` so exported artifact fingerprints match the
    engine serve-bench builds."""
    return sorted({cfg.num_samples_per_class,
                   cfg.num_samples_per_class + 1})


def _synth_store(cfg, rows: int = 256, seed: int = 7) -> np.ndarray:
    """A deterministic synthetic uint8 store for the index ingest."""
    rng = np.random.RandomState(seed)
    h, w, c = cfg.im_shape
    return rng.randint(0, 256, (rows, h, w, c)).astype(np.uint8)


def _synth_request(cfg, rng, shots: int, ingest: str, store_rows: int,
                   tenant_id: str):
    from .batcher import AdaptRequest, IndexRequest

    n, t = cfg.num_classes_per_set, cfg.num_target_samples
    h, w, c = cfg.im_shape
    if ingest == "index":
        return IndexRequest(
            support_idx=rng.randint(
                0, store_rows, (n, shots)
            ).astype(np.int32),
            query_idx=rng.randint(0, store_rows, (n, t)).astype(np.int32),
            labeled=True,
            tenant_id=tenant_id,
        )
    if ingest == "uint8":
        sx = rng.randint(0, 256, (n, shots, h, w, c)).astype(np.uint8)
        qx = rng.randint(0, 256, (n, t, h, w, c)).astype(np.uint8)
    else:
        sx = rng.randn(n, shots, h, w, c).astype(np.float32)
        qx = rng.randn(n, t, h, w, c).astype(np.float32)
    return AdaptRequest(
        support_x=sx,
        support_y=np.tile(np.arange(n, dtype=np.int32)[:, None], (1, shots)),
        query_x=qx,
        query_y=np.tile(np.arange(n, dtype=np.int32)[:, None], (1, t)),
        tenant_id=tenant_id,
    )


def _synth_groups(cfg, shots_buckets, n_requests: int, cap: int,
                  seed: int, ingest: str = "f32", store_rows: int = 0,
                  repeat_fraction: float = 0.0) -> List[List]:
    """Deterministic synthetic traffic as DISPATCH GROUPS: group sizes
    cycle 1..cap (every tenant bucket sees steady traffic) and each
    group's shots bucket cycles the configured ladder (every compiled
    program sees steady traffic) — the mixed-bucket pattern the
    zero-retrace contract must hold under.

    ``repeat_fraction`` > 0 makes that fraction of requests REPEAT
    TENANTS: they reuse a previously generated request's support set
    (same content fingerprint — an adapted-params-cache hit once the
    first occurrence has been adapted), modelling the
    same-tenant-returns traffic the cache fast path exists for."""
    rng = np.random.RandomState(seed)
    groups: List[List] = []
    # repeat pool per shots bucket: a reused tenant must reuse its own
    # shots count or the fingerprints can never collide
    pool: dict = {s: [] for s in shots_buckets}
    size, total, g = 1, 0, 0
    while total < n_requests:
        take = min(size, n_requests - total)
        s = shots_buckets[g % len(shots_buckets)]
        group = []
        for _ in range(take):
            if pool[s] and rng.rand() < repeat_fraction:
                prev = pool[s][rng.randint(len(pool[s]))]
                group.append(prev)
            else:
                req = _synth_request(
                    cfg, rng, s, ingest, store_rows,
                    tenant_id=f"tenant-{total + len(group)}",
                )
                pool[s].append(req)
                group.append(req)
        groups.append(group)
        total += take
        g += 1
        size = size + 1 if size < cap else 1
    return groups


def _arrival_schedule(args, n: int) -> List[float]:
    """Fixed-seed OPEN-LOOP arrival offsets (seconds from run start).

    ``poisson`` (and ``zipf``, which reuses Poisson timing under a
    Zipf tenant-popularity law): exponential inter-arrival gaps at the
    mean ``--rate``. ``bursty``: on/off-modulated Poisson — arrivals
    run at 2x the mean rate during the ON half of each
    ``--burst-period-s`` square wave and pause during the OFF half
    (same average rate, periodic backlog spikes). The schedule is a
    pure function of ``--seed``, so above/below-capacity comparisons
    replay the identical arrival process."""
    rng = np.random.RandomState(args.seed + 1)
    rate = float(args.rate)
    if args.arrival == "bursty":
        # draw on "busy time" at 2x rate, then map busy time onto the
        # wall clock by skipping every OFF half-period — arrivals land
        # only inside ON windows, exactly Poisson-at-2x within them
        gaps = rng.exponential(1.0 / (2.0 * rate), size=n)
        busy = np.cumsum(gaps)
        period = float(args.burst_period_s)
        half = period / 2.0
        return [float((t // half) * period + (t % half)) for t in busy]
    gaps = rng.exponential(1.0 / rate, size=n)
    return [float(t) for t in np.cumsum(gaps)]


def _zipf_requests(cfg, shots_buckets, n_requests: int, args,
                   ingest: str, store_rows: int) -> List:
    """Zipf-tenant-popularity traffic: a fixed tenant pool whose
    request frequencies follow ``P(rank k) ∝ k^-a`` — a head of hot
    tenants that keeps hitting the adapted-params cache and a long
    cold tail, the skew real multi-tenant serving sees. Reuses each
    tenant's ORIGINAL request object, so repeats are exact
    content-fingerprint matches (cache hits once first adapted)."""
    rng = np.random.RandomState(args.seed)
    pool_size = max(len(shots_buckets), min(n_requests, 4 + n_requests // 4))
    tenant_pool = [
        _synth_request(
            cfg, rng, shots_buckets[i % len(shots_buckets)], ingest,
            store_rows, tenant_id=f"tenant-{i}",
        )
        for i in range(pool_size)
    ]
    weights = np.arange(1, pool_size + 1, dtype=np.float64) ** (
        -float(args.zipf_exponent)
    )
    weights /= weights.sum()
    picks = rng.choice(pool_size, size=n_requests, p=weights)
    return [tenant_pool[int(k)] for k in picks]


def _drive_open_loop(submit, requests, offsets):
    """Submit each request at its scheduled wall-clock offset, whether
    or not the service has kept up — the arrival process is INDEPENDENT
    of service time, so a saturated service accumulates backlog (the
    queueing collapse a closed-loop driver can never produce).
    ``submit`` only enqueues (micro-batcher semantics), so a slow
    dispatch never stalls the generator. Returns the pending futures
    plus the worst generator lateness (ms) — scheduling fidelity: how
    far behind its own schedule the generator itself fell."""
    t0 = time.perf_counter()
    pendings = []
    late_ms_max = 0.0
    for req, off in zip(requests, offsets):
        now = time.perf_counter() - t0
        if off > now:
            time.sleep(off - now)
        else:
            late_ms_max = max(late_ms_max, (now - off) * 1e3)
        pendings.append(submit(req))
    return pendings, late_ms_max


def _bench_traffic(args, cfg, shots_buckets, n_requests, engine,
                   ingest, deadline_ms):
    """The bench traffic plan: dispatch groups (what the closed loop
    serves), their flattened request stream (what the batcher paths
    submit), and the open-loop arrival offsets (``None`` under
    ``--arrival closed``). Stamps ``deadline_ms`` onto every request
    when deadline accounting is armed."""
    if args.arrival == "zipf":
        requests = _zipf_requests(
            cfg, shots_buckets, n_requests, args, ingest=ingest,
            store_rows=engine._store_rows,
        )
        groups = [requests]  # zipf is open-loop only; groups unused
    else:
        groups = _synth_groups(
            cfg, shots_buckets, n_requests, engine.max_tenants,
            args.seed, ingest=ingest, store_rows=engine._store_rows,
            repeat_fraction=args.repeat_tenant_fraction,
        )
        requests = [r for g in groups for r in g]
    offsets = (
        _arrival_schedule(args, len(requests))
        if args.arrival != "closed" else None
    )
    if deadline_ms is not None:
        # repeat-pool requests appear more than once; stamping the same
        # budget twice is harmless (each SUBMISSION gets its own clock)
        for r in requests:
            r.deadline_ms = float(deadline_ms)
    return groups, requests, offsets


class _DeviceOccupancyShim:
    """CPU replica-emulation (``--emulate-device-ms``): proxy one
    replica's engine and hold its dispatch slot for a fixed extra
    window after each ``serve_group`` — the host-side shape of a real
    accelerator dispatch, where the host thread BLOCKS (GIL released,
    core yielded) while the device computes. One replica serializes
    compute + occupancy; N replicas overlap their occupancy windows,
    which is exactly the scaling a real per-device pool exhibits and
    the only scaling observable on a CI box whose XLA:CPU "devices"
    all contend for the same physical core(s). The sleep runs inside
    the replica's swap lock (it proxies the engine the ``Replica``
    dispatches through), so rollover swaps still wait out the full
    emulated dispatch — the zero-drop semantics are exercised
    unchanged."""

    def __init__(self, engine, hold_ms: float):
        self._engine = engine
        self._hold_s = float(hold_ms) / 1e3

    def serve_group(self, requests, queue_ms: float = 0.0):
        out = self._engine.serve_group(requests, queue_ms=queue_ms)
        t0 = time.perf_counter()
        time.sleep(self._hold_s)
        tracer = getattr(self._engine, "tracer", None)
        if tracer is not None and tracer.enabled:
            # the emulated occupancy window is device time the engine's
            # own dispatch span can't see; without this span the fleet
            # critical path would blame it on "wire" / lose it entirely
            sp = tracer.start_span(
                "device_hold", cat="serving", start_ms=t0 * 1e3,
                emulated=True, hold_ms=round(self._hold_s * 1e3, 3),
            )
            tracer.end_span(sp)
        return out

    def __getattr__(self, name):
        return getattr(self._engine, name)

    def __setattr__(self, name, value):
        # attribute WRITES forward too (sans the shim's own state): the
        # rollover swap hands the outgoing engine's watchdog to the
        # standby via `standby.watchdog = dog`, and through a shimmed
        # standby that assignment must land on the real engine whose
        # dispatch heartbeat the watchdog reads
        if name in ("_engine", "_hold_s"):
            object.__setattr__(self, name, value)
        else:
            setattr(self._engine, name, value)


def _drive_pool(args, cfg, pool, router, requests, state, sink,
                offsets=None):
    """Drive the replica pool (and, under ``--rollover``, roll a new
    checkpoint through it MID-LOAD). ``offsets`` switches the
    submission discipline: ``None`` submits the whole batch at once
    (the saturating burst), a schedule submits each request at its
    wall-clock arrival time (the open-loop generators). Returns
    ``{"dropped_requests": n, "rollover": block-or-None,
    "open_loop_late_ms_max": ms-or-None}`` — the zero-downtime
    acceptance surface: every submitted future must resolve, and every
    swap must report zero XLA compiles."""
    import shutil
    import tempfile

    daemon = None
    scratch = None
    save_dir = None
    stats = None
    if args.rollover:
        from ..experiment import checkpoint as ckpt
        from .refresh import RefreshDaemon

        scratch = tempfile.mkdtemp(prefix="serve_bench_rollover_")
        save_dir = os.path.join(scratch, "saved_models")
        os.makedirs(save_dir, exist_ok=True)
        ckpt.save_checkpoint(
            save_dir, "train_model", "latest", state, {"current_iter": 0}
        )
        daemon = RefreshDaemon(
            pool, cfg, save_dir, poll_s=0.05, sink=sink
        )
        daemon.prime()
    open_late_ms = None
    if offsets is None:
        pendings = [router.submit(r) for r in requests]
    else:
        pendings, open_late_ms = _drive_open_loop(
            router.submit, requests, offsets
        )
    if daemon is not None:
        # write a NEW checkpoint while the pool serves the backlog,
        # then roll on a BACKGROUND thread while this thread keeps
        # waves of live submissions flowing until every swap landed —
        # on any machine speed the swaps contend with real in-flight
        # dispatches (a fast runner could otherwise drain the first
        # wave before the standby even starts warming, making the
        # zero-drop assertion vacuous), and the post-rollover waves
        # prove traffic flows on the fresh snapshot
        import threading

        from ..experiment import checkpoint as ckpt

        ckpt.save_checkpoint(
            save_dir, "train_model", "latest", state, {"current_iter": 1}
        )
        roll_result = []
        roller = threading.Thread(
            target=lambda: roll_result.append(daemon.poll_once()),
            name="serve-bench-rollover",
        )
        roller.start()
        while roller.is_alive():
            wave = [router.submit(r) for r in requests]
            pendings += wave
            for p in wave:
                try:
                    p.get(timeout=600)
                except Exception:  # noqa: BLE001 - counted below
                    pass
        roller.join()
        stats = roll_result[0] if roll_result else None
    dropped = 0
    for p in pendings:
        try:
            p.get(timeout=600)
        except Exception:  # noqa: BLE001 - counted, reported, asserted 0
            dropped += 1
    block = None
    if daemon is not None:
        swaps = stats or []
        block = {
            "rollovers": daemon.rollovers,
            "swaps": len(swaps),
            "xla_compiles_at_swap": sum(
                s.get("xla_compiles_at_swap", 0) for s in swaps
            ),
            "swap_ms_max": (
                max(s.get("swap_ms", 0.0) for s in swaps) if swaps
                else None
            ),
            "standby_warmup_modes": sorted(
                {str(s.get("standby_warmup_mode")) for s in swaps}
            ),
            "rollover_error": (
                repr(daemon.last_error) if daemon.last_error else None
            ),
        }
        shutil.rmtree(scratch, ignore_errors=True)
    return {"dropped_requests": dropped, "rollover": block,
            "open_loop_late_ms_max": open_late_ms}


def _host_log_path(base: str, host_id: str) -> str:
    """Per-host telemetry path: ``telemetry.jsonl`` ->
    ``telemetry.host00.jsonl`` (the ``cli slo --fleet`` input set)."""
    root, ext = os.path.splitext(base)
    return f"{root}.{host_id}{ext}"


class _FleetPending:
    """One in-flight socket request: a thread per submission (the
    open-loop generator must never block on the fleet), resolving to a
    ``GatewayReply`` or the transport error — a request with NEITHER is
    STRANDED, the zero-stranded acceptance counter."""

    def __init__(self, client, body: bytes):
        import threading

        self.reply = None
        self.error: Optional[BaseException] = None
        self.e2e_ms: Optional[float] = None
        self._done = threading.Event()
        self._t0 = time.perf_counter()
        self._thread = threading.Thread(
            target=self._run, args=(client, body), daemon=True
        )
        self._thread.start()

    def _run(self, client, body: bytes) -> None:
        try:
            self.reply = client.serve_frame(body)
        except BaseException as e:  # noqa: BLE001 - counted as stranded
            self.error = e
        finally:
            self.e2e_ms = (time.perf_counter() - self._t0) * 1e3
            self._done.set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)


def _spawn_fleet_hosts(args, n_hosts: int, per_host_replicas: int,
                       ingest: str):
    """Start N fleet-host processes and wait for their readiness
    lines. Returns ``(procs, members)`` — ``{host_id: Popen}`` and the
    gateway membership ``{host_id: address}``."""
    import subprocess
    import threading

    procs, members = {}, {}
    for i in range(n_hosts):
        host_id = f"host{i:02d}"
        cmd = [
            sys.executable, "-m",
            "howtotrainyourmamlpytorch_tpu.serving.fleet",
            "--host-id", host_id, "--port", "0",
            "--replicas", str(per_host_replicas),
            "--ingest", ingest,
            "--seed", str(args.seed),
        ]
        if args.config:
            cmd += ["--config", args.config]
        elif args.fast:
            cmd += ["--fast"]
        if args.emulate_device_ms:
            cmd += ["--emulate-device-ms", str(args.emulate_device_ms)]
        if args.cache_size is not None:
            cmd += ["--cache-size", str(args.cache_size)]
        if args.telemetry:
            cmd += ["--telemetry",
                    _host_log_path(args.telemetry, host_id)]
            if args.trace:
                cmd += ["--trace"]
        procs[host_id] = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, text=True
        )
    for host_id, proc in procs.items():
        got: dict = {}

        def _read(p=proc, out=got):
            out["line"] = p.stdout.readline()

        reader = threading.Thread(target=_read, daemon=True)
        reader.start()
        reader.join(timeout=300)
        line = got.get("line")
        if not line:
            for p in procs.values():
                p.kill()
            raise RuntimeError(
                f"fleet host {host_id} never printed its readiness "
                f"line (exit code {proc.poll()})"
            )
        ready = json.loads(line)
        members[host_id] = f"127.0.0.1:{ready['port']}"
    return procs, members


def _drive_fleet(args, cfg, shots_buckets, n_requests, deadline_ms):
    """The ``--fleet H`` driver: H host processes behind the gateway,
    the fixed-seed open-loop schedule submitted through real sockets
    in the wire format, optional mid-run SIGKILL of one host. Prints
    the JSON line with the `fleet` block and returns the exit code —
    this process never imports jax."""
    import signal

    from .gateway import (
        Gateway,
        GatewayClient,
        GatewayServer,
        encode_request,
    )

    ingest = args.ingest or cfg.serving_ingest
    cap = cfg.serving_max_tenants_per_dispatch
    store_rows = 256  # _synth_store default — hosts build the same one
    if args.arrival == "zipf":
        requests = _zipf_requests(
            cfg, shots_buckets, n_requests, args, ingest=ingest,
            store_rows=store_rows,
        )
    else:
        groups = _synth_groups(
            cfg, shots_buckets, n_requests, cap, args.seed,
            ingest=ingest, store_rows=store_rows,
            repeat_fraction=args.repeat_tenant_fraction,
        )
        requests = [r for g in groups for r in g]
    offsets = _arrival_schedule(args, len(requests))

    procs, members = _spawn_fleet_hosts(
        args, args.fleet, args.replicas or 1, ingest
    )
    sink = None
    if args.telemetry:
        from ..telemetry.sinks import JsonlSink

        sink = JsonlSink(args.telemetry)
    tracer = None
    if args.trace and sink is not None:
        from ..telemetry.sinks import make_record
        from ..telemetry.tracing import Tracer

        span_sink = sink

        def _emit(**fields):
            span_sink.write(make_record("span", **fields))

        # the edge's tracer: process-labelled and id-prefixed so the
        # merged fleet log (`cli trace --fleet`) keeps one track per
        # process and span ids unique across processes
        tracer = Tracer(emit=_emit, process="gateway", span_prefix="gw-")
    gateway = Gateway(cfg, members, sink=sink, tracer=tracer)
    exit_code = 1
    try:
        gateway.wait_ready(timeout_s=300)
        server = GatewayServer(gateway, port=0)
        client = GatewayClient(f"127.0.0.1:{server.port}")
        kill_id = sorted(members)[-1]
        killed = None
        tiers = int(cfg.serving_gateway_priority_tiers)
        t0 = time.perf_counter()
        pendings: List[_FleetPending] = []
        late_ms_max = 0.0
        wire_bytes = 0
        for i, (req, off) in enumerate(zip(requests, offsets)):
            if args.kill_host_at is not None and i == args.kill_host_at:
                os.kill(procs[kill_id].pid, signal.SIGKILL)
                procs[kill_id].wait()
                killed = kill_id
            now = time.perf_counter() - t0
            if off > now:
                time.sleep(off - now)
            else:
                late_ms_max = max(late_ms_max, (now - off) * 1e3)
            # per-SUBMISSION fields stamped then encoded immediately:
            # repeat-tenant traffic reuses request OBJECTS, so the frame
            # must capture this submission's priority/deadline
            req.priority = (i % tiers) if args.priority_spread else None
            if deadline_ms is not None:
                req.deadline_ms = float(deadline_ms)
            body = encode_request(req)
            wire_bytes += len(body)
            pendings.append(_FleetPending(client, body))
        stranded = 0
        for p in pendings:
            if not p.wait(timeout=600):
                stranded += 1
        span_s = time.perf_counter() - t0
        admitted_ms, met = [], 0
        shed = {"admission": 0, "deadline": 0}
        host_down = failed = 0
        for p in pendings:
            if p.error is not None or p.reply is None:
                failed += 1
            elif p.reply.ok:
                admitted_ms.append(p.e2e_ms)
                if deadline_ms is None or p.e2e_ms <= deadline_ms:
                    met += 1
            elif p.reply.shed_reason is not None:
                shed[p.reply.shed_reason] = (
                    shed.get(p.reply.shed_reason, 0) + 1
                )
            elif p.reply.status == 503:
                host_down += 1
            else:
                failed += 1
        rollup = gateway.rollup()
        adm = np.asarray(admitted_ms, np.float64)

        def _pct(q):
            return round(float(np.percentile(adm, q)), 3) if adm.size \
                else None

        line = {
            "metric": "fleet_admitted_latency_ms",
            "value": _pct(50),
            "unit": "ms",
            "fast": bool(args.fast),
            "arrival": args.arrival,
            "rate": args.rate,
            "deadline_ms": deadline_ms,
            "requests": len(requests),
            "ingest": ingest,
            "wire_bytes_per_request": round(
                wire_bytes / max(1, len(requests)), 1
            ),
            "open_loop_late_ms_max": round(late_ms_max, 3),
            "backend": "fleet",
            "fleet": {
                "hosts": args.fleet,
                "replicas_per_host": args.replicas or 1,
                "emulate_device_ms": args.emulate_device_ms,
                "killed_host": killed,
                "admitted": len(admitted_ms),
                "admitted_ms_p50": _pct(50),
                "admitted_ms_p95": _pct(95),
                "admitted_ms_p99": _pct(99),
                "met_deadline": met,
                "goodput_met_per_sec": (
                    round(met / span_s, 3) if span_s > 0 else None
                ),
                "span_s": round(span_s, 3),
                "shed": shed,
                "host_down": host_down,
                "failed": failed,
                "stranded": stranded,
                "rehomes": rollup["rehomes"],
                "tripped_hosts": rollup["tripped_hosts"],
                "fleet_adapt_ms_p99": rollup["adapt_ms_p99"],
                "tenants": rollup["tenants"],
                "dispatches": rollup["dispatches"],
                "priority_spread": bool(args.priority_spread),
                "traced": bool(args.trace),
            },
        }
        print(json.dumps(line))
        exit_code = 0
        server.close()
    finally:
        # Stop the health loop BEFORE killing hosts: otherwise the
        # gateway observes the teardown SIGTERMs as host failures and
        # logs spurious ``rehome`` records after the run is over.
        gateway.close()
        for host_id, proc in procs.items():
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
        for proc in procs.values():
            try:
                proc.wait(timeout=30)
            except Exception:  # noqa: BLE001 - teardown best-effort
                proc.kill()
        if sink is not None:
            sink.close()
    return exit_code


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="serve-bench",
        description="Load generator for the adapt-on-request serving "
                    "engine: closed-loop (latency p50/p95, tenants/sec, "
                    "zero-retrace gate) or open-loop (--arrival: "
                    "Poisson/bursty/Zipf schedules, deadline + SLO "
                    "accounting)",
    )
    parser.add_argument("--fast", action="store_true",
                        help="seconds-scale smoke workload (the CI gate)")
    parser.add_argument("--config", default=None,
                        help="experiment JSON supplying the geometry and "
                             "serving_* knobs")
    parser.add_argument("--checkpoint", default=None, metavar="DIR",
                        help="serve this saved_models directory's "
                             "checkpoint (read-only restore) instead of a "
                             "fresh init_state snapshot; REQUIRES --config "
                             "with the training run's geometry (the "
                             "restore template and the compiled programs "
                             "are built from it — nothing in the "
                             "checkpoint directory records the config)")
    parser.add_argument("--model-idx", default="latest",
                        help="checkpoint index under --checkpoint "
                             "(default: latest)")
    parser.add_argument("--requests", type=int, default=None,
                        help="synthetic requests to serve (default: 8 "
                             "fast, 64 otherwise)")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--telemetry", default=None, metavar="PATH",
                        help="write serving telemetry records (JSONL, "
                             "schema v9) to this path")
    parser.add_argument("--ingest", default=None,
                        choices=["f32", "uint8", "index"],
                        help="serving ingest tier to drive (default: the "
                             "config's serving_ingest): f32 host pixels, "
                             "uint8 device-decoded pixels (~4x less H2D), "
                             "or index-only dispatch against a synthetic "
                             "resident store (<1KB H2D)")
    parser.add_argument("--repeat-tenant-fraction", type=float, default=0.0,
                        metavar="F",
                        help="fraction of requests that repeat an earlier "
                             "tenant's support set (adapted-params-cache "
                             "hits; enables the cache when > 0)")
    parser.add_argument("--cache-size", type=int, default=None,
                        help="adapted-params LRU capacity (default: the "
                             "config's serving_adapted_cache_size, or "
                             "auto-enabled when --repeat-tenant-fraction "
                             "> 0)")
    parser.add_argument("--export-dir", default=None, metavar="DIR",
                        help="AOT artifact root: warmup loads exported "
                             "executables from here (zero XLA compiles) "
                             "and falls back to compile-then-save — see "
                             "cli serve-export")
    parser.add_argument("--trace", action="store_true",
                        help="emit schema-v10 span records (request/"
                             "queue/assemble/dispatch/sync causal "
                             "timeline) into the --telemetry log; render "
                             "with `cli trace` (requires --telemetry)")
    parser.add_argument("--metrics-port", type=int, default=None,
                        metavar="PORT",
                        help="serve Prometheus text-format metrics on "
                             "127.0.0.1:PORT for the duration of the run "
                             "(0 = ephemeral port; the bound port lands "
                             "in the JSON line as metrics_port)")
    parser.add_argument("--profile-request", default=None, metavar="PATH",
                        help="on-demand device profiling trigger file: "
                             "writing a dispatch count to PATH mid-run "
                             "captures a jax.profiler trace of the next "
                             "N serving dispatches (see utils.profiling."
                             "OnDemandProfiler)")
    parser.add_argument("--replicas", type=int, default=None, metavar="N",
                        help="drive an N-replica shared-nothing pool "
                             "(serving/replica.py) through the cache-"
                             "affinity router instead of one engine: "
                             "requests are submitted open-loop to the "
                             "per-replica micro-batchers and the line "
                             "reports the POOL aggregate tenants_per_sec "
                             "+ per-replica rollups. On CPU the host "
                             "platform is forced to N virtual devices "
                             "(one disjoint device per replica) before "
                             "jax loads — the TPU-free smoke protocol")
    parser.add_argument("--spill-depth", type=int, default=None,
                        metavar="D",
                        help="router spillover depth override (default "
                             "for the bench: the request count, i.e. "
                             "spillover OFF — the closed-loop generator "
                             "saturates every queue by construction, so "
                             "depth-based spilling would only randomize "
                             "placement and dilute the cache-affinity "
                             "measurement; pass a small D to measure "
                             "spillover itself)")
    parser.add_argument("--rollover", action="store_true",
                        help="exercise zero-downtime checkpoint rollover "
                             "MID-LOAD (requires --replicas): the bench "
                             "saves a checkpoint into a scratch "
                             "experiment dir, points a RefreshDaemon at "
                             "it, writes a NEW checkpoint while the pool "
                             "is serving, and rolls every replica onto "
                             "it — the line gains a `rollover` block "
                             "(swaps, swap compiles — must be 0 — and "
                             "dropped requests — must be 0)")
    parser.add_argument("--emulate-device-ms", type=float, default=0.0,
                        metavar="MS",
                        help="CPU replica-emulation recipe (requires "
                             "--replicas): hold each replica's dispatch "
                             "slot for MS extra milliseconds after the "
                             "XLA work — the host-side shape of a real "
                             "accelerator dispatch, where the host "
                             "BLOCKS while the device computes. On a "
                             "TPU pool this is what makes replicas "
                             "scale (each blocks on its OWN device); "
                             "on a shared-core CI box it is the only "
                             "way pool orchestration scaling is "
                             "observable at all: XLA:CPU compute from "
                             "all replicas contends for the same "
                             "core(s) and cannot scale, but the "
                             "occupancy window overlaps perfectly. "
                             "0 (default) disables the shim")
    parser.add_argument("--fleet", type=int, default=None, metavar="H",
                        help="drive an H-HOST networked fleet through "
                             "the HTTP gateway (serving/gateway.py): "
                             "spawn H fleet-host processes (each its "
                             "own ReplicaSet of --replicas width, "
                             "default 1), put the admission-controlled "
                             "gateway in front, and submit the OPEN-"
                             "LOOP schedule through real sockets in the "
                             "wire format. The line gains a `fleet` "
                             "block (admitted/shed/rehome counts, "
                             "client-observed admitted p99, goodput). "
                             "Requires an open-loop --arrival")
    parser.add_argument("--kill-host-at", type=int, default=None,
                        metavar="K",
                        help="SIGKILL the highest-ring-position fleet "
                             "host when request K is submitted "
                             "(requires --fleet): exercises between-"
                             "sweep host death — in-flight requests "
                             "must fail over to their re-homed host, "
                             "never strand")
    parser.add_argument("--priority-spread", action="store_true",
                        help="cycle request priorities over the "
                             "gateway's tiers (requires --fleet; "
                             "default: every request rides tier 0)")
    parser.add_argument("--arrival", default="closed",
                        choices=["closed", "poisson", "bursty", "zipf"],
                        help="traffic discipline: 'closed' (default) "
                             "waits for each dispatch before the next — "
                             "measures service latency but can never "
                             "exhibit queueing collapse; the rest are "
                             "OPEN-LOOP fixed-seed arrival schedules "
                             "submitted against the wall clock "
                             "(requires --rate): 'poisson' exponential "
                             "inter-arrivals, 'bursty' on/off-modulated "
                             "Poisson (2x rate during the ON half of "
                             "each --burst-period-s), 'zipf' Poisson "
                             "timing with Zipf tenant popularity "
                             "(hot-head/cold-tail cache skew)")
    parser.add_argument("--rate", type=float, default=None, metavar="R",
                        help="mean arrival rate, requests/sec (open-loop "
                             "arrivals only)")
    parser.add_argument("--deadline-ms", type=float, default=None,
                        metavar="MS",
                        help="per-request latency budget counted from "
                             "submit: every response lands a telemetry "
                             "deadline record (slack or miss) and the "
                             "line gains an `slo` block (miss rate, "
                             "error budget, burn rates). Default: the "
                             "config's serving_slo_target_ms when > 0, "
                             "else deadline accounting is off")
    parser.add_argument("--burst-period-s", type=float, default=1.0,
                        metavar="S",
                        help="square-wave period for --arrival bursty "
                             "(ON for the first half, OFF for the "
                             "second; default 1.0)")
    parser.add_argument("--zipf-exponent", type=float, default=1.2,
                        metavar="A",
                        help="popularity exponent for --arrival zipf: "
                             "P(tenant rank k) ~ k^-A over the tenant "
                             "pool (must be > 1; default 1.2)")
    args = parser.parse_args(argv)
    if args.trace and not args.telemetry:
        parser.error("--trace requires --telemetry: span records ride "
                     "the telemetry JSONL sink")
    if not 0.0 <= args.repeat_tenant_fraction <= 1.0:
        parser.error("--repeat-tenant-fraction must be in [0, 1]")
    if args.checkpoint and not args.config:
        parser.error(
            "--checkpoint requires --config: the checkpoint directory "
            "records no geometry, so the restore template and compiled "
            "programs must come from the training run's experiment JSON "
            "(a mismatched default config would fail the restore — or, "
            "worse, silently serve with the wrong inner-step count)"
        )
    if args.rollover and args.replicas is None:
        parser.error("--rollover requires --replicas (the rollover "
                     "lifecycle is a pool operation; use --replicas 1 "
                     "for a single-replica pool)")
    if args.emulate_device_ms < 0:
        parser.error("--emulate-device-ms must be >= 0, got "
                     f"{args.emulate_device_ms}")
    if (args.emulate_device_ms and args.replicas is None
            and args.fleet is None):
        parser.error("--emulate-device-ms requires --replicas or "
                     "--fleet (the device-occupancy shim emulates "
                     "PER-REPLICA device blocking; it has no meaning "
                     "on the single-engine closed loop)")
    if args.arrival != "closed" and args.rate is None:
        parser.error("--arrival poisson|bursty|zipf is OPEN-LOOP and "
                     "needs its arrival process parameterized: pass "
                     "--rate (mean requests/sec)")
    if args.rate is not None and args.arrival == "closed":
        parser.error("--rate has no meaning for the closed-loop "
                     "generator (the service sets the pace); pick an "
                     "open-loop --arrival")
    if args.rate is not None and args.rate <= 0:
        parser.error(f"--rate must be > 0, got {args.rate}")
    if args.deadline_ms is not None and args.deadline_ms <= 0:
        parser.error(f"--deadline-ms must be > 0, got {args.deadline_ms}")
    if args.burst_period_s <= 0:
        parser.error(f"--burst-period-s must be > 0, got "
                     f"{args.burst_period_s}")
    if args.zipf_exponent <= 1.0:
        parser.error("--zipf-exponent must be > 1 (the popularity law "
                     f"must be normalizable), got {args.zipf_exponent}")
    if args.rollover and args.arrival != "closed":
        parser.error("--rollover drives closed-loop live-traffic waves "
                     "around the swap; combine it with the default "
                     "--arrival closed (mid-run rollover under open "
                     "loop is covered by the pool unit tests)")
    if args.fleet is not None:
        if args.fleet < 1:
            parser.error(f"--fleet must be >= 1, got {args.fleet}")
        if args.arrival == "closed":
            parser.error("--fleet is the networked OPEN-LOOP driver "
                         "(real sockets, wall-clock arrivals): pick an "
                         "open-loop --arrival and a --rate")
        if args.replicas is not None and args.replicas < 1:
            parser.error("--replicas (per-host pool width under "
                         f"--fleet) must be >= 1, got {args.replicas}")
        # --trace is fleet-legal since the distributed-tracing PR: the
        # gateway traces the edge, every host traces its own engine,
        # and `cli trace --fleet` merges the per-process logs
        for flag, name in ((args.rollover, "--rollover"),
                           (args.profile_request, "--profile-request"),
                           (args.metrics_port, "--metrics-port"),
                           (args.export_dir, "--export-dir")):
            if flag:
                parser.error(f"{name} applies to the in-process paths; "
                             "the fleet hosts own their engines (drive "
                             "them via the fleet-host flags instead)")
    if args.kill_host_at is not None:
        if args.fleet is None:
            parser.error("--kill-host-at requires --fleet")
        if args.kill_host_at < 0:
            parser.error("--kill-host-at must be >= 0, got "
                         f"{args.kill_host_at}")
    if args.priority_spread and args.fleet is None:
        parser.error("--priority-spread requires --fleet (priority "
                     "tiers are a gateway admission concept)")
    if args.replicas is not None and args.fleet is None:
        if args.replicas < 1:
            parser.error(f"--replicas must be >= 1, got {args.replicas}")
        # each replica needs its own disjoint device; on CPU force the
        # host platform to present enough virtual devices BEFORE jax
        # first loads (the audit-cli --mesh pattern; no effect on a
        # backend whose real chips already exist)
        if "jax" not in sys.modules:
            flags = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    flags
                    + f" --xla_force_host_platform_device_count="
                      f"{args.replicas}"
                ).strip()

    cfg = _bench_cfg(args)
    n_requests = args.requests or (8 if args.fast else 64)
    shots_buckets = bench_shots_buckets(cfg)

    # deadline accounting: the flag wins, else the config's SLO target
    # doubles as the bench deadline. Deadline records are emitted by the
    # micro-batcher at request resolution, so the single-engine CLOSED
    # loop (which dispatches directly, no batcher) cannot account them —
    # say so instead of silently reporting an empty SLO block.
    deadline_ms = args.deadline_ms
    if deadline_ms is None and cfg.serving_slo_target_ms > 0:
        deadline_ms = float(cfg.serving_slo_target_ms)
    if (deadline_ms is not None and args.arrival == "closed"
            and args.replicas is None):
        print("serve-bench: deadline accounting rides the micro-batcher "
              "path; ignored on the single-engine closed loop (use an "
              "open-loop --arrival or --replicas)",
              file=sys.stderr, flush=True)
        deadline_ms = None
    if args.fleet is not None:
        # the networked path never touches jax in THIS process: the
        # hosts own the engines, the gateway/client/codec are stdlib +
        # numpy (serving/gateway.py)
        return _drive_fleet(
            args, cfg, shots_buckets, n_requests, deadline_ms
        )

    slo = None
    if deadline_ms is not None:
        from .metrics import SLOTracker

        slo = SLOTracker(
            target_ms=deadline_ms,
            availability=cfg.serving_slo_availability,
            burn_windows_s=tuple(cfg.serving_slo_burn_windows_s),
        )

    from ..core import maml
    from .batcher import serve_requests
    from .engine import ServingEngine, load_servable_snapshot

    if args.checkpoint:
        # load_servable_snapshot also points the persistent compilation
        # cache at the training run's xla_cache (warm-started warmup)
        state, _ = load_servable_snapshot(
            cfg, args.checkpoint, args.model_idx
        )
    else:
        state = maml.init_state(cfg)

    sink = None
    metrics = None
    metrics_server = None
    if args.telemetry:
        from ..telemetry.sinks import JsonlSink

        sink = JsonlSink(args.telemetry)
    if args.metrics_port is not None:
        # the metrics registry is a telemetry sink teed off the same
        # record stream the JSONL gets — endpoint and log cannot
        # disagree (the HTTP server itself starts AFTER the engine/pool
        # exists, so /healthz can report pool readiness)
        from .metrics import FanoutSink, ServingMetrics

        metrics = ServingMetrics(slo=slo)
        sink = FanoutSink(sink, metrics) if sink is not None else metrics
    elif slo is not None:
        # no metrics registry: tee the SLO tracker into the record
        # stream directly. Either way the tracker is wired EXACTLY once
        # (inside the registry or as its own sink, never both), so the
        # endpoint, the JSONL log, and the line's slo block count each
        # deadline record once from the same stream.
        from .metrics import FanoutSink

        sink = FanoutSink(sink, slo) if sink is not None else slo

    tracer = None
    if args.trace:
        from ..telemetry.sinks import make_record
        from ..telemetry.tracing import Tracer

        span_sink = sink

        def _emit(**fields):
            span_sink.write(make_record("span", **fields))

        tracer = Tracer(emit=_emit)

    profiler = None
    if args.profile_request:
        from ..utils.profiling import OnDemandProfiler

        profiler = OnDemandProfiler(
            args.profile_request,
            os.path.dirname(os.path.abspath(args.profile_request))
            or ".",
            trace_id=tracer.trace_id if tracer is not None else None,
        )

    ingest = args.ingest or cfg.serving_ingest
    cache_size = args.cache_size
    if cache_size is None:
        cache_size = cfg.serving_adapted_cache_size
        if args.repeat_tenant_fraction > 0 and cache_size == 0:
            # a repeat-tenant workload without the cache measures
            # nothing; auto-enable it at a capacity the workload fits
            cache_size = max(64, n_requests)
    store = _synth_store(cfg) if ingest == "index" else None

    pool = None
    router = None
    pool_drive = None
    open_late_ms = None
    open_dropped = None
    if args.replicas is not None:
        # the multi-replica protocol: one full engine per disjoint
        # device, requests routed by cache affinity, OPEN-LOOP
        # submission into the per-replica micro-batchers — the aggregate
        # tenants_per_sec is total tenants over the union wall-clock
        # span (serving/replica.py rollup)
        from .replica import ReplicaSet
        from .router import ReplicaRouter

        if profiler is not None:
            print("serve-bench: --profile-request applies to the "
                  "single-engine path; ignored under --replicas",
                  file=sys.stderr, flush=True)
        import jax

        pool_devices = None
        if (jax.default_backend() == "cpu"
                and len(jax.devices()) > args.replicas):
            # virtual host devices beyond the pool width are
            # meaningless (an already-initialized jax, e.g. in-process
            # tests, may present more than --replicas forced): take
            # width-1 slices. On a real accelerator the pool partitions
            # every chip and warns about idle capacity instead.
            pool_devices = list(jax.devices())[:args.replicas]
        pool = ReplicaSet(
            cfg, state, n_replicas=args.replicas, devices=pool_devices,
            shots_buckets=shots_buckets, sink=sink, strict_retrace=True,
            ingest=ingest, store=store, cache_size=cache_size,
            tracer=tracer, metrics=metrics, export_root=args.export_dir,
        )
        engine = pool.replicas[0].engine  # line metadata (shared knobs)
        if cfg.watchdog_timeout_s > 0:
            # one watchdog per replica, tagged with its replica_id;
            # the pool rewires them across restart_replica and rollover
            # engine swaps, and stops them in close()
            pool.attach_watchdogs(cfg.watchdog_timeout_s, sink=sink)
        if args.metrics_port is not None:
            from .metrics import MetricsServer

            metrics_server = MetricsServer(
                metrics, port=args.metrics_port,
                readiness=pool.readiness,
            )
            print(f"serve-bench: metrics at {metrics_server.url}",
                  file=sys.stderr, flush=True)
        warmup_s = pool.warmup()
        if args.emulate_device_ms:
            # shim AFTER warmup (compiles must stay un-padded) and shim
            # the rollover standby builder too, so swapped-in engines
            # keep the same emulated occupancy as the ones they replace
            for r in pool.replicas:
                r.engine = _DeviceOccupancyShim(
                    r.engine, args.emulate_device_ms
                )
            _build = pool.build_standby_engine

            def _shimmed_standby(rid, st, snapshot_id=None):
                return _DeviceOccupancyShim(
                    _build(rid, st, snapshot_id), args.emulate_device_ms
                )

            pool.build_standby_engine = _shimmed_standby
        # spillover default: OFF for the closed-loop generator (every
        # queue is saturated by construction, so depth spilling would
        # only randomize placement and dilute the affinity measurement)
        spill = (
            args.spill_depth if args.spill_depth is not None
            else max(cfg.serving_router_spill_depth, n_requests)
        )
        router = ReplicaRouter(pool, spill_depth=spill)
        groups, requests, offsets = _bench_traffic(
            args, cfg, shots_buckets, n_requests, engine, ingest,
            deadline_ms,
        )
        pool_drive = _drive_pool(args, cfg, pool, router, requests,
                                 state, sink, offsets=offsets)
        open_late_ms = pool_drive["open_loop_late_ms_max"]
        rollup = pool.rollup()
        pool.close()
    else:
        engine = ServingEngine(
            cfg, state, shots_buckets=shots_buckets, sink=sink,
            strict_retrace=True, ingest=ingest, store=store,
            cache_size=cache_size, tracer=tracer, profiler=profiler,
        )
        if args.metrics_port is not None:
            from .metrics import MetricsServer

            metrics_server = MetricsServer(metrics, port=args.metrics_port)
            print(f"serve-bench: metrics at {metrics_server.url}",
                  file=sys.stderr, flush=True)
    watchdog = None
    if args.replicas is None:
        if cfg.watchdog_timeout_s > 0:
            # a wedged serving dispatch must produce a watchdog_stall
            # record, not a silent hang — same contract as the train loop
            from .engine import attach_serving_watchdog

            watchdog = attach_serving_watchdog(
                engine, cfg.watchdog_timeout_s, sink=sink,
            )
        warmup_s = engine.warmup(artifact_dir=args.export_dir)

        groups, requests, offsets = _bench_traffic(
            args, cfg, shots_buckets, n_requests, engine, ingest,
            deadline_ms,
        )
        if offsets is not None:
            # open loop on one engine: submit through a micro-batcher
            # (the layer that owns queueing + deadline accounting) at
            # the scheduled arrival times, then collect every future
            from .batcher import MicroBatcher

            batcher = MicroBatcher(engine, metrics=metrics)
            pendings, open_late_ms = _drive_open_loop(
                batcher.submit, requests, offsets
            )
            open_dropped = 0
            for p in pendings:
                try:
                    p.get(timeout=600)
                except Exception:  # noqa: BLE001 - counted, reported
                    open_dropped += 1
            batcher.close()
        else:
            for group in groups:
                serve_requests(engine, group)

        rollup = engine.rollup()
    if profiler is not None:
        profiler.close()
    if watchdog is not None:
        watchdog.stop()
    if metrics_server is not None:
        metrics_server.close()
    if slo is not None and sink is not None:
        # the run's SLO verdict as a first-class telemetry record — the
        # same summary() the JSON line carries and `cli slo` recomputes
        # from the log's deadline records
        from ..telemetry.sinks import make_record

        sink.write(make_record("slo", **slo.summary()))
    if sink is not None:
        sink.close()
    line = {
        "metric": "serving_adaptation_latency_ms",
        "value": rollup["adapt_ms_p50"],
        "unit": "ms",
        "adaptation_latency_ms_p50": rollup["adapt_ms_p50"],
        "adaptation_latency_ms_p95": rollup["adapt_ms_p95"],
        # the engine's rollup is the ONE definition of this metric — the
        # printed line and the telemetry rollup record can never disagree
        "tenants_per_sec": rollup["tenants_per_sec"],
        "dispatches": rollup["dispatches"],
        "tenants": rollup["tenants"],
        "retraces": rollup["retraces"],
        "warmup_seconds": round(warmup_s, 3),
        # the latency decomposition (schema v10): queue wait + host batch
        # assembly + device dispatch enqueue + blocking sync account for
        # the end-to-end latency (adapt = dispatch + sync by definition)
        "queue_ms_p50": rollup["queue_ms_p50"],
        "batch_ms_mean": rollup["batch_ms_mean"],
        "dispatch_ms_p50": rollup["dispatch_ms_p50"],
        "sync_ms_p50": rollup["sync_ms_p50"],
        "metrics_port": (
            metrics_server.port if metrics_server is not None else None
        ),
        "traced": bool(args.trace),
        # the fast-path acceptance surface: measured H2D per dispatch
        # (the ingest tiers' ratio is the bench's 4x/index claim), cache
        # hit rate, and how warmup materialized its programs (the AOT
        # artifact path reports mode='artifacts' with 0 compiles)
        "ingest": rollup["ingest"],
        "h2d_bytes_per_dispatch": rollup["h2d_bytes_per_dispatch"],
        "cache_hit_rate": rollup["cache_hit_rate"],
        "cache_size": engine.cache_size,
        "repeat_tenant_fraction": float(args.repeat_tenant_fraction),
        "warmup_mode": engine.warmup_stats.get("mode"),
        "warmup_xla_compiles": engine.warmup_stats.get("xla_compiles"),
        "bucket_ladder": list(engine.buckets),
        "shots_buckets": list(engine.shots_buckets),
        "max_tenants_per_dispatch": engine.max_tenants,
        "fast": bool(args.fast),
        # SLO observability surface: the traffic discipline, the
        # per-request budget in force, how many dispatches aged out of
        # the windowed percentile samples (the histograms above kept
        # them), and — when deadlines were armed — the full SLO verdict
        "arrival": args.arrival,
        "rate": args.rate,
        "deadline_ms": deadline_ms,
        "window_dropped": rollup["window_dropped"],
    }
    if open_late_ms is not None:
        line["open_loop_late_ms_max"] = round(open_late_ms, 3)
    if open_dropped is not None:
        line["dropped_requests"] = open_dropped
    if slo is not None:
        line["slo"] = slo.summary()
    if pool is not None:
        # the pool surface: aggregate tenants_per_sec is total tenants
        # over the UNION wall-clock span (never a sum of per-replica
        # rates — their spans overlap), per-replica rollups ride along,
        # and the router reports how affinity/spillover placed traffic
        line["replicas"] = rollup["replicas"]
        line["per_replica"] = [
            {
                "replica_id": ru["replica_id"],
                "dispatches": ru["dispatches"],
                "tenants": ru["tenants"],
                "adapt_ms_p50": ru["adapt_ms_p50"],
                "tenants_per_sec": ru["tenants_per_sec"],
                "cache_hit_rate": ru["cache_hit_rate"],
            }
            for ru in rollup["per_replica"]
        ]
        line["router"] = router.stats()
        line["dropped_requests"] = pool_drive["dropped_requests"]
        line["rollover"] = pool_drive["rollover"]
        line["emulate_device_ms"] = args.emulate_device_ms
        # every replica warmed; the line's single warmup fields reflect
        # replica 0, the totals say whether ANY replica compiled
        line["warmup_xla_compiles_total"] = sum(
            r.engine.warmup_stats.get("xla_compiles", 0)
            for r in pool.replicas
        )
    import jax

    line["backend"] = jax.default_backend()
    print(json.dumps(line))
    return 0


if __name__ == "__main__":
    sys.exit(main())
