"""``cli serve-bench`` — closed-loop load generator for the serving path.

Drives a ``ServingEngine`` with synthetic adapt-on-request traffic that
cycles through MIXED tenant-group sizes (1..max_tenants) and every
configured shots bucket — the steady-state mixed-bucket pattern the
zero-retrace contract must hold under (the engine's RetraceDetector runs
strict: any mid-run recompile fails the bench). Prints ONE JSON line:

.. code-block:: json

   {"metric": "serving_adaptation_latency_ms", "value": <p50>,
    "unit": "ms", "adaptation_latency_ms_p50": ..., "..._p95": ...,
    "tenants_per_sec": ..., "dispatches": ..., "tenants": ...,
    "warmup_seconds": ..., "retraces": 0, "backend": ...,
    "bucket_ladder": [...], "shots_buckets": [...]}

With ``--telemetry PATH`` the per-dispatch ``serving`` records plus the
final rollup go to a schema-v8 JSONL log that ``cli inspect summary``
renders and the CI serving-smoke job schema-validates. ``--checkpoint
DIR`` serves a real training checkpoint (restored READ-ONLY) instead of
a fresh ``init_state`` snapshot; ``--fast`` shrinks the workload to a
seconds-scale smoke (the CI gate).

Exit codes: 0 on success (including the emitted line), nonzero on any
failure — a retrace, a schema-invalid record, a broken engine.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

import numpy as np


def _bench_cfg(args):
    """The generator's config: the user's JSON when given, else a small
    deterministic serving config (``--fast`` shrinks it further)."""
    from ..config import MAMLConfig

    if args.config:
        cfg = MAMLConfig.from_json_file(args.config)
    elif args.fast:
        cfg = MAMLConfig(
            dataset_name="omniglot_dataset",
            image_height=10, image_width=10, image_channels=1,
            num_classes_per_set=3, num_samples_per_class=1,
            num_target_samples=2, batch_size=2, cnn_num_filters=4,
            num_stages=2, number_of_training_steps_per_iter=2,
            number_of_evaluation_steps_per_iter=2, use_remat=False,
            serving_bucket_ladder=[1, 2],
            serving_max_tenants_per_dispatch=2,
            compilation_cache_dir="",
        )
    else:
        cfg = MAMLConfig(
            dataset_name="omniglot_dataset",
            image_height=28, image_width=28, image_channels=1,
            num_classes_per_set=5, num_samples_per_class=1,
            num_target_samples=5, batch_size=8, cnn_num_filters=32,
            num_stages=4, number_of_training_steps_per_iter=3,
            number_of_evaluation_steps_per_iter=3,
            compilation_cache_dir="",
        )
    return cfg


def _synth_groups(cfg, shots_buckets, n_requests: int, cap: int,
                  seed: int) -> List[List]:
    """Deterministic synthetic traffic as DISPATCH GROUPS: group sizes
    cycle 1..cap (every tenant bucket sees steady traffic) and each
    group's shots bucket cycles the configured ladder (every compiled
    program sees steady traffic) — the mixed-bucket pattern the
    zero-retrace contract must hold under."""
    from .batcher import AdaptRequest

    rng = np.random.RandomState(seed)
    n, t = cfg.num_classes_per_set, cfg.num_target_samples
    h, w, c = cfg.im_shape
    groups: List[List] = []
    size, total, g = 1, 0, 0
    while total < n_requests:
        take = min(size, n_requests - total)
        s = shots_buckets[g % len(shots_buckets)]
        group = []
        for _ in range(take):
            group.append(AdaptRequest(
                support_x=rng.randn(n, s, h, w, c).astype(np.float32),
                support_y=np.tile(
                    np.arange(n, dtype=np.int32)[:, None], (1, s)
                ),
                query_x=rng.randn(n, t, h, w, c).astype(np.float32),
                query_y=np.tile(
                    np.arange(n, dtype=np.int32)[:, None], (1, t)
                ),
                tenant_id=f"tenant-{total + len(group)}",
            ))
        groups.append(group)
        total += take
        g += 1
        size = size + 1 if size < cap else 1
    return groups


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="serve-bench",
        description="Closed-loop load generator for the adapt-on-request "
                    "serving engine (latency p50/p95, tenants/sec, "
                    "zero-retrace gate)",
    )
    parser.add_argument("--fast", action="store_true",
                        help="seconds-scale smoke workload (the CI gate)")
    parser.add_argument("--config", default=None,
                        help="experiment JSON supplying the geometry and "
                             "serving_* knobs")
    parser.add_argument("--checkpoint", default=None, metavar="DIR",
                        help="serve this saved_models directory's "
                             "checkpoint (read-only restore) instead of a "
                             "fresh init_state snapshot; REQUIRES --config "
                             "with the training run's geometry (the "
                             "restore template and the compiled programs "
                             "are built from it — nothing in the "
                             "checkpoint directory records the config)")
    parser.add_argument("--model-idx", default="latest",
                        help="checkpoint index under --checkpoint "
                             "(default: latest)")
    parser.add_argument("--requests", type=int, default=None,
                        help="synthetic requests to serve (default: 8 "
                             "fast, 64 otherwise)")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--telemetry", default=None, metavar="PATH",
                        help="write serving telemetry records (JSONL, "
                             "schema v8) to this path")
    args = parser.parse_args(argv)
    if args.checkpoint and not args.config:
        parser.error(
            "--checkpoint requires --config: the checkpoint directory "
            "records no geometry, so the restore template and compiled "
            "programs must come from the training run's experiment JSON "
            "(a mismatched default config would fail the restore — or, "
            "worse, silently serve with the wrong inner-step count)"
        )

    cfg = _bench_cfg(args)
    n_requests = args.requests or (8 if args.fast else 64)
    # two shots buckets prove the mixed-bucket no-retrace contract even
    # on the smoke workload
    shots_buckets = sorted({cfg.num_samples_per_class,
                            cfg.num_samples_per_class + 1})

    from ..core import maml
    from .batcher import serve_requests
    from .engine import ServingEngine, load_servable_snapshot

    if args.checkpoint:
        # load_servable_snapshot also points the persistent compilation
        # cache at the training run's xla_cache (warm-started warmup)
        state, _ = load_servable_snapshot(
            cfg, args.checkpoint, args.model_idx
        )
    else:
        state = maml.init_state(cfg)

    sink = None
    if args.telemetry:
        from ..telemetry.sinks import JsonlSink

        sink = JsonlSink(args.telemetry)

    engine = ServingEngine(
        cfg, state, shots_buckets=shots_buckets, sink=sink,
        strict_retrace=True,
    )
    warmup_s = engine.warmup()

    groups = _synth_groups(
        cfg, shots_buckets, n_requests, engine.max_tenants, args.seed
    )
    for group in groups:
        serve_requests(engine, group)

    rollup = engine.rollup()
    if sink is not None:
        sink.close()
    line = {
        "metric": "serving_adaptation_latency_ms",
        "value": rollup["adapt_ms_p50"],
        "unit": "ms",
        "adaptation_latency_ms_p50": rollup["adapt_ms_p50"],
        "adaptation_latency_ms_p95": rollup["adapt_ms_p95"],
        # the engine's rollup is the ONE definition of this metric — the
        # printed line and the telemetry rollup record can never disagree
        "tenants_per_sec": rollup["tenants_per_sec"],
        "dispatches": rollup["dispatches"],
        "tenants": rollup["tenants"],
        "retraces": rollup["retraces"],
        "warmup_seconds": round(warmup_s, 3),
        "bucket_ladder": list(engine.buckets),
        "shots_buckets": list(engine.shots_buckets),
        "max_tenants_per_dispatch": engine.max_tenants,
        "fast": bool(args.fast),
    }
    import jax

    line["backend"] = jax.default_backend()
    print(json.dumps(line))
    return 0


if __name__ == "__main__":
    sys.exit(main())
