"""``cli serve-bench`` — closed-loop load generator for the serving path.

Drives a ``ServingEngine`` with synthetic adapt-on-request traffic that
cycles through MIXED tenant-group sizes (1..max_tenants) and every
configured shots bucket — the steady-state mixed-bucket pattern the
zero-retrace contract must hold under (the engine's RetraceDetector runs
strict: any mid-run recompile fails the bench). Prints ONE JSON line:

.. code-block:: json

   {"metric": "serving_adaptation_latency_ms", "value": <p50>,
    "unit": "ms", "adaptation_latency_ms_p50": ..., "..._p95": ...,
    "tenants_per_sec": ..., "dispatches": ..., "tenants": ...,
    "warmup_seconds": ..., "retraces": 0, "backend": ...,
    "ingest": "f32|uint8|index", "h2d_bytes_per_dispatch": ...,
    "cache_hit_rate": ..., "warmup_mode": "compile|artifacts",
    "warmup_xla_compiles": ..., "bucket_ladder": [...],
    "shots_buckets": [...]}

With ``--telemetry PATH`` the per-dispatch ``serving`` records plus the
final rollup go to a schema-v9 JSONL log that ``cli inspect summary``
renders and the CI serving-smoke job schema-validates. ``--checkpoint
DIR`` serves a real training checkpoint (restored READ-ONLY) instead of
a fresh ``init_state`` snapshot; ``--fast`` shrinks the workload to a
seconds-scale smoke (the CI gate). ``--ingest`` selects the serving
ingest tier (the H2D bytes land in the JSON line, so the uint8/index
reductions are measurable under the same closed-loop protocol);
``--repeat-tenant-fraction`` mixes repeat tenants in (adapted-params
cache hits — ``cache_hit_rate`` lands in the line); ``--export-dir``
warms the engine from AOT export artifacts (``cli serve-export``),
reporting ``warmup_mode`` and the warmup's XLA compile count.

Exit codes: 0 on success (including the emitted line), nonzero on any
failure — a retrace, a schema-invalid record, a broken engine.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List, Optional

import numpy as np


def _bench_cfg(args):
    """The generator's config: the user's JSON when given, else a small
    deterministic serving config (``--fast`` shrinks it further)."""
    from ..config import MAMLConfig

    if args.config:
        cfg = MAMLConfig.from_json_file(args.config)
    elif args.fast:
        cfg = MAMLConfig(
            dataset_name="omniglot_dataset",
            image_height=10, image_width=10, image_channels=1,
            num_classes_per_set=3, num_samples_per_class=1,
            num_target_samples=2, batch_size=2, cnn_num_filters=4,
            num_stages=2, number_of_training_steps_per_iter=2,
            number_of_evaluation_steps_per_iter=2, use_remat=False,
            serving_bucket_ladder=[1, 2],
            serving_max_tenants_per_dispatch=2,
            compilation_cache_dir="",
        )
    else:
        cfg = MAMLConfig(
            dataset_name="omniglot_dataset",
            image_height=28, image_width=28, image_channels=1,
            num_classes_per_set=5, num_samples_per_class=1,
            num_target_samples=5, batch_size=8, cnn_num_filters=32,
            num_stages=4, number_of_training_steps_per_iter=3,
            number_of_evaluation_steps_per_iter=3,
            compilation_cache_dir="",
        )
    return cfg


def bench_shots_buckets(cfg) -> List[int]:
    """The bench's shots ladder: two buckets, so even the smoke workload
    proves the mixed-bucket no-retrace contract. Shared with
    ``cli serve-export`` so exported artifact fingerprints match the
    engine serve-bench builds."""
    return sorted({cfg.num_samples_per_class,
                   cfg.num_samples_per_class + 1})


def _synth_store(cfg, rows: int = 256, seed: int = 7) -> np.ndarray:
    """A deterministic synthetic uint8 store for the index ingest."""
    rng = np.random.RandomState(seed)
    h, w, c = cfg.im_shape
    return rng.randint(0, 256, (rows, h, w, c)).astype(np.uint8)


def _synth_request(cfg, rng, shots: int, ingest: str, store_rows: int,
                   tenant_id: str):
    from .batcher import AdaptRequest, IndexRequest

    n, t = cfg.num_classes_per_set, cfg.num_target_samples
    h, w, c = cfg.im_shape
    if ingest == "index":
        return IndexRequest(
            support_idx=rng.randint(
                0, store_rows, (n, shots)
            ).astype(np.int32),
            query_idx=rng.randint(0, store_rows, (n, t)).astype(np.int32),
            labeled=True,
            tenant_id=tenant_id,
        )
    if ingest == "uint8":
        sx = rng.randint(0, 256, (n, shots, h, w, c)).astype(np.uint8)
        qx = rng.randint(0, 256, (n, t, h, w, c)).astype(np.uint8)
    else:
        sx = rng.randn(n, shots, h, w, c).astype(np.float32)
        qx = rng.randn(n, t, h, w, c).astype(np.float32)
    return AdaptRequest(
        support_x=sx,
        support_y=np.tile(np.arange(n, dtype=np.int32)[:, None], (1, shots)),
        query_x=qx,
        query_y=np.tile(np.arange(n, dtype=np.int32)[:, None], (1, t)),
        tenant_id=tenant_id,
    )


def _synth_groups(cfg, shots_buckets, n_requests: int, cap: int,
                  seed: int, ingest: str = "f32", store_rows: int = 0,
                  repeat_fraction: float = 0.0) -> List[List]:
    """Deterministic synthetic traffic as DISPATCH GROUPS: group sizes
    cycle 1..cap (every tenant bucket sees steady traffic) and each
    group's shots bucket cycles the configured ladder (every compiled
    program sees steady traffic) — the mixed-bucket pattern the
    zero-retrace contract must hold under.

    ``repeat_fraction`` > 0 makes that fraction of requests REPEAT
    TENANTS: they reuse a previously generated request's support set
    (same content fingerprint — an adapted-params-cache hit once the
    first occurrence has been adapted), modelling the
    same-tenant-returns traffic the cache fast path exists for."""
    rng = np.random.RandomState(seed)
    groups: List[List] = []
    # repeat pool per shots bucket: a reused tenant must reuse its own
    # shots count or the fingerprints can never collide
    pool: dict = {s: [] for s in shots_buckets}
    size, total, g = 1, 0, 0
    while total < n_requests:
        take = min(size, n_requests - total)
        s = shots_buckets[g % len(shots_buckets)]
        group = []
        for _ in range(take):
            if pool[s] and rng.rand() < repeat_fraction:
                prev = pool[s][rng.randint(len(pool[s]))]
                group.append(prev)
            else:
                req = _synth_request(
                    cfg, rng, s, ingest, store_rows,
                    tenant_id=f"tenant-{total + len(group)}",
                )
                pool[s].append(req)
                group.append(req)
        groups.append(group)
        total += take
        g += 1
        size = size + 1 if size < cap else 1
    return groups


class _DeviceOccupancyShim:
    """CPU replica-emulation (``--emulate-device-ms``): proxy one
    replica's engine and hold its dispatch slot for a fixed extra
    window after each ``serve_group`` — the host-side shape of a real
    accelerator dispatch, where the host thread BLOCKS (GIL released,
    core yielded) while the device computes. One replica serializes
    compute + occupancy; N replicas overlap their occupancy windows,
    which is exactly the scaling a real per-device pool exhibits and
    the only scaling observable on a CI box whose XLA:CPU "devices"
    all contend for the same physical core(s). The sleep runs inside
    the replica's swap lock (it proxies the engine the ``Replica``
    dispatches through), so rollover swaps still wait out the full
    emulated dispatch — the zero-drop semantics are exercised
    unchanged."""

    def __init__(self, engine, hold_ms: float):
        self._engine = engine
        self._hold_s = float(hold_ms) / 1e3

    def serve_group(self, requests, queue_ms: float = 0.0):
        out = self._engine.serve_group(requests, queue_ms=queue_ms)
        time.sleep(self._hold_s)
        return out

    def __getattr__(self, name):
        return getattr(self._engine, name)


def _drive_pool(args, cfg, pool, router, requests, state, sink):
    """Drive the replica pool open-loop (and, under ``--rollover``,
    roll a new checkpoint through it MID-LOAD). Returns
    ``{"dropped_requests": n, "rollover": block-or-None}`` — the
    zero-downtime acceptance surface: every submitted future must
    resolve, and every swap must report zero XLA compiles."""
    import shutil
    import tempfile

    daemon = None
    scratch = None
    save_dir = None
    stats = None
    if args.rollover:
        from ..experiment import checkpoint as ckpt
        from .refresh import RefreshDaemon

        scratch = tempfile.mkdtemp(prefix="serve_bench_rollover_")
        save_dir = os.path.join(scratch, "saved_models")
        os.makedirs(save_dir, exist_ok=True)
        ckpt.save_checkpoint(
            save_dir, "train_model", "latest", state, {"current_iter": 0}
        )
        daemon = RefreshDaemon(
            pool, cfg, save_dir, poll_s=0.05, sink=sink
        )
        daemon.prime()
    pendings = [router.submit(r) for r in requests]
    if daemon is not None:
        # write a NEW checkpoint while the pool serves the backlog,
        # then roll on a BACKGROUND thread while this thread keeps
        # waves of live submissions flowing until every swap landed —
        # on any machine speed the swaps contend with real in-flight
        # dispatches (a fast runner could otherwise drain the first
        # wave before the standby even starts warming, making the
        # zero-drop assertion vacuous), and the post-rollover waves
        # prove traffic flows on the fresh snapshot
        import threading

        from ..experiment import checkpoint as ckpt

        ckpt.save_checkpoint(
            save_dir, "train_model", "latest", state, {"current_iter": 1}
        )
        roll_result = []
        roller = threading.Thread(
            target=lambda: roll_result.append(daemon.poll_once()),
            name="serve-bench-rollover",
        )
        roller.start()
        while roller.is_alive():
            wave = [router.submit(r) for r in requests]
            pendings += wave
            for p in wave:
                try:
                    p.get(timeout=600)
                except Exception:  # noqa: BLE001 - counted below
                    pass
        roller.join()
        stats = roll_result[0] if roll_result else None
    dropped = 0
    for p in pendings:
        try:
            p.get(timeout=600)
        except Exception:  # noqa: BLE001 - counted, reported, asserted 0
            dropped += 1
    block = None
    if daemon is not None:
        swaps = stats or []
        block = {
            "rollovers": daemon.rollovers,
            "swaps": len(swaps),
            "xla_compiles_at_swap": sum(
                s.get("xla_compiles_at_swap", 0) for s in swaps
            ),
            "swap_ms_max": (
                max(s.get("swap_ms", 0.0) for s in swaps) if swaps
                else None
            ),
            "standby_warmup_modes": sorted(
                {str(s.get("standby_warmup_mode")) for s in swaps}
            ),
            "rollover_error": (
                repr(daemon.last_error) if daemon.last_error else None
            ),
        }
        shutil.rmtree(scratch, ignore_errors=True)
    return {"dropped_requests": dropped, "rollover": block}


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="serve-bench",
        description="Closed-loop load generator for the adapt-on-request "
                    "serving engine (latency p50/p95, tenants/sec, "
                    "zero-retrace gate)",
    )
    parser.add_argument("--fast", action="store_true",
                        help="seconds-scale smoke workload (the CI gate)")
    parser.add_argument("--config", default=None,
                        help="experiment JSON supplying the geometry and "
                             "serving_* knobs")
    parser.add_argument("--checkpoint", default=None, metavar="DIR",
                        help="serve this saved_models directory's "
                             "checkpoint (read-only restore) instead of a "
                             "fresh init_state snapshot; REQUIRES --config "
                             "with the training run's geometry (the "
                             "restore template and the compiled programs "
                             "are built from it — nothing in the "
                             "checkpoint directory records the config)")
    parser.add_argument("--model-idx", default="latest",
                        help="checkpoint index under --checkpoint "
                             "(default: latest)")
    parser.add_argument("--requests", type=int, default=None,
                        help="synthetic requests to serve (default: 8 "
                             "fast, 64 otherwise)")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--telemetry", default=None, metavar="PATH",
                        help="write serving telemetry records (JSONL, "
                             "schema v9) to this path")
    parser.add_argument("--ingest", default=None,
                        choices=["f32", "uint8", "index"],
                        help="serving ingest tier to drive (default: the "
                             "config's serving_ingest): f32 host pixels, "
                             "uint8 device-decoded pixels (~4x less H2D), "
                             "or index-only dispatch against a synthetic "
                             "resident store (<1KB H2D)")
    parser.add_argument("--repeat-tenant-fraction", type=float, default=0.0,
                        metavar="F",
                        help="fraction of requests that repeat an earlier "
                             "tenant's support set (adapted-params-cache "
                             "hits; enables the cache when > 0)")
    parser.add_argument("--cache-size", type=int, default=None,
                        help="adapted-params LRU capacity (default: the "
                             "config's serving_adapted_cache_size, or "
                             "auto-enabled when --repeat-tenant-fraction "
                             "> 0)")
    parser.add_argument("--export-dir", default=None, metavar="DIR",
                        help="AOT artifact root: warmup loads exported "
                             "executables from here (zero XLA compiles) "
                             "and falls back to compile-then-save — see "
                             "cli serve-export")
    parser.add_argument("--trace", action="store_true",
                        help="emit schema-v10 span records (request/"
                             "queue/assemble/dispatch/sync causal "
                             "timeline) into the --telemetry log; render "
                             "with `cli trace` (requires --telemetry)")
    parser.add_argument("--metrics-port", type=int, default=None,
                        metavar="PORT",
                        help="serve Prometheus text-format metrics on "
                             "127.0.0.1:PORT for the duration of the run "
                             "(0 = ephemeral port; the bound port lands "
                             "in the JSON line as metrics_port)")
    parser.add_argument("--profile-request", default=None, metavar="PATH",
                        help="on-demand device profiling trigger file: "
                             "writing a dispatch count to PATH mid-run "
                             "captures a jax.profiler trace of the next "
                             "N serving dispatches (see utils.profiling."
                             "OnDemandProfiler)")
    parser.add_argument("--replicas", type=int, default=None, metavar="N",
                        help="drive an N-replica shared-nothing pool "
                             "(serving/replica.py) through the cache-"
                             "affinity router instead of one engine: "
                             "requests are submitted open-loop to the "
                             "per-replica micro-batchers and the line "
                             "reports the POOL aggregate tenants_per_sec "
                             "+ per-replica rollups. On CPU the host "
                             "platform is forced to N virtual devices "
                             "(one disjoint device per replica) before "
                             "jax loads — the TPU-free smoke protocol")
    parser.add_argument("--spill-depth", type=int, default=None,
                        metavar="D",
                        help="router spillover depth override (default "
                             "for the bench: the request count, i.e. "
                             "spillover OFF — the closed-loop generator "
                             "saturates every queue by construction, so "
                             "depth-based spilling would only randomize "
                             "placement and dilute the cache-affinity "
                             "measurement; pass a small D to measure "
                             "spillover itself)")
    parser.add_argument("--rollover", action="store_true",
                        help="exercise zero-downtime checkpoint rollover "
                             "MID-LOAD (requires --replicas): the bench "
                             "saves a checkpoint into a scratch "
                             "experiment dir, points a RefreshDaemon at "
                             "it, writes a NEW checkpoint while the pool "
                             "is serving, and rolls every replica onto "
                             "it — the line gains a `rollover` block "
                             "(swaps, swap compiles — must be 0 — and "
                             "dropped requests — must be 0)")
    parser.add_argument("--emulate-device-ms", type=float, default=0.0,
                        metavar="MS",
                        help="CPU replica-emulation recipe (requires "
                             "--replicas): hold each replica's dispatch "
                             "slot for MS extra milliseconds after the "
                             "XLA work — the host-side shape of a real "
                             "accelerator dispatch, where the host "
                             "BLOCKS while the device computes. On a "
                             "TPU pool this is what makes replicas "
                             "scale (each blocks on its OWN device); "
                             "on a shared-core CI box it is the only "
                             "way pool orchestration scaling is "
                             "observable at all: XLA:CPU compute from "
                             "all replicas contends for the same "
                             "core(s) and cannot scale, but the "
                             "occupancy window overlaps perfectly. "
                             "0 (default) disables the shim")
    args = parser.parse_args(argv)
    if args.trace and not args.telemetry:
        parser.error("--trace requires --telemetry: span records ride "
                     "the telemetry JSONL sink")
    if not 0.0 <= args.repeat_tenant_fraction <= 1.0:
        parser.error("--repeat-tenant-fraction must be in [0, 1]")
    if args.checkpoint and not args.config:
        parser.error(
            "--checkpoint requires --config: the checkpoint directory "
            "records no geometry, so the restore template and compiled "
            "programs must come from the training run's experiment JSON "
            "(a mismatched default config would fail the restore — or, "
            "worse, silently serve with the wrong inner-step count)"
        )
    if args.rollover and args.replicas is None:
        parser.error("--rollover requires --replicas (the rollover "
                     "lifecycle is a pool operation; use --replicas 1 "
                     "for a single-replica pool)")
    if args.emulate_device_ms < 0:
        parser.error("--emulate-device-ms must be >= 0, got "
                     f"{args.emulate_device_ms}")
    if args.emulate_device_ms and args.replicas is None:
        parser.error("--emulate-device-ms requires --replicas (the "
                     "device-occupancy shim emulates PER-REPLICA "
                     "device blocking; it has no meaning on the "
                     "single-engine closed loop)")
    if args.replicas is not None:
        if args.replicas < 1:
            parser.error(f"--replicas must be >= 1, got {args.replicas}")
        # each replica needs its own disjoint device; on CPU force the
        # host platform to present enough virtual devices BEFORE jax
        # first loads (the audit-cli --mesh pattern; no effect on a
        # backend whose real chips already exist)
        if "jax" not in sys.modules:
            flags = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    flags
                    + f" --xla_force_host_platform_device_count="
                      f"{args.replicas}"
                ).strip()

    cfg = _bench_cfg(args)
    n_requests = args.requests or (8 if args.fast else 64)
    shots_buckets = bench_shots_buckets(cfg)

    from ..core import maml
    from .batcher import serve_requests
    from .engine import ServingEngine, load_servable_snapshot

    if args.checkpoint:
        # load_servable_snapshot also points the persistent compilation
        # cache at the training run's xla_cache (warm-started warmup)
        state, _ = load_servable_snapshot(
            cfg, args.checkpoint, args.model_idx
        )
    else:
        state = maml.init_state(cfg)

    sink = None
    metrics = None
    metrics_server = None
    if args.telemetry:
        from ..telemetry.sinks import JsonlSink

        sink = JsonlSink(args.telemetry)
    if args.metrics_port is not None:
        # the metrics registry is a telemetry sink teed off the same
        # record stream the JSONL gets — endpoint and log cannot
        # disagree (the HTTP server itself starts AFTER the engine/pool
        # exists, so /healthz can report pool readiness)
        from .metrics import FanoutSink, ServingMetrics

        metrics = ServingMetrics()
        sink = FanoutSink(sink, metrics) if sink is not None else metrics

    tracer = None
    if args.trace:
        from ..telemetry.sinks import make_record
        from ..telemetry.tracing import Tracer

        span_sink = sink

        def _emit(**fields):
            span_sink.write(make_record("span", **fields))

        tracer = Tracer(emit=_emit)

    profiler = None
    if args.profile_request:
        from ..utils.profiling import OnDemandProfiler

        profiler = OnDemandProfiler(
            args.profile_request,
            os.path.dirname(os.path.abspath(args.profile_request))
            or ".",
            trace_id=tracer.trace_id if tracer is not None else None,
        )

    ingest = args.ingest or cfg.serving_ingest
    cache_size = args.cache_size
    if cache_size is None:
        cache_size = cfg.serving_adapted_cache_size
        if args.repeat_tenant_fraction > 0 and cache_size == 0:
            # a repeat-tenant workload without the cache measures
            # nothing; auto-enable it at a capacity the workload fits
            cache_size = max(64, n_requests)
    store = _synth_store(cfg) if ingest == "index" else None

    pool = None
    router = None
    pool_drive = None
    if args.replicas is not None:
        # the multi-replica protocol: one full engine per disjoint
        # device, requests routed by cache affinity, OPEN-LOOP
        # submission into the per-replica micro-batchers — the aggregate
        # tenants_per_sec is total tenants over the union wall-clock
        # span (serving/replica.py rollup)
        from .replica import ReplicaSet
        from .router import ReplicaRouter

        if profiler is not None:
            print("serve-bench: --profile-request applies to the "
                  "single-engine path; ignored under --replicas",
                  file=sys.stderr, flush=True)
        if cfg.watchdog_timeout_s > 0:
            # the PR-14 watchdog wraps ONE engine's dispatch heartbeat;
            # per-replica watchdogs (which must survive rollover engine
            # swaps) are future work — say so instead of silently
            # dropping the knob
            print("serve-bench: watchdog_timeout_s applies to the "
                  "single-engine path; NOT wired under --replicas "
                  "(per-replica watchdogs are future work)",
                  file=sys.stderr, flush=True)
        import jax

        pool_devices = None
        if (jax.default_backend() == "cpu"
                and len(jax.devices()) > args.replicas):
            # virtual host devices beyond the pool width are
            # meaningless (an already-initialized jax, e.g. in-process
            # tests, may present more than --replicas forced): take
            # width-1 slices. On a real accelerator the pool partitions
            # every chip and warns about idle capacity instead.
            pool_devices = list(jax.devices())[:args.replicas]
        pool = ReplicaSet(
            cfg, state, n_replicas=args.replicas, devices=pool_devices,
            shots_buckets=shots_buckets, sink=sink, strict_retrace=True,
            ingest=ingest, store=store, cache_size=cache_size,
            tracer=tracer, metrics=metrics, export_root=args.export_dir,
        )
        engine = pool.replicas[0].engine  # line metadata (shared knobs)
        if args.metrics_port is not None:
            from .metrics import MetricsServer

            metrics_server = MetricsServer(
                metrics, port=args.metrics_port,
                readiness=pool.readiness,
            )
            print(f"serve-bench: metrics at {metrics_server.url}",
                  file=sys.stderr, flush=True)
        warmup_s = pool.warmup()
        if args.emulate_device_ms:
            # shim AFTER warmup (compiles must stay un-padded) and shim
            # the rollover standby builder too, so swapped-in engines
            # keep the same emulated occupancy as the ones they replace
            for r in pool.replicas:
                r.engine = _DeviceOccupancyShim(
                    r.engine, args.emulate_device_ms
                )
            _build = pool.build_standby_engine

            def _shimmed_standby(rid, st, snapshot_id=None):
                return _DeviceOccupancyShim(
                    _build(rid, st, snapshot_id), args.emulate_device_ms
                )

            pool.build_standby_engine = _shimmed_standby
        # spillover default: OFF for the closed-loop generator (every
        # queue is saturated by construction, so depth spilling would
        # only randomize placement and dilute the affinity measurement)
        spill = (
            args.spill_depth if args.spill_depth is not None
            else max(cfg.serving_router_spill_depth, n_requests)
        )
        router = ReplicaRouter(pool, spill_depth=spill)
        groups = _synth_groups(
            cfg, shots_buckets, n_requests, engine.max_tenants,
            args.seed, ingest=ingest, store_rows=engine._store_rows,
            repeat_fraction=args.repeat_tenant_fraction,
        )
        requests = [r for g in groups for r in g]
        pool_drive = _drive_pool(args, cfg, pool, router, requests,
                                 state, sink)
        rollup = pool.rollup()
        pool.close()
    else:
        engine = ServingEngine(
            cfg, state, shots_buckets=shots_buckets, sink=sink,
            strict_retrace=True, ingest=ingest, store=store,
            cache_size=cache_size, tracer=tracer, profiler=profiler,
        )
        if args.metrics_port is not None:
            from .metrics import MetricsServer

            metrics_server = MetricsServer(metrics, port=args.metrics_port)
            print(f"serve-bench: metrics at {metrics_server.url}",
                  file=sys.stderr, flush=True)
    watchdog = None
    if args.replicas is None:
        if cfg.watchdog_timeout_s > 0:
            # a wedged serving dispatch must produce a watchdog_stall
            # record, not a silent hang — same contract as the train loop
            from .engine import attach_serving_watchdog

            watchdog = attach_serving_watchdog(
                engine, cfg.watchdog_timeout_s, sink=sink,
            )
        warmup_s = engine.warmup(artifact_dir=args.export_dir)

        groups = _synth_groups(
            cfg, shots_buckets, n_requests, engine.max_tenants, args.seed,
            ingest=ingest, store_rows=engine._store_rows,
            repeat_fraction=args.repeat_tenant_fraction,
        )
        for group in groups:
            serve_requests(engine, group)

        rollup = engine.rollup()
    if profiler is not None:
        profiler.close()
    if watchdog is not None:
        watchdog.stop()
    if metrics_server is not None:
        metrics_server.close()
    if sink is not None:
        sink.close()
    line = {
        "metric": "serving_adaptation_latency_ms",
        "value": rollup["adapt_ms_p50"],
        "unit": "ms",
        "adaptation_latency_ms_p50": rollup["adapt_ms_p50"],
        "adaptation_latency_ms_p95": rollup["adapt_ms_p95"],
        # the engine's rollup is the ONE definition of this metric — the
        # printed line and the telemetry rollup record can never disagree
        "tenants_per_sec": rollup["tenants_per_sec"],
        "dispatches": rollup["dispatches"],
        "tenants": rollup["tenants"],
        "retraces": rollup["retraces"],
        "warmup_seconds": round(warmup_s, 3),
        # the latency decomposition (schema v10): queue wait + host batch
        # assembly + device dispatch enqueue + blocking sync account for
        # the end-to-end latency (adapt = dispatch + sync by definition)
        "queue_ms_p50": rollup["queue_ms_p50"],
        "batch_ms_mean": rollup["batch_ms_mean"],
        "dispatch_ms_p50": rollup["dispatch_ms_p50"],
        "sync_ms_p50": rollup["sync_ms_p50"],
        "metrics_port": (
            metrics_server.port if metrics_server is not None else None
        ),
        "traced": bool(args.trace),
        # the fast-path acceptance surface: measured H2D per dispatch
        # (the ingest tiers' ratio is the bench's 4x/index claim), cache
        # hit rate, and how warmup materialized its programs (the AOT
        # artifact path reports mode='artifacts' with 0 compiles)
        "ingest": rollup["ingest"],
        "h2d_bytes_per_dispatch": rollup["h2d_bytes_per_dispatch"],
        "cache_hit_rate": rollup["cache_hit_rate"],
        "cache_size": engine.cache_size,
        "repeat_tenant_fraction": float(args.repeat_tenant_fraction),
        "warmup_mode": engine.warmup_stats.get("mode"),
        "warmup_xla_compiles": engine.warmup_stats.get("xla_compiles"),
        "bucket_ladder": list(engine.buckets),
        "shots_buckets": list(engine.shots_buckets),
        "max_tenants_per_dispatch": engine.max_tenants,
        "fast": bool(args.fast),
    }
    if pool is not None:
        # the pool surface: aggregate tenants_per_sec is total tenants
        # over the UNION wall-clock span (never a sum of per-replica
        # rates — their spans overlap), per-replica rollups ride along,
        # and the router reports how affinity/spillover placed traffic
        line["replicas"] = rollup["replicas"]
        line["per_replica"] = [
            {
                "replica_id": ru["replica_id"],
                "dispatches": ru["dispatches"],
                "tenants": ru["tenants"],
                "adapt_ms_p50": ru["adapt_ms_p50"],
                "tenants_per_sec": ru["tenants_per_sec"],
                "cache_hit_rate": ru["cache_hit_rate"],
            }
            for ru in rollup["per_replica"]
        ]
        line["router"] = router.stats()
        line["dropped_requests"] = pool_drive["dropped_requests"]
        line["rollover"] = pool_drive["rollover"]
        line["emulate_device_ms"] = args.emulate_device_ms
        # every replica warmed; the line's single warmup fields reflect
        # replica 0, the totals say whether ANY replica compiled
        line["warmup_xla_compiles_total"] = sum(
            r.engine.warmup_stats.get("xla_compiles", 0)
            for r in pool.replicas
        )
    import jax

    line["backend"] = jax.default_backend()
    print(json.dumps(line))
    return 0


if __name__ == "__main__":
    sys.exit(main())
