"""Host-side micro-batching front end for the serving engine.

Two entry points share the grouping policy:

* ``serve_requests(engine, requests)`` — SYNCHRONOUS closed-loop API:
  partition a request list into per-shots groups of at most
  ``serving_max_tenants_per_dispatch``, dispatch each group through
  ``ServingEngine.serve_group``, and return results aligned with the
  input order. The deterministic path — tests, batch jobs, the
  ``serve-bench`` load generator.
* ``MicroBatcher`` — the ONLINE front end: ``submit()`` enqueues a
  request into its shots bucket's queue and returns a handle;
  a worker thread dispatches a queue when it holds
  ``serving_max_tenants_per_dispatch`` requests OR its oldest request
  has waited ``serving_max_wait_ms`` — the classic max-batch/max-wait
  latency-throughput dial. Per-request queue time rides into the
  telemetry ``serving`` records as the dispatch's mean ``queue_ms``.
  ``close()`` SERVES every queued request before the worker exits (and
  fails — never strands — anything a crashed worker left behind).

Shots are a BUCKET KEY, never a padding axis: requests with different
support-shot counts go to different queues and different compiled
programs (pad support samples would enter the adaptation loss). Tenant
count IS padded — up to the bucket ladder — with masked zeros the engine
proves inert.
"""

from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

import numpy as np


@dataclass
class AdaptRequest:
    """One tenant's adapt-then-predict request (pixel ingests).

    Arrays are NHWC, matching the engine config's task geometry:
    ``support_x`` (way, shots, h, w, c), ``support_y`` (way, shots),
    ``query_x`` (way, targets, h, w, c), and optionally ``query_y``
    (way, targets) when the caller wants query loss/accuracy back
    (predictions never need labels). Pixel dtype follows the engine's
    ingest tier: float32 decoded pixels for ``ingest='f32'``, RAW uint8
    pixels for ``ingest='uint8'`` (decoded on device — the engine
    refuses a mismatched dtype rather than silently casting).
    """

    support_x: np.ndarray
    support_y: np.ndarray
    query_x: np.ndarray
    query_y: Optional[np.ndarray] = None
    tenant_id: Optional[str] = None
    #: per-request latency budget in ms, counted from ``submit()``.
    #: None (default) opts out of deadline accounting; when set, the
    #: micro-batcher emits one ``event='deadline'`` serving record for
    #: this request — slack or miss, with the stage attribution
    #: (queue/route/assemble/dispatch/sync) — at resolution.
    deadline_ms: Optional[float] = None
    #: admission tier stamped by the fleet gateway (0 = highest; see
    #: serving/gateway.py) — None for in-process traffic that never
    #: crossed the edge. Rides into the deadline record when set.
    priority: Optional[int] = None
    #: milliseconds the request spent at the network edge (gateway
    #: decode + admission + forward) before the home host enqueued it —
    #: the gateway's share of the deadline record's stage attribution.
    #: None for in-process traffic.
    gateway_ms: Optional[float] = None
    #: the gateway's trace baggage (serving/fleet.py stamps it from the
    #: wire header when the edge is tracing): ``trace_id`` /
    #: ``parent_span_id`` — the batcher's root span then parents under
    #: the gateway's forward span, carrying the gateway's trace id —
    #: plus the pass-through ``request_id`` and the edge's current
    #: ``clock_offset_ms`` estimate for this host. None for in-process
    #: traffic AND whenever the edge isn't tracing.
    trace_ctx: Optional[Dict[str, Any]] = None

    @property
    def shots(self) -> int:
        return int(np.asarray(self.support_x).shape[1])


@dataclass
class IndexRequest:
    """One tenant's request as STORE ROWS (``ingest='index'``).

    The engine holds a registered uint8 ``FlatStore`` resident in HBM;
    an index request ships only int32 row tensors — ``support_idx``
    (way, shots) and ``query_idx`` (way, targets) — so per-request H2D
    is a few hundred bytes. Labels never cross H2D: sample (i, j) of
    either set carries label i by construction (slot iota — rows must be
    grouped by class slot, the training index-path convention).
    ``labeled=False`` marks a tenant whose query grouping is NOT
    truthful (unknown query classes): its predictions are unaffected,
    but it is masked out of loss/accuracy like a label-free pixel
    request.
    """

    support_idx: np.ndarray
    query_idx: np.ndarray
    labeled: bool = True
    tenant_id: Optional[str] = None
    #: see ``AdaptRequest.deadline_ms``
    deadline_ms: Optional[float] = None
    #: see ``AdaptRequest.priority`` / ``AdaptRequest.gateway_ms`` /
    #: ``AdaptRequest.trace_ctx``
    priority: Optional[int] = None
    gateway_ms: Optional[float] = None
    trace_ctx: Optional[Dict[str, Any]] = None

    @property
    def shots(self) -> int:
        return int(np.asarray(self.support_idx).shape[1])


def update_support_digest(h, request) -> None:
    """Feed EXACTLY a request's adaptation-identity content — the
    support set, in index or pixel form, with shapes/dtype — into the
    hashlib object ``h``. THE shared recipe with two consumers: the
    engine's adapted-params cache key (``ServingEngine._cache_key``:
    this content + shots + the engine-local snapshot salt) and the
    router's affinity fingerprint
    (``serving.router.request_fingerprint``: this content alone — the
    shots and salt suffixes are deliberately router-excluded). Affinity
    routing only preserves pool cache-hit rates while the router's
    identity keeps covering the cache identity's CONTENT core, so any
    content field added to the cache key must be added here — one
    recipe keeps them in lockstep by construction."""
    support_idx = getattr(request, "support_idx", None)
    if support_idx is not None:
        si = np.ascontiguousarray(np.asarray(support_idx, np.int64))
        h.update(b"index|")
        h.update(str(si.shape).encode())
        h.update(si)
    else:
        sx = np.ascontiguousarray(np.asarray(request.support_x))
        sy = np.ascontiguousarray(
            np.asarray(request.support_y, np.int64)
        )
        h.update(b"pixel|")
        h.update(str(sx.shape).encode())
        h.update(str(sx.dtype).encode())
        h.update(sx)
        h.update(sy)


def engine_ready(engine) -> bool:
    """True when an engine (or a ``serving.replica.Replica`` proxying
    one) can serve a dispatch right now without dying or paying a
    cold-start compile bill: it completed ``warmup()`` OR has already
    served traffic (a lazily-compiled engine that never called warmup()
    but has been dispatching keeps the drain guarantee), and has not
    died mid-dispatch — the gate between ``close()``'s
    drain-the-backlog semantics and the immediate shutdown a
    broken/never-started replica needs."""
    return (
        getattr(engine, "_dead", None) is None
        and (
            bool(getattr(engine, "warmup_stats", None))
            or getattr(engine, "_tenants_served", 0) > 0
        )
    )


def group_requests(
    requests: Sequence[AdaptRequest], max_tenants: int
) -> List[List[int]]:
    """The shared grouping policy: stable-partition request INDICES by
    shots bucket, then chunk each partition at ``max_tenants``. Order is
    preserved within a bucket, so results can be re-aligned by index."""
    if max_tenants < 1:
        raise ValueError(f"max_tenants must be >= 1, got {max_tenants}")
    by_shots: Dict[int, List[int]] = {}
    for i, req in enumerate(requests):
        by_shots.setdefault(req.shots, []).append(i)
    groups: List[List[int]] = []
    for shots in sorted(by_shots):
        idxs = by_shots[shots]
        for at in range(0, len(idxs), max_tenants):
            groups.append(idxs[at:at + max_tenants])
    return groups


def serve_requests(
    engine, requests: Sequence[AdaptRequest],
    max_tenants: Optional[int] = None,
):
    """Serve a request list synchronously; returns
    ``(results, dispatches)`` where ``results[i]`` is request i's
    ``TenantResult`` and ``dispatches`` the per-dispatch
    ``DispatchResult`` list (latency + masked metrics, in dispatch
    order)."""
    cap = engine.max_tenants if max_tenants is None else min(
        int(max_tenants), engine.max_tenants
    )
    results: List[Any] = [None] * len(requests)
    dispatches = []
    for idxs in group_requests(requests, cap):
        dr = engine.serve_group([requests[i] for i in idxs])
        dispatches.append(dr)
        for i, res in zip(idxs, dr.results):
            results[i] = res
    return results, dispatches


@dataclass
class _Pending:
    """A submitted request waiting for its dispatch.

    ``span`` / ``queue_span`` (tracing on only): the request's root span
    — opened at submit, closed when the future resolves — and its
    ``queue`` child covering the micro-batcher wait. The worker thread
    adopts the root as parent around the engine dispatch, so one
    request's tree spans queue → assemble → dispatch → sync across
    threads.
    """

    request: AdaptRequest
    enqueued: float
    done: threading.Event = field(default_factory=threading.Event)
    result: Any = None
    error: Optional[BaseException] = None
    span: Any = None
    queue_span: Any = None
    #: absolute perf_counter() deadline (enqueued + request.deadline_ms)
    #: — None when the request opted out of deadline accounting
    deadline: Optional[float] = None
    #: router decision time (ms) stamped by ReplicaRouter.submit — the
    #: 'route' share of the deadline record's stage attribution
    route_ms: float = 0.0

    def get(self, timeout: Optional[float] = None):
        """Block until the request was served; returns its
        ``TenantResult`` or re-raises the dispatch's error."""
        if not self.done.wait(timeout):
            raise TimeoutError("request not served within timeout")
        if self.error is not None:
            raise self.error
        return self.result


class MicroBatcher:
    """Online max-batch / max-wait micro-batcher feeding one engine.

    One worker thread drains per-shots queues: a queue dispatches when
    it holds ``max_tenants`` requests, or when its oldest request has
    waited ``max_wait_ms`` (0 => dispatch immediately). ``submit()``
    returns a ``_Pending`` handle whose ``get()`` blocks for the result.
    ``close()`` drains every queue, then stops the worker.

    Single-engine, single-worker by design: the engine serializes on the
    donated state anyway, so one dispatcher thread is the contention-free
    shape; scale-out is more engines (one per replica), not more threads.
    """

    def __init__(self, engine, max_tenants: Optional[int] = None,
                 max_wait_ms: Optional[float] = None, metrics=None):
        self.engine = engine
        # optional ServingMetrics (serving/metrics.py): the batcher
        # reports its backlog as the serving_queue_depth gauge
        self.metrics = metrics
        self.max_tenants = (
            engine.max_tenants if max_tenants is None
            else min(int(max_tenants), engine.max_tenants)
        )
        if self.max_tenants < 1:
            # 0 would make every queue "full" with an empty group — the
            # worker would spin forever and close() would never join
            raise ValueError(
                f"max_tenants must be >= 1, got {self.max_tenants}"
            )
        self.max_wait_ms = (
            float(engine.cfg.serving_max_wait_ms)
            if max_wait_ms is None else float(max_wait_ms)
        )
        if self.max_wait_ms < 0:
            raise ValueError(
                f"max_wait_ms must be >= 0, got {self.max_wait_ms}"
            )
        self._queues: Dict[int, List[_Pending]] = {}
        self._request_ids = itertools.count(1)
        self._cond = threading.Condition()
        self._closed = False
        # close() normally DRAINS (serves every queued request before the
        # worker exits); a close against a never-warmed or dead engine
        # flips this off so shutdown is immediate — dispatching there
        # would pay the full lazy-compile bill (or a doomed dispatch)
        # just to tear the replica down (the circuit-breaker drain path)
        self._drain_on_close = True
        self._worker = threading.Thread(
            target=self._run, name="serving-batcher", daemon=True
        )
        self._worker.start()

    def queue_depth(self) -> int:
        """Current backlog across every shots queue — the router's
        spillover signal and the metrics queue-depth gauge."""
        with self._cond:
            return sum(len(q) for q in self._queues.values())

    @property
    def worker_alive(self) -> bool:
        """False once the worker thread crashed or exited — a replica
        whose dispatcher died must read unhealthy to the router."""
        return self._worker.is_alive()

    def submit(self, request: AdaptRequest) -> _Pending:
        # validate HERE, against the engine geometry, so a malformed
        # request raises to ITS submitter — deferred to dispatch time it
        # would fail the whole co-batched group with someone else's
        # shape error
        self.engine._validate(request)
        pending = _Pending(request=request, enqueued=time.perf_counter())
        deadline_ms = getattr(request, "deadline_ms", None)
        if deadline_ms is not None:
            if float(deadline_ms) <= 0:
                raise ValueError(
                    f"deadline_ms must be > 0, got {deadline_ms}"
                )
            pending.deadline = pending.enqueued + float(deadline_ms) / 1e3
        tracer = self.engine.tracer
        if tracer.enabled:
            # the request's causal root: request_id ties every stage of
            # this request together across threads; closed when the
            # future resolves (success, dispatch error, or close() sweep).
            # A gateway-minted trace (request.trace_ctx, stamped from the
            # wire header by serving/fleet.py) is ADOPTED: the root
            # parents under the gateway's forward span and inherits its
            # trace id, so `cli trace --fleet` reassembles one tree
            ctx = getattr(request, "trace_ctx", None) or {}
            parent = None
            root_attrs: Dict[str, Any] = {}
            if ctx.get("trace_id") and ctx.get("parent_span_id"):
                from ..telemetry.tracing import remote_span

                parent = remote_span(
                    str(ctx["trace_id"]), str(ctx["parent_span_id"])
                )
                offset = ctx.get("clock_offset_ms")
                if offset is not None:
                    root_attrs["clock_offset_ms"] = offset
            request_id = (
                ctx.get("request_id")
                or f"{tracer.trace_id}-r{next(self._request_ids):06d}"
            )
            pending.span = tracer.start_span(
                "request", cat="serving", parent=parent,
                request_id=request_id, shots=request.shots,
                tenant_id=getattr(request, "tenant_id", None),
                **root_attrs,
            )
            pending.queue_span = tracer.start_span(
                "queue", cat="serving", parent=pending.span,
                shots=request.shots,
            )
        with self._cond:
            if self._closed:
                raise RuntimeError("MicroBatcher is closed")
            self._queues.setdefault(request.shots, []).append(pending)
            depth = sum(len(q) for q in self._queues.values())
            self._cond.notify()
        if self.metrics is not None:
            self.metrics.observe_queue_depth(depth)
        return pending

    def close(self, drain: Optional[bool] = None) -> None:
        """Drain every queue, then stop the worker thread.

        In-flight requests at close() are SERVED (the worker dispatches
        every non-empty per-shots queue before exiting — the drain
        guarantee), and anything that could NOT be served — the worker
        crashed, or died before reaching a queue — is FAILED with the
        root cause, never left as a hanging future: ``close()`` sweeps
        the queues after the join as a final safety net (a dead worker's
        join returns immediately, which previously stranded its queued
        futures forever).

        ``drain`` defaults to auto: when the engine never completed
        ``warmup()`` or is already dead, the drain dispatches are
        SKIPPED and shutdown is immediate — serving the backlog there
        would block the join on the full lazy-compile bill (or a doomed
        post-donation dispatch) just to tear a broken replica down; the
        queued futures fail promptly with a clear error instead (the
        circuit-breaker drain semantics, serving/router.py). Pass
        ``drain=False`` to force the immediate path, ``drain=True`` to
        force a full drain regardless.
        """
        if drain is None:
            drain = engine_ready(self.engine)
        with self._cond:
            self._closed = True
            self._drain_on_close = bool(drain)
            self._cond.notify()
        self._worker.join()
        self._fail_pending(
            RuntimeError(
                "MicroBatcher closed before this request could be served "
                + ("(worker exited early)" if drain else
                   "(engine never warmed or is dead — close skipped the "
                   "drain dispatches for an immediate shutdown)")
            )
        )

    def _fail_pending(self, error: BaseException) -> None:
        """Fail every still-queued request (worker crash / late close
        safety net); requests already served are untouched."""
        with self._cond:
            leftovers = [p for q in self._queues.values() for p in q]
            self._queues.clear()
        tracer = self.engine.tracer
        for p in leftovers:
            if not p.done.is_set():
                p.error = error
                p.done.set()
                tracer.end_span(p.queue_span, outcome="failed")
                tracer.end_span(p.span, outcome="failed")

    # -- worker ------------------------------------------------------------

    def _ripe_group(self) -> Optional[List[_Pending]]:
        """Pop the ripe queue (full, past its wait deadline, or draining
        at close) whose HEAD has waited longest — oldest-first across
        queues, so a saturated low-shots queue can never starve another
        shots bucket past its max-wait promise (caller holds the lock);
        None when nothing is ripe yet."""
        if self._closed and not self._drain_on_close:
            # immediate shutdown: nothing is ripe — the worker exits and
            # close() fails the backlog instead of dispatching it
            return None
        now = time.perf_counter()
        ripe_shots, oldest = None, None
        for shots, q in self._queues.items():
            if not q:
                continue
            full = len(q) >= self.max_tenants
            expired = (now - q[0].enqueued) * 1e3 >= self.max_wait_ms
            if (full or expired or self._closed) and (
                oldest is None or q[0].enqueued < oldest
            ):
                ripe_shots, oldest = shots, q[0].enqueued
        if ripe_shots is None:
            return None
        q = self._queues[ripe_shots]
        group = q[:self.max_tenants]
        self._queues[ripe_shots] = q[self.max_tenants:]
        return group

    def _next_deadline_s(self) -> Optional[float]:
        """Seconds until the oldest queued request's wait expires (caller
        holds the lock); None when every queue is empty."""
        oldest = min(
            (q[0].enqueued for q in self._queues.values() if q),
            default=None,
        )
        if oldest is None:
            return None
        return max(
            0.0, self.max_wait_ms / 1e3 - (time.perf_counter() - oldest)
        )

    def _run(self) -> None:
        try:
            self._run_loop()
        except BaseException as e:  # noqa: BLE001 - worker crash: the
            # queues' futures must FAIL with the root cause, not hang
            # forever waiting on a dead thread
            err = RuntimeError(
                "MicroBatcher worker crashed; request was never "
                "dispatched (root cause chained below)"
            )
            err.__cause__ = e
            self._fail_pending(err)
            raise

    def _run_loop(self) -> None:
        while True:
            with self._cond:
                group = self._ripe_group()
                depth = sum(len(q) for q in self._queues.values())
                if group is None:
                    if self._closed:
                        return
                    self._cond.wait(timeout=self._next_deadline_s())
                    continue
            if self.metrics is not None:
                self.metrics.observe_queue_depth(depth)
            # dispatch OUTSIDE the lock: submit() stays non-blocking
            # while the device works
            now = time.perf_counter()
            queue_ms = float(
                np.mean([(now - p.enqueued) * 1e3 for p in group])
            )
            tracer = self.engine.tracer
            for p in group:
                # the queue wait ends here: the group is off its queue
                # and about to assemble/dispatch
                tracer.end_span(p.queue_span)
                p.queue_span = None
            try:
                # the first request's root span adopts the dispatch work:
                # the engine's assemble/dispatch/sync spans (emitted on
                # THIS worker thread) nest under a request, so at least
                # one request's tree spans queue -> dispatch -> sync
                with tracer.use_parent(group[0].span):
                    dr = self.engine.serve_group(
                        [p.request for p in group], queue_ms=queue_ms
                    )
                for p, res in zip(group, dr.results):
                    p.result = res
                    p.done.set()
                    tracer.end_span(
                        p.span, bucket=dr.bucket, outcome="served",
                    )
                self._record_deadlines(group, now, dr=dr)
            except BaseException as e:  # noqa: BLE001 - relayed to callers
                for p in group:
                    p.error = e
                    p.done.set()
                    tracer.end_span(p.span, outcome="error")
                self._record_deadlines(group, now, failed=True)

    def _record_deadlines(self, group: List[_Pending], dequeued: float,
                          dr: Any = None, failed: bool = False) -> None:
        """One ``event='deadline'`` serving record per deadline-carrying
        request in the resolved group: slack (positive = met) or miss,
        with the stage attribution — this request's own queue wait, its
        router decision time, and the dispatch's assemble(batch)/
        dispatch/sync decomposition. A FAILED dispatch counts as a miss
        (the availability objective is over useful responses), flagged
        ``failed`` so miss forensics can split overload from errors.
        Requests without a deadline emit nothing — closed-loop traffic
        is unchanged."""
        record = getattr(self.engine, "_record", None)
        if record is None:
            return
        resolved = time.perf_counter()
        for p in group:
            if p.deadline is None:
                continue
            slack_ms = (p.deadline - resolved) * 1e3
            fields: Dict[str, Any] = dict(
                event="deadline",
                tenant_id=getattr(p.request, "tenant_id", None),
                shots=p.request.shots,
                deadline_ms=round(float(p.request.deadline_ms), 3),
                slack_ms=round(slack_ms, 3),
                missed=bool(failed or slack_ms < 0),
                e2e_ms=round((resolved - p.enqueued) * 1e3, 3),
                queue_ms=round((dequeued - p.enqueued) * 1e3, 3),
                route_ms=round(p.route_ms, 3),
            )
            if failed:
                fields["failed"] = True
            # gateway-path attribution (schema v13): present only when
            # the request crossed the network edge (serving/gateway.py
            # stamps both) — in-process traffic emits the v12 shape
            priority = getattr(p.request, "priority", None)
            if priority is not None:
                fields["priority"] = int(priority)
            gateway_ms = getattr(p.request, "gateway_ms", None)
            if gateway_ms is not None:
                fields["gateway_ms"] = round(float(gateway_ms), 3)
            if dr is not None:
                fields.update(
                    batch_ms=round(dr.batch_ms, 3),
                    dispatch_ms=round(dr.dispatch_ms, 3),
                    sync_ms=round(dr.sync_ms, 3),
                )
            record(**fields)
