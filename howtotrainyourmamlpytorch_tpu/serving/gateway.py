"""The networked fleet front tier: one endpoint over N host processes.

PR 15's ``ReplicaRouter`` scales serving across replicas in one process;
this module lifts the same affinity/trip/re-home semantics one level, to
a fleet of HOST processes (each running a full ``ReplicaSet`` behind
:mod:`serving.fleet`), and adds the SLO *enforcement* the pool only
measures:

* **Wire schema** — one framed binary format for requests and
  responses: a 4-byte big-endian header length, a JSON header (kind /
  tenant / deadline / priority / array manifest), then the raw C-order
  array buffers concatenated in manifest order. The arrays ship in the
  PR-13 ingest encodings, so the compression the device ingest tiers
  bought applies ON THE WIRE too: uint8 support sets are ~4x smaller
  than f32, and an index request against a fleet-resident store is a
  few hundred bytes of int32 rows.
* **Fleet-wide cache affinity** — ``home_host`` hashes the SAME
  ``batcher.update_support_digest`` content fingerprint the in-process
  router and the engine's adapted-params cache key use
  (``router.request_fingerprint``), over the sorted host-id ring. A
  tenant's adapted-params LRU entry therefore lives on exactly one
  host, fleet-wide, and routing identity can never drift from cache
  identity — one recipe, three consumers.
* **Admission control** — a request is REJECTED AT THE EDGE with a
  typed response (HTTP 429, ``reason='admission'``) when its home
  host's load estimate (last-polled queue depth + the gateway's own
  in-flight count) reaches the per-host budget
  (``serving_gateway_queue_budget``), right-shifted by the request's
  priority tier (tier 0 keeps the full budget, tier 1 half, ...).
* **Deadline-aware shedding** — a deadline-carrying request whose
  budget cannot cover the home host's current queue estimate
  (load x an EWMA of observed host service time — conservative by
  construction: the EWMA includes host queue wait, so overload sheds
  harder and self-corrects as the queue drains) is rejected typed
  (``reason='deadline'``) instead of joining a queue it can only
  collapse. Both shed shapes emit ``gateway`` telemetry records
  (schema v13).
* **Health-checked membership + deterministic re-homing** — a
  background thread polls each host's ``/healthz``; a host that stops
  answering AFTER it was ready is tripped (latched, PR-15 semantics:
  never-ready hosts are skipped, not tripped). The ring POSITIONS are
  fixed at construction, so losing host k deterministically re-homes
  exactly k's tenants to the next ready host on the ring — every other
  home assignment is untouched. A host that dies BETWEEN sweeps is
  caught at forward time: the in-flight socket request fails
  immediately with the chained root cause (the PR-13/15
  batcher-crash semantics at the network layer), the host is tripped,
  and the request is retried on its re-homed host — adapt-on-request
  is a pure function of (support, query, snapshot), so the retry is
  idempotent; only a fleet with NO ready host left returns the typed
  ``host_down`` failure (HTTP 503, root causes chained in the body).
* **Fleet rollup** — ``rollup()`` fetches every ready host's
  ``/rollup`` and merges the per-host ``LogHistogram`` buckets EXACTLY
  (serving/metrics.py — the PR-17 mergeable-histogram machinery), so
  fleet p99 and burn rates come from one histogram family, never from
  averaged percentiles.
* **Keep-alive forwarding** — each host handle keeps a small pool of
  ``http.client`` connections; a reused keep-alive that fails
  mid-request (the host closed it between requests — indistinguishable
  from a death at the socket level) earns exactly ONE retry on a
  guaranteed-fresh socket before the failure trips the host, so stale
  pool entries never masquerade as host loss and real loss is still
  caught on the first fresh socket. ``/stats`` reports the reuse rate.
* **Distributed tracing** — with a tracer wired, the gateway mints one
  trace per request (root ``request`` span backdated to edge arrival,
  ``gateway_queue`` for decode+admission, per-attempt ``forward`` +
  ``wire`` children — re-home retries are SIBLING forwards under the
  same root, typed sheds zero-duration ``shed`` spans) and carries
  ``trace_id`` / ``parent_span_id`` baggage in the forward frame's
  header so the host-side tree parents under the gateway's forward
  span. Tracing off is the NULL_TRACER one-attribute check and the
  forward frames stay byte-identical to the schema-v13 wire (the trace
  keys are simply absent). Because the processes never share a clock,
  the health sweep doubles as a Cristian clock-offset estimator
  (``ClockOffsetEstimator``): ``cli trace --fleet`` merges the
  per-process logs into one clock-aligned Perfetto export.

Everything here is stdlib + numpy — importable (and testable) without
jax, like the router it extends.
"""

from __future__ import annotations

import http.client
import itertools
import json
import struct
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..telemetry.tracing import NULL_TRACER, new_trace_id
from .batcher import AdaptRequest, IndexRequest
from .router import home_replica, request_fingerprint

#: request/response content type for the framed binary wire format
WIRE_CONTENT_TYPE = "application/x-maml-wire"


class WireError(ValueError):
    """A frame that cannot be decoded (truncated, bad manifest, short
    buffers) — the gateway answers HTTP 400, never a stack trace."""


class HostDownError(RuntimeError):
    """No ready host left to serve a request; ``__cause__`` chains the
    last forward failure's root cause (the network-layer twin of the
    batcher's worker-crash chaining)."""


# -- wire codec --------------------------------------------------------------


def _encode_frame(header: Dict[str, Any],
                  buffers: Sequence[bytes]) -> bytes:
    hb = json.dumps(header).encode("utf-8")
    return struct.pack(">I", len(hb)) + hb + b"".join(buffers)


def _decode_frame(payload: bytes) -> Tuple[Dict[str, Any], bytes]:
    """Split a frame into (header, concatenated buffer blob)."""
    if len(payload) < 4:
        raise WireError(
            f"wire frame truncated: {len(payload)} bytes, need >= 4"
        )
    (hlen,) = struct.unpack_from(">I", payload)
    if len(payload) < 4 + hlen:
        raise WireError(
            f"wire frame truncated: header says {hlen} bytes, frame "
            f"holds {len(payload) - 4}"
        )
    try:
        header = json.loads(payload[4:4 + hlen].decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise WireError(f"wire header is not valid JSON: {e}") from e
    if not isinstance(header, dict):
        raise WireError(
            f"wire header must be an object, got "
            f"{type(header).__name__}"
        )
    return header, payload[4 + hlen:]


def _array_manifest(named: Sequence[Tuple[str, np.ndarray]]) -> Tuple[
        List[Dict[str, Any]], List[bytes]]:
    manifest, buffers = [], []
    for name, arr in named:
        arr = np.ascontiguousarray(arr)
        manifest.append({
            "name": name,
            "dtype": str(arr.dtype),
            "shape": list(arr.shape),
        })
        buffers.append(arr.tobytes())
    return manifest, buffers


def _decode_arrays(header: Dict[str, Any],
                   blob: bytes) -> Dict[str, np.ndarray]:
    arrays: Dict[str, np.ndarray] = {}
    at = 0
    for spec in header.get("arrays", []):
        try:
            dtype = np.dtype(spec["dtype"])
            shape = tuple(int(d) for d in spec["shape"])
            name = spec["name"]
        except (KeyError, TypeError, ValueError) as e:
            raise WireError(f"bad array manifest entry {spec!r}") from e
        nbytes = dtype.itemsize * int(np.prod(shape, dtype=np.int64))
        if at + nbytes > len(blob):
            raise WireError(
                f"wire buffers truncated: array {name!r} needs "
                f"{nbytes} bytes at offset {at}, blob holds {len(blob)}"
            )
        # copy: frombuffer views are read-only and would pin the whole
        # request body alive behind every small array
        arrays[name] = np.frombuffer(
            blob, dtype=dtype, count=int(np.prod(shape, dtype=np.int64)),
            offset=at,
        ).copy().reshape(shape)
        at += nbytes
    return arrays


def encode_request(request) -> bytes:
    """One request as a wire frame. Index requests ship only their
    int32 row tensors (<1KB against a fleet-resident store); pixel
    requests ship their support/query arrays in the ingest dtype the
    engine expects (uint8 stays uint8 — the wire inherits the ~4x
    ingest compression)."""
    header: Dict[str, Any] = {
        "tenant_id": getattr(request, "tenant_id", None),
        "deadline_ms": getattr(request, "deadline_ms", None),
    }
    priority = getattr(request, "priority", None)
    if priority is not None:
        header["priority"] = int(priority)
    if getattr(request, "support_idx", None) is not None:
        header["kind"] = "index"
        header["labeled"] = bool(request.labeled)
        named = [
            ("support_idx", np.asarray(request.support_idx, np.int32)),
            ("query_idx", np.asarray(request.query_idx, np.int32)),
        ]
    else:
        header["kind"] = "adapt"
        named = [
            ("support_x", np.asarray(request.support_x)),
            ("support_y", np.asarray(request.support_y)),
            ("query_x", np.asarray(request.query_x)),
        ]
        if request.query_y is not None:
            named.append(("query_y", np.asarray(request.query_y)))
    header["arrays"], buffers = _array_manifest(named)
    return _encode_frame(header, buffers)


def decode_request(payload: bytes) -> Tuple[Any, Dict[str, Any]]:
    """Decode a wire frame back into an ``AdaptRequest`` /
    ``IndexRequest`` plus its raw header (the gateway-path fields —
    ``gateway_elapsed_ms``, clamped ``priority`` — ride the header; the
    HOST decides how they land on the request, see serving/fleet.py)."""
    header, blob = _decode_frame(payload)
    arrays = _decode_arrays(header, blob)
    kind = header.get("kind")
    try:
        if kind == "index":
            request = IndexRequest(
                support_idx=arrays["support_idx"],
                query_idx=arrays["query_idx"],
                labeled=bool(header.get("labeled", True)),
                tenant_id=header.get("tenant_id"),
                deadline_ms=header.get("deadline_ms"),
            )
        elif kind == "adapt":
            request = AdaptRequest(
                support_x=arrays["support_x"],
                support_y=arrays["support_y"],
                query_x=arrays["query_x"],
                query_y=arrays.get("query_y"),
                tenant_id=header.get("tenant_id"),
                deadline_ms=header.get("deadline_ms"),
            )
        else:
            raise WireError(
                f"wire header kind must be 'adapt' or 'index', got "
                f"{kind!r}"
            )
    except KeyError as e:
        raise WireError(
            f"wire frame of kind {kind!r} is missing array {e}"
        ) from e
    return request, header


def encode_result(result, **extra: Any) -> bytes:
    """One ``TenantResult`` as a response frame (predictions as a raw
    buffer, scalars + host timings in the header)."""
    preds = np.ascontiguousarray(np.asarray(result.preds))
    header: Dict[str, Any] = {
        "ok": True,
        "tenant_id": result.tenant_id,
        "loss": None if result.loss is None else float(result.loss),
        "accuracy": (
            None if result.accuracy is None else float(result.accuracy)
        ),
        **extra,
    }
    header["arrays"], buffers = _array_manifest([("preds", preds)])
    return _encode_frame(header, buffers)


def decode_result(payload: bytes) -> Dict[str, Any]:
    """Decode a response frame into its header dict with ``preds``
    attached as an ndarray."""
    header, blob = _decode_frame(payload)
    out = dict(header)
    out.update(_decode_arrays(header, blob))
    return out


# -- the consistent-hash host ring -------------------------------------------


def home_host(fingerprint: str, hosts: Sequence[str]) -> str:
    """The fleet-level home assignment: the SAME modular arithmetic as
    ``router.home_replica``, over the sorted host-id ring — so the
    (fingerprint -> home) map is a pure function of the content digest
    and the membership set, stable across processes and restarts (the
    cross-process twin of the router's fingerprint stability test)."""
    ring = sorted(str(h) for h in hosts)
    return ring[home_replica(fingerprint, len(ring))]


# -- gateway -----------------------------------------------------------------


class ClockOffsetEstimator:
    """Cristian's algorithm over the health sweep's request/response
    timestamps.

    The gateway and its hosts deliberately never compare clocks — every
    process records spans against its OWN ``time.perf_counter`` origin.
    To merge their span logs onto one timeline, each /healthz poll
    contributes one sample: the gateway stamps ``t0``/``t1`` around the
    GET, the host replies with its own perf_counter milliseconds
    (``remote``), and under symmetric transit the host read the clock at
    the gateway-time midpoint, so ``offset = remote - (t0 + t1) / 2``.
    Transit is NOT symmetric, but the error is bounded: with one-way
    delays d1 + d2 = RTT, the estimate is off by ``(d1 - d2) / 2``, i.e.
    ``|error| <= RTT / 2`` — so the MINIMUM-RTT sample across sweeps is
    kept (the bound only ever tightens) and the bound is recorded as
    ``clock_skew_bound_ms``. perf_counter clocks do not step, so a
    latched min-RTT sample never goes stale over a serving run."""

    __slots__ = ("offset_ms", "bound_ms", "rtt_ms", "samples")

    def __init__(self):
        self.offset_ms: Optional[float] = None
        self.bound_ms: Optional[float] = None
        self.rtt_ms: Optional[float] = None
        self.samples = 0

    def observe(self, t0_ms: float, t1_ms: float,
                remote_ms: float) -> bool:
        """Feed one poll's sample; True when it became the new best
        (lower RTT → tighter bound) — the caller's cue to re-record."""
        rtt = float(t1_ms) - float(t0_ms)
        if rtt < 0:
            return False  # a clock anomaly, never a usable sample
        self.samples += 1
        if self.rtt_ms is None or rtt < self.rtt_ms:
            self.rtt_ms = rtt
            self.offset_ms = float(remote_ms) - (
                float(t0_ms) + float(t1_ms)
            ) / 2.0
            self.bound_ms = rtt / 2.0
            return True
        return False


#: pooled keep-alive connections kept per host (overflow closes eagerly)
_POOL_CAP = 4


@dataclass
class _HostHandle:
    """One fleet member as the gateway sees it."""

    host_id: str
    address: str  # "host:port"
    #: answered /healthz 200 at the last contact — routable now
    ready: bool = False
    #: was EVER ready — the trip gate (a host that never came up is
    #: skipped, not tripped: the PR-15 not-yet-warmed semantics)
    was_ready: bool = False
    #: latched once the host is declared dead; never un-trips
    tripped: bool = False
    trip_cause: Optional[BaseException] = None
    #: last-polled host queue depth (the admission signal's slow term)
    depth: int = 0
    #: gateway-side in-flight count (the admission signal's live term)
    in_flight: int = 0
    #: EWMA of observed host service time (ms) — the deadline-shed
    #: queue-estimate multiplier; None until the first response
    ewma_ms: Optional[float] = None
    #: the health sweep's Cristian clock estimate for this host
    clock: ClockOffsetEstimator = field(
        default_factory=ClockOffsetEstimator
    )
    #: idle keep-alive connections (satellite of the forward path; the
    #: health poller keeps using fresh sockets — its RTT IS the clock
    #: estimator's input and must not ride a warm connection's luck)
    pool: List[http.client.HTTPConnection] = field(default_factory=list)
    pool_lock: threading.Lock = field(default_factory=threading.Lock)

    def conn(self, timeout: float) -> http.client.HTTPConnection:
        host, _, port = self.address.rpartition(":")
        return http.client.HTTPConnection(
            host, int(port), timeout=timeout
        )

    def acquire(self, timeout: float) -> Tuple[
            http.client.HTTPConnection, bool]:
        """A connection to this host: a pooled keep-alive when one is
        idle (True — reused), else a fresh socket (False)."""
        with self.pool_lock:
            while self.pool:
                c = self.pool.pop()
                if c.sock is not None:
                    return c, True
                c.close()
        return self.conn(timeout), False

    def release(self, conn: http.client.HTTPConnection) -> None:
        """Return a healthy keep-alive to the pool (overflow closes)."""
        with self.pool_lock:
            if len(self.pool) < _POOL_CAP:
                self.pool.append(conn)
                return
        conn.close()

    def drain_pool(self) -> None:
        with self.pool_lock:
            conns, self.pool = self.pool, []
        for c in conns:
            c.close()


@dataclass
class _Shed:
    """A typed edge rejection (never an exception: sheds are the
    gateway WORKING, not failing)."""

    reason: str  # 'admission' | 'deadline'
    host: str
    detail: Dict[str, Any] = field(default_factory=dict)


class Gateway:
    """The fleet front door: affinity routing + admission control +
    deadline shedding + health-checked membership over N host
    processes.

    :param cfg: a ``MAMLConfig`` for the gateway knobs
        (``serving_gateway_queue_budget`` / ``_priority_tiers`` /
        ``_health_interval_s``).
    :param hosts: the fleet membership — ``{host_id: "addr:port"}`` (or
        a sequence of ``"addr:port"`` strings, ids assigned
        ``host0..hostN-1`` in the given order). Membership is fixed for
        the gateway's lifetime; ring positions come from the SORTED
        host ids.
    :param sink: optional telemetry sink for the schema-v13 ``gateway``
        records (shed / rehome / rollup; since v14 also clock).
    :param start_health_loop: start the background /healthz poller
        (pass False in tests that drive ``poll_once()`` by hand).
    :param tracer: optional ``telemetry.tracing.Tracer`` (pass one built
        with ``process='gateway'`` / ``span_prefix='gw-'``); None keeps
        every request on the NULL_TRACER one-attribute-check path and
        the forward frames byte-identical to the v13 wire.
    """

    def __init__(self, cfg, hosts, sink=None,
                 start_health_loop: bool = True,
                 connect_timeout_s: float = 2.0,
                 request_timeout_s: float = 600.0,
                 tracer=None):
        if isinstance(hosts, dict):
            members = {str(k): str(v) for k, v in hosts.items()}
        else:
            members = {
                f"host{i}": str(addr) for i, addr in enumerate(hosts)
            }
        if not members:
            raise ValueError("Gateway needs at least one host")
        self.cfg = cfg
        self.sink = sink
        self.queue_budget = int(cfg.serving_gateway_queue_budget)
        self.priority_tiers = int(cfg.serving_gateway_priority_tiers)
        self.health_interval_s = float(
            cfg.serving_gateway_health_interval_s
        )
        self.connect_timeout_s = float(connect_timeout_s)
        self.request_timeout_s = float(request_timeout_s)
        #: ring order is the sorted host-id list — fixed at
        #: construction, so home assignments and re-homing are
        #: deterministic for the fleet's whole life
        self.ring: List[_HostHandle] = [
            _HostHandle(host_id=hid, address=members[hid])
            for hid in sorted(members)
        ]
        self._lock = threading.Lock()
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self.admitted = 0
        self.admitted_by_priority: Dict[int, int] = {}
        self.shed: Dict[str, int] = {"admission": 0, "deadline": 0}
        self.rehomes = 0
        self.forward_failures = 0
        self.pool_reused = 0
        self.pool_fresh = 0
        self.pool_retries = 0
        self._req_ids = itertools.count(1)
        # admitted-request latency at the edge (arrival → response) —
        # the /metrics histogram family; LogHistogram so the exposition
        # and any offline consumer share one exact ladder
        from .metrics import LogHistogram

        self.admitted_ms_hist = LogHistogram()
        self._stop = threading.Event()
        self._health_thread: Optional[threading.Thread] = None
        if start_health_loop:
            self._health_thread = threading.Thread(
                target=self._health_loop, name="gateway-health",
                daemon=True,
            )
            self._health_thread.start()

    # -- membership / health ---------------------------------------------

    def _record(self, **fields: Any) -> None:
        if self.sink is None:
            return
        from ..telemetry.sinks import make_record

        self.sink.write(make_record("gateway", **fields))

    def _get_json(self, h: _HostHandle, path: str,
                  timeout: float) -> Tuple[int, Any]:
        conn = h.conn(timeout)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            body = resp.read()
        finally:
            conn.close()
        try:
            return resp.status, json.loads(body)
        except (UnicodeDecodeError, json.JSONDecodeError):
            return resp.status, None

    def poll_once(self) -> None:
        """One health sweep: refresh readiness + queue depth for every
        untripped host; a host that stops answering AFTER it was ready
        is tripped (latched). Never-ready hosts are left unready, not
        tripped — they may still be warming up.

        Each poll doubles as one Cristian clock sample: ``t0``/``t1``
        stamped around the GET plus the host's own ``perf_ms`` reply
        feed ``ClockOffsetEstimator``; whenever a lower-RTT sample
        tightens the bound, a ``gateway`` ``event='clock'`` record pins
        the new estimate in the log (the LAST clock record per host is
        always the best one — what ``cli trace --fleet`` aligns with)."""
        for h in self.ring:
            if h.tripped:
                continue
            t0 = time.perf_counter()
            try:
                status, payload = self._get_json(
                    h, "/healthz", self.connect_timeout_s
                )
            except (OSError, http.client.HTTPException) as e:
                if h.was_ready:
                    self._trip(h, e)
                continue
            t1 = time.perf_counter()
            with self._lock:
                h.ready = status == 200
                if h.ready:
                    h.was_ready = True
                if isinstance(payload, dict):
                    h.depth = int(payload.get("queue_depth", h.depth))
            remote_ms = (
                payload.get("perf_ms") if isinstance(payload, dict)
                else None
            )
            if (
                status == 200
                and isinstance(remote_ms, (int, float))
                and not isinstance(remote_ms, bool)
                and h.clock.observe(t0 * 1e3, t1 * 1e3, float(remote_ms))
            ):
                self._record(
                    event="clock", host=h.host_id,
                    clock_offset_ms=round(h.clock.offset_ms, 3),
                    clock_skew_bound_ms=round(h.clock.bound_ms, 3),
                    rtt_ms=round(h.clock.rtt_ms, 3),
                    samples=h.clock.samples,
                )

    def _health_loop(self) -> None:
        while not self._stop.is_set():
            self.poll_once()
            self._stop.wait(self.health_interval_s)

    def wait_ready(self, timeout_s: float = 60.0,
                   min_hosts: Optional[int] = None) -> None:
        """Block until ``min_hosts`` (default: all) hosts answer
        /healthz 200 — the fleet-level warmup barrier."""
        need = len(self.ring) if min_hosts is None else int(min_hosts)
        deadline = time.perf_counter() + float(timeout_s)
        while True:
            self.poll_once()
            ready = sum(1 for h in self.ring if h.ready)
            if ready >= need:
                return
            if time.perf_counter() >= deadline:
                raise TimeoutError(
                    f"only {ready}/{need} fleet hosts ready within "
                    f"{timeout_s}s: "
                    + ", ".join(
                        f"{h.host_id}={'ready' if h.ready else 'down'}"
                        for h in self.ring
                    )
                )
            time.sleep(min(0.05, self.health_interval_s))

    def _trip(self, h: _HostHandle, cause: BaseException) -> bool:
        """Latch ``h`` out of the ring; True only on the tripping
        transition (every later failure observes it already dead —
        exactly one ``rehome`` record per lost host)."""
        with self._lock:
            if h.tripped:
                return False
            h.tripped = True
            h.ready = False
            h.trip_cause = cause
            stranded = h.in_flight
            self.rehomes += 1
        h.drain_pool()
        self._record(
            event="rehome", host=h.host_id, cause=repr(cause),
            in_flight=stranded,
        )
        return True

    # -- routing + admission ---------------------------------------------

    def _pick(self, home_idx: int) -> Optional[_HostHandle]:
        """Ring walk from the home POSITION (computed over the full
        fixed membership, so healthy hosts' homes never reshuffle when
        another host trips) to the first ready host; None when the
        whole ring is down."""
        n = len(self.ring)
        with self._lock:
            for step in range(n):
                h = self.ring[(home_idx + step) % n]
                if h.ready and not h.tripped:
                    return h
        return None

    def _admission(self, h: _HostHandle, priority: int,
                   deadline_ms: Optional[float]) -> Optional[_Shed]:
        """The edge decision for one request against its home host:
        None admits; a ``_Shed`` names the typed rejection."""
        with self._lock:
            load = h.depth + h.in_flight
            ewma = h.ewma_ms
        budget = max(1, self.queue_budget >> priority)
        if load >= budget:
            return _Shed(
                reason="admission", host=h.host_id,
                detail={"load": load, "budget": budget},
            )
        if deadline_ms is not None and ewma is not None:
            est_ms = load * ewma
            if float(deadline_ms) <= est_ms:
                return _Shed(
                    reason="deadline", host=h.host_id,
                    detail={
                        "queue_est_ms": round(est_ms, 3),
                        "load": load,
                        "ewma_ms": round(ewma, 3),
                    },
                )
        return None

    def _forward(self, host: _HostHandle,
                 body: bytes) -> Tuple[int, bytes, bool]:
        """POST one wire frame to ``host`` over a pooled keep-alive.

        Retry-once semantics: a REUSED connection that fails
        mid-request is usually a keep-alive the host's HTTP server
        closed between requests, not a host death — it earns exactly
        one retry on a guaranteed-fresh socket (never another pool
        entry: a pool full of stale sockets must not spend the whole
        retry budget). A FRESH socket's failure propagates immediately,
        so connection-refused still trips the host on the first try
        (the between-sweeps death semantics are unchanged). Returns
        ``(status, payload, reused)``."""
        use_pool = True
        while True:
            if use_pool:
                conn, reused = host.acquire(self.request_timeout_s)
            else:
                conn, reused = host.conn(self.request_timeout_s), False
            with self._lock:
                if reused:
                    self.pool_reused += 1
                else:
                    self.pool_fresh += 1
            try:
                conn.request(
                    "POST", "/v1/serve", body=body,
                    headers={"Content-Type": WIRE_CONTENT_TYPE},
                )
                resp = conn.getresponse()
                payload = resp.read()
            except (OSError, http.client.HTTPException):
                conn.close()
                if reused:
                    use_pool = False
                    with self._lock:
                        self.pool_retries += 1
                    continue
                raise
            if resp.will_close:
                conn.close()
            else:
                host.release(conn)
            return resp.status, payload, reused

    def handle_serve(self, body: bytes) -> Tuple[int, str, bytes]:
        """Serve one wire-framed request end to end; returns
        ``(http_status, content_type, response_body)``. 200 carries the
        host's response frame verbatim; everything else is typed JSON
        (shed / host_down / bad_request) — a client can always tell WHY
        it was refused.

        With a tracer wired, this is where the fleet trace is minted:
        root ``request`` span (backdated to edge arrival),
        ``gateway_queue`` until the first forward, one ``forward`` +
        ``wire`` child pair per attempt (re-home retries are siblings
        under the same root), zero-duration ``shed`` spans for typed
        rejections — and the forward header carries the trace baggage
        the host's batcher adopts. Tracer off: ``root`` stays None and
        the forwarded header is key-identical to the v13 wire."""
        t_edge = time.perf_counter()
        tracer = self.tracer
        try:
            request, header = decode_request(body)
            fingerprint = request_fingerprint(request)
        except (WireError, ValueError, TypeError) as e:
            return 400, "application/json", json.dumps(
                {"error": "bad_request", "detail": str(e)}
            ).encode()
        priority = int(header.get("priority") or 0)
        priority = min(max(priority, 0), self.priority_tiers - 1)
        deadline_ms = header.get("deadline_ms")
        home_idx = home_replica(fingerprint, len(self.ring))
        hlen = struct.unpack_from(">I", body)[0]
        blob = body[4 + hlen:]
        root = gq = None
        request_id = None
        if tracer.enabled:
            request_id = f"{tracer.trace_id}-g{next(self._req_ids):06d}"
            # each edge request is its OWN causal tree: mint a fresh
            # trace id here rather than inheriting the tracer's
            # run-scoped one — `cli trace --fleet` groups by trace_id,
            # so sharing one would fuse every request into a single
            # unreadable "trace"
            root = tracer.start_span(
                "request", cat="gateway", start_ms=t_edge * 1e3,
                trace_id=new_trace_id(), request_id=request_id,
                tenant_id=header.get("tenant_id"), priority=priority,
            )
            gq = tracer.start_span(
                "gateway_queue", cat="gateway", parent=root,
                start_ms=t_edge * 1e3,
            )
        causes: List[BaseException] = []
        attempt = 0
        while True:
            host = self._pick(home_idx)
            if host is None:
                err = HostDownError(
                    "no ready fleet host left for this request (root "
                    "cause chained below)"
                )
                if causes:
                    err.__cause__ = causes[-1]
                tracer.end_span(gq)
                tracer.end_span(root, outcome="host_down")
                return 503, "application/json", json.dumps({
                    "error": "host_down",
                    "detail": str(err),
                    "cause": repr(causes[-1]) if causes else None,
                    "causes": [repr(c) for c in causes],
                }).encode()
            shed = self._admission(host, priority, deadline_ms)
            if shed is not None:
                with self._lock:
                    self.shed[shed.reason] += 1
                trace_fields: Dict[str, Any] = {}
                if root is not None:
                    trace_fields = {
                        "trace_id": root.trace_id,
                        "request_id": request_id,
                    }
                self._record(
                    event="shed", reason=shed.reason,
                    tenant_id=header.get("tenant_id"),
                    priority=priority, deadline_ms=deadline_ms,
                    host=shed.host, **shed.detail, **trace_fields,
                )
                if root is not None:
                    tracer.end_span(gq)
                    gq = None
                    # a zero-duration annotated marker: the rejection
                    # is an instant, not an interval
                    sp = tracer.start_span(
                        "shed", cat="gateway", parent=root,
                        reason=shed.reason, host=shed.host,
                    )
                    tracer.end_span(sp, end_ms=sp.start_ms)
                    tracer.end_span(
                        root, outcome="shed", reason=shed.reason
                    )
                return 429, "application/json", json.dumps({
                    "error": "shed", "reason": shed.reason,
                    "host": shed.host, **shed.detail,
                }).encode()
            if gq is not None:
                tracer.end_span(gq)
                gq = None
            fspan = None
            if root is not None:
                fspan = tracer.start_span(
                    "forward", cat="gateway", parent=root,
                    host=host.host_id, attempt=attempt,
                )
            # re-stamp the edge share per attempt (retries after a trip
            # have spent more of the budget) and forward the ORIGINAL
            # buffer bytes — the arrays are never re-encoded
            fwd_header = dict(header)
            fwd_header["priority"] = priority
            fwd_header["gateway_elapsed_ms"] = round(
                (time.perf_counter() - t_edge) * 1e3, 3
            )
            if fspan is not None:
                # the trace baggage the host-side batcher adopts; only
                # present while tracing — with it absent the header is
                # key-identical to the v13 wire, bytes and all
                fwd_header["trace_id"] = fspan.trace_id
                fwd_header["parent_span_id"] = fspan.span_id
                fwd_header["request_id"] = request_id
                if host.clock.offset_ms is not None:
                    fwd_header["clock_offset_ms"] = round(
                        host.clock.offset_ms, 3
                    )
            fwd = _encode_frame(fwd_header, [blob])
            with self._lock:
                host.in_flight += 1
            wire = None
            if fspan is not None:
                wire = tracer.start_span(
                    "wire", cat="gateway", parent=fspan,
                    host=host.host_id,
                )
            t_fwd = time.perf_counter()
            try:
                status, payload, reused = self._forward(host, fwd)
            except (OSError, http.client.HTTPException) as e:
                # the between-sweeps death path: fail fast, trip, and
                # re-home THIS request on the ring walk (idempotent by
                # construction) instead of stranding it on a socket
                with self._lock:
                    host.in_flight -= 1
                    self.forward_failures += 1
                causes.append(e)
                tracer.end_span(wire, outcome="error", error=repr(e))
                tracer.end_span(fspan, outcome="rehome")
                self._trip(host, e)
                attempt += 1
                continue
            rtt_ms = (time.perf_counter() - t_fwd) * 1e3
            tracer.end_span(wire, status=status, reused=reused)
            tracer.end_span(
                fspan,
                outcome="ok" if status == 200 else f"http_{status}",
            )
            with self._lock:
                host.in_flight -= 1
                if status == 200:
                    self.admitted += 1
                    self.admitted_by_priority[priority] = (
                        self.admitted_by_priority.get(priority, 0) + 1
                    )
                    self.admitted_ms_hist.observe(
                        (time.perf_counter() - t_edge) * 1e3
                    )
                    host.ewma_ms = (
                        rtt_ms if host.ewma_ms is None
                        else 0.7 * host.ewma_ms + 0.3 * rtt_ms
                    )
            tracer.end_span(
                root,
                outcome="served" if status == 200 else "error",
                status=status,
            )
            ctype = WIRE_CONTENT_TYPE if status == 200 else (
                "application/json"
            )
            return status, ctype, payload

    # -- fleet surfaces ---------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            pool_total = self.pool_reused + self.pool_fresh
            return {
                "hosts": [
                    {
                        "host_id": h.host_id,
                        "address": h.address,
                        "ready": h.ready,
                        "tripped": h.tripped,
                        "trip_cause": (
                            repr(h.trip_cause) if h.trip_cause else None
                        ),
                        "depth": h.depth,
                        "in_flight": h.in_flight,
                        "ewma_ms": (
                            round(h.ewma_ms, 3) if h.ewma_ms is not None
                            else None
                        ),
                        "clock_offset_ms": (
                            round(h.clock.offset_ms, 3)
                            if h.clock.offset_ms is not None else None
                        ),
                        "clock_skew_bound_ms": (
                            round(h.clock.bound_ms, 3)
                            if h.clock.bound_ms is not None else None
                        ),
                    }
                    for h in self.ring
                ],
                "admitted": self.admitted,
                "admitted_by_priority": {
                    str(p): n
                    for p, n in sorted(self.admitted_by_priority.items())
                },
                "shed": dict(self.shed),
                "rehomes": self.rehomes,
                "forward_failures": self.forward_failures,
                "conn_pool": {
                    "reused": self.pool_reused,
                    "fresh": self.pool_fresh,
                    "retries": self.pool_retries,
                    "reuse_rate": (
                        round(self.pool_reused / pool_total, 4)
                        if pool_total else None
                    ),
                },
            }

    def render_metrics(self) -> str:
        """The gateway's Prometheus text-format (0.0.4) payload — the
        edge twin of ``ServingMetrics.render``, built from the same
        serving/metrics.py exposition helpers so the formats can never
        drift: typed shed counters, the rehome/forward-failure
        counters, per-priority admitted counters, connection-pool
        reuse, a ready-host gauge, and the admitted-latency
        ``LogHistogram`` as a real histogram family (exact cumulative
        buckets, the ladder shared with every rollup consumer)."""
        from .metrics import _render_labeled

        with self._lock:
            shed = dict(self.shed)
            admitted = {
                f'priority="{p}"': n
                for p, n in self.admitted_by_priority.items()
            }
            lines = _render_labeled(
                "gateway_shed_total",
                "Requests rejected typed at the fleet edge, by reason",
                "counter",
                {f'reason="{r}"': n for r, n in shed.items()},
                scalar=False,
            )
            lines += _render_labeled(
                "gateway_admitted_total",
                "Requests admitted and served 200, by priority tier",
                "counter", admitted, scalar=False,
            )
            lines += _render_labeled(
                "gateway_rehomes_total",
                "Hosts tripped out of the serving ring",
                "counter", {"": self.rehomes},
            )
            lines += _render_labeled(
                "gateway_forward_failures_total",
                "Forward attempts that failed at the socket layer",
                "counter", {"": self.forward_failures},
            )
            lines += _render_labeled(
                "gateway_conn_pool_reused_total",
                "Forwards served over a pooled keep-alive connection",
                "counter", {"": self.pool_reused},
            )
            lines += _render_labeled(
                "gateway_conn_pool_fresh_total",
                "Forwards that opened a fresh connection",
                "counter", {"": self.pool_fresh},
            )
            lines += _render_labeled(
                "gateway_conn_pool_retries_total",
                "Stale keep-alives retried once on a fresh socket",
                "counter", {"": self.pool_retries},
            )
            lines += _render_labeled(
                "gateway_ready_hosts",
                "Fleet hosts currently ready (untripped, healthz 200)",
                "gauge",
                {"": sum(
                    1 for h in self.ring if h.ready and not h.tripped
                )},
            )
            lines += self.admitted_ms_hist.render(
                "gateway_admitted_latency_ms",
                "End-to-end latency of admitted requests at the edge "
                "(arrival to response, milliseconds)",
            )
        return "\n".join(lines) + "\n"

    def rollup(self) -> Dict[str, Any]:
        """The fleet aggregate: per-host rollups fetched live, their
        log histograms merged EXACTLY bucket-by-bucket (the same
        ladder, enforced by ``LogHistogram.merge``), plus the
        gateway-side admission counters. Emits one ``gateway``
        ``event='rollup'`` record when a sink is wired."""
        from .metrics import LogHistogram

        merged = {
            "adapt_ms_hist": LogHistogram(),
            "queue_ms_hist": LogHistogram(),
        }
        per_host: List[Dict[str, Any]] = []
        tenants = dispatches = 0
        for h in self.ring:
            if not h.ready or h.tripped:
                continue
            try:
                status, payload = self._get_json(
                    h, "/rollup", self.request_timeout_s
                )
            except (OSError, http.client.HTTPException) as e:
                self._trip(h, e)
                continue
            if status != 200 or not isinstance(payload, dict):
                continue
            per_host.append({"host_id": h.host_id, **payload})
            tenants += int(payload.get("tenants", 0))
            dispatches += int(payload.get("dispatches", 0))
            for key, hist in merged.items():
                if payload.get(key):
                    hist.merge(LogHistogram.from_dict(payload[key]))
        with self._lock:
            out: Dict[str, Any] = {
                "hosts": len(self.ring),
                "ready_hosts": sum(
                    1 for h in self.ring if h.ready and not h.tripped
                ),
                "tripped_hosts": [
                    h.host_id for h in self.ring if h.tripped
                ],
                "admitted": self.admitted,
                "shed": dict(self.shed),
                "rehomes": self.rehomes,
            }
        out.update(
            tenants=tenants,
            dispatches=dispatches,
            adapt_ms_p99=merged["adapt_ms_hist"].quantile(0.99),
            adapt_ms_hist=merged["adapt_ms_hist"].to_dict(),
            queue_ms_hist=merged["queue_ms_hist"].to_dict(),
            per_host=per_host,
        )
        rec = {k: v for k, v in out.items() if k != "per_host"}
        self._record(event="rollup", **rec)
        return out

    def close(self) -> None:
        self._stop.set()
        if self._health_thread is not None:
            self._health_thread.join(timeout=5)
            self._health_thread = None


# -- the gateway's own HTTP face ---------------------------------------------


class GatewayServer:
    """The one fleet endpoint: POST ``/v1/serve`` (wire frames in/out),
    GET ``/healthz`` (200 once >= 1 host is ready — the fleet is
    serving), GET ``/stats`` (membership + admission counters), GET
    ``/rollup`` (the exact-merge fleet aggregate), GET ``/metrics``
    (Prometheus text format: the edge counters + the admitted-latency
    histogram family). ``port=0`` binds an
    ephemeral port (the CI shape); stdlib ``ThreadingHTTPServer``, one
    thread per connection, same as serving/metrics.py."""

    def __init__(self, gateway: Gateway, port: int = 0,
                 host: str = "127.0.0.1"):
        from http.server import (
            BaseHTTPRequestHandler,
            ThreadingHTTPServer,
        )

        gw = gateway

        class Handler(BaseHTTPRequestHandler):
            def _send(self, status: int, ctype: str,
                      body: bytes) -> None:
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):  # noqa: N802 - http.server API
                if self.path != "/v1/serve":
                    self._send(404, "text/plain", b"not found\n")
                    return
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
                status, ctype, payload = gw.handle_serve(body)
                self._send(status, ctype, payload)

            def do_GET(self):  # noqa: N802 - http.server API
                if self.path == "/healthz":
                    ready = any(
                        h.ready and not h.tripped for h in gw.ring
                    )
                    body = json.dumps({
                        "ready": ready,
                        "hosts": {
                            h.host_id: h.ready and not h.tripped
                            for h in gw.ring
                        },
                    }).encode()
                    self._send(
                        200 if ready else 503, "application/json", body
                    )
                elif self.path == "/stats":
                    self._send(
                        200, "application/json",
                        json.dumps(gw.stats()).encode(),
                    )
                elif self.path == "/rollup":
                    self._send(
                        200, "application/json",
                        json.dumps(gw.rollup()).encode(),
                    )
                elif self.path == "/metrics":
                    self._send(
                        200, "text/plain; version=0.0.4",
                        gw.render_metrics().encode(),
                    )
                else:
                    self._send(404, "text/plain", b"not found\n")

            def log_message(self, fmt, *args):  # noqa: A003 - silence
                pass

        self.gateway = gateway
        self._server = ThreadingHTTPServer((host, int(port)), Handler)
        self.port = self._server.server_address[1]
        self.url = f"http://{host}:{self.port}"
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="gateway-http",
            daemon=True,
        )
        self._thread.start()

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5)


@dataclass
class GatewayReply:
    """One request's fate at the fleet edge, decoded."""

    status: int
    #: the decoded response frame (preds + scalars + host timings) on
    #: 200; None otherwise
    result: Optional[Dict[str, Any]] = None
    #: the typed JSON body on any non-200 (shed / host_down / ...)
    error: Optional[Dict[str, Any]] = None

    @property
    def ok(self) -> bool:
        return self.status == 200

    @property
    def shed_reason(self) -> Optional[str]:
        if self.error is not None and self.error.get("error") == "shed":
            return self.error.get("reason")
        return None


class GatewayClient:
    """A minimal wire client: encode, POST, decode — what serve-bench's
    ``--fleet`` driver and the tests speak."""

    def __init__(self, address: str, timeout_s: float = 600.0):
        self.address = str(address)
        self.timeout_s = float(timeout_s)

    def serve(self, request) -> GatewayReply:
        return self.serve_frame(encode_request(request))

    def serve_frame(self, body: bytes) -> GatewayReply:
        """POST an already-encoded wire frame (the open-loop driver
        encodes at SUBMISSION time, so a shared repeat-tenant request
        object's per-submission fields are captured correctly)."""
        host, _, port = self.address.rpartition(":")
        conn = http.client.HTTPConnection(
            host, int(port), timeout=self.timeout_s
        )
        try:
            conn.request(
                "POST", "/v1/serve", body=body,
                headers={"Content-Type": WIRE_CONTENT_TYPE},
            )
            resp = conn.getresponse()
            status, payload = resp.status, resp.read()
        finally:
            conn.close()
        if status == 200:
            return GatewayReply(
                status=status, result=decode_result(payload)
            )
        try:
            error = json.loads(payload)
        except (UnicodeDecodeError, json.JSONDecodeError):
            error = {"error": "opaque", "body": payload[:200].decode(
                "utf-8", "replace"
            )}
        return GatewayReply(status=status, error=error)
