"""The networked fleet front tier: one endpoint over N host processes.

PR 15's ``ReplicaRouter`` scales serving across replicas in one process;
this module lifts the same affinity/trip/re-home semantics one level, to
a fleet of HOST processes (each running a full ``ReplicaSet`` behind
:mod:`serving.fleet`), and adds the SLO *enforcement* the pool only
measures:

* **Wire schema** — one framed binary format for requests and
  responses: a 4-byte big-endian header length, a JSON header (kind /
  tenant / deadline / priority / array manifest), then the raw C-order
  array buffers concatenated in manifest order. The arrays ship in the
  PR-13 ingest encodings, so the compression the device ingest tiers
  bought applies ON THE WIRE too: uint8 support sets are ~4x smaller
  than f32, and an index request against a fleet-resident store is a
  few hundred bytes of int32 rows.
* **Fleet-wide cache affinity** — ``home_host`` hashes the SAME
  ``batcher.update_support_digest`` content fingerprint the in-process
  router and the engine's adapted-params cache key use
  (``router.request_fingerprint``), over the sorted host-id ring. A
  tenant's adapted-params LRU entry therefore lives on exactly one
  host, fleet-wide, and routing identity can never drift from cache
  identity — one recipe, three consumers.
* **Admission control** — a request is REJECTED AT THE EDGE with a
  typed response (HTTP 429, ``reason='admission'``) when its home
  host's load estimate (last-polled queue depth + the gateway's own
  in-flight count) reaches the per-host budget
  (``serving_gateway_queue_budget``), right-shifted by the request's
  priority tier (tier 0 keeps the full budget, tier 1 half, ...).
* **Deadline-aware shedding** — a deadline-carrying request whose
  budget cannot cover the home host's current queue estimate
  (load x an EWMA of observed host service time — conservative by
  construction: the EWMA includes host queue wait, so overload sheds
  harder and self-corrects as the queue drains) is rejected typed
  (``reason='deadline'``) instead of joining a queue it can only
  collapse. Both shed shapes emit ``gateway`` telemetry records
  (schema v13).
* **Health-checked membership + deterministic re-homing** — a
  background thread polls each host's ``/healthz``; a host that stops
  answering AFTER it was ready is tripped (latched, PR-15 semantics:
  never-ready hosts are skipped, not tripped). The ring POSITIONS are
  fixed at construction, so losing host k deterministically re-homes
  exactly k's tenants to the next ready host on the ring — every other
  home assignment is untouched. A host that dies BETWEEN sweeps is
  caught at forward time: the in-flight socket request fails
  immediately with the chained root cause (the PR-13/15
  batcher-crash semantics at the network layer), the host is tripped,
  and the request is retried on its re-homed host — adapt-on-request
  is a pure function of (support, query, snapshot), so the retry is
  idempotent; only a fleet with NO ready host left returns the typed
  ``host_down`` failure (HTTP 503, root causes chained in the body).
* **Fleet rollup** — ``rollup()`` fetches every ready host's
  ``/rollup`` and merges the per-host ``LogHistogram`` buckets EXACTLY
  (serving/metrics.py — the PR-17 mergeable-histogram machinery), so
  fleet p99 and burn rates come from one histogram family, never from
  averaged percentiles.

Everything here is stdlib + numpy — importable (and testable) without
jax, like the router it extends.
"""

from __future__ import annotations

import http.client
import json
import struct
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .batcher import AdaptRequest, IndexRequest
from .router import home_replica, request_fingerprint

#: request/response content type for the framed binary wire format
WIRE_CONTENT_TYPE = "application/x-maml-wire"


class WireError(ValueError):
    """A frame that cannot be decoded (truncated, bad manifest, short
    buffers) — the gateway answers HTTP 400, never a stack trace."""


class HostDownError(RuntimeError):
    """No ready host left to serve a request; ``__cause__`` chains the
    last forward failure's root cause (the network-layer twin of the
    batcher's worker-crash chaining)."""


# -- wire codec --------------------------------------------------------------


def _encode_frame(header: Dict[str, Any],
                  buffers: Sequence[bytes]) -> bytes:
    hb = json.dumps(header).encode("utf-8")
    return struct.pack(">I", len(hb)) + hb + b"".join(buffers)


def _decode_frame(payload: bytes) -> Tuple[Dict[str, Any], bytes]:
    """Split a frame into (header, concatenated buffer blob)."""
    if len(payload) < 4:
        raise WireError(
            f"wire frame truncated: {len(payload)} bytes, need >= 4"
        )
    (hlen,) = struct.unpack_from(">I", payload)
    if len(payload) < 4 + hlen:
        raise WireError(
            f"wire frame truncated: header says {hlen} bytes, frame "
            f"holds {len(payload) - 4}"
        )
    try:
        header = json.loads(payload[4:4 + hlen].decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise WireError(f"wire header is not valid JSON: {e}") from e
    if not isinstance(header, dict):
        raise WireError(
            f"wire header must be an object, got "
            f"{type(header).__name__}"
        )
    return header, payload[4 + hlen:]


def _array_manifest(named: Sequence[Tuple[str, np.ndarray]]) -> Tuple[
        List[Dict[str, Any]], List[bytes]]:
    manifest, buffers = [], []
    for name, arr in named:
        arr = np.ascontiguousarray(arr)
        manifest.append({
            "name": name,
            "dtype": str(arr.dtype),
            "shape": list(arr.shape),
        })
        buffers.append(arr.tobytes())
    return manifest, buffers


def _decode_arrays(header: Dict[str, Any],
                   blob: bytes) -> Dict[str, np.ndarray]:
    arrays: Dict[str, np.ndarray] = {}
    at = 0
    for spec in header.get("arrays", []):
        try:
            dtype = np.dtype(spec["dtype"])
            shape = tuple(int(d) for d in spec["shape"])
            name = spec["name"]
        except (KeyError, TypeError, ValueError) as e:
            raise WireError(f"bad array manifest entry {spec!r}") from e
        nbytes = dtype.itemsize * int(np.prod(shape, dtype=np.int64))
        if at + nbytes > len(blob):
            raise WireError(
                f"wire buffers truncated: array {name!r} needs "
                f"{nbytes} bytes at offset {at}, blob holds {len(blob)}"
            )
        # copy: frombuffer views are read-only and would pin the whole
        # request body alive behind every small array
        arrays[name] = np.frombuffer(
            blob, dtype=dtype, count=int(np.prod(shape, dtype=np.int64)),
            offset=at,
        ).copy().reshape(shape)
        at += nbytes
    return arrays


def encode_request(request) -> bytes:
    """One request as a wire frame. Index requests ship only their
    int32 row tensors (<1KB against a fleet-resident store); pixel
    requests ship their support/query arrays in the ingest dtype the
    engine expects (uint8 stays uint8 — the wire inherits the ~4x
    ingest compression)."""
    header: Dict[str, Any] = {
        "tenant_id": getattr(request, "tenant_id", None),
        "deadline_ms": getattr(request, "deadline_ms", None),
    }
    priority = getattr(request, "priority", None)
    if priority is not None:
        header["priority"] = int(priority)
    if getattr(request, "support_idx", None) is not None:
        header["kind"] = "index"
        header["labeled"] = bool(request.labeled)
        named = [
            ("support_idx", np.asarray(request.support_idx, np.int32)),
            ("query_idx", np.asarray(request.query_idx, np.int32)),
        ]
    else:
        header["kind"] = "adapt"
        named = [
            ("support_x", np.asarray(request.support_x)),
            ("support_y", np.asarray(request.support_y)),
            ("query_x", np.asarray(request.query_x)),
        ]
        if request.query_y is not None:
            named.append(("query_y", np.asarray(request.query_y)))
    header["arrays"], buffers = _array_manifest(named)
    return _encode_frame(header, buffers)


def decode_request(payload: bytes) -> Tuple[Any, Dict[str, Any]]:
    """Decode a wire frame back into an ``AdaptRequest`` /
    ``IndexRequest`` plus its raw header (the gateway-path fields —
    ``gateway_elapsed_ms``, clamped ``priority`` — ride the header; the
    HOST decides how they land on the request, see serving/fleet.py)."""
    header, blob = _decode_frame(payload)
    arrays = _decode_arrays(header, blob)
    kind = header.get("kind")
    try:
        if kind == "index":
            request = IndexRequest(
                support_idx=arrays["support_idx"],
                query_idx=arrays["query_idx"],
                labeled=bool(header.get("labeled", True)),
                tenant_id=header.get("tenant_id"),
                deadline_ms=header.get("deadline_ms"),
            )
        elif kind == "adapt":
            request = AdaptRequest(
                support_x=arrays["support_x"],
                support_y=arrays["support_y"],
                query_x=arrays["query_x"],
                query_y=arrays.get("query_y"),
                tenant_id=header.get("tenant_id"),
                deadline_ms=header.get("deadline_ms"),
            )
        else:
            raise WireError(
                f"wire header kind must be 'adapt' or 'index', got "
                f"{kind!r}"
            )
    except KeyError as e:
        raise WireError(
            f"wire frame of kind {kind!r} is missing array {e}"
        ) from e
    return request, header


def encode_result(result, **extra: Any) -> bytes:
    """One ``TenantResult`` as a response frame (predictions as a raw
    buffer, scalars + host timings in the header)."""
    preds = np.ascontiguousarray(np.asarray(result.preds))
    header: Dict[str, Any] = {
        "ok": True,
        "tenant_id": result.tenant_id,
        "loss": None if result.loss is None else float(result.loss),
        "accuracy": (
            None if result.accuracy is None else float(result.accuracy)
        ),
        **extra,
    }
    header["arrays"], buffers = _array_manifest([("preds", preds)])
    return _encode_frame(header, buffers)


def decode_result(payload: bytes) -> Dict[str, Any]:
    """Decode a response frame into its header dict with ``preds``
    attached as an ndarray."""
    header, blob = _decode_frame(payload)
    out = dict(header)
    out.update(_decode_arrays(header, blob))
    return out


# -- the consistent-hash host ring -------------------------------------------


def home_host(fingerprint: str, hosts: Sequence[str]) -> str:
    """The fleet-level home assignment: the SAME modular arithmetic as
    ``router.home_replica``, over the sorted host-id ring — so the
    (fingerprint -> home) map is a pure function of the content digest
    and the membership set, stable across processes and restarts (the
    cross-process twin of the router's fingerprint stability test)."""
    ring = sorted(str(h) for h in hosts)
    return ring[home_replica(fingerprint, len(ring))]


# -- gateway -----------------------------------------------------------------


@dataclass
class _HostHandle:
    """One fleet member as the gateway sees it."""

    host_id: str
    address: str  # "host:port"
    #: answered /healthz 200 at the last contact — routable now
    ready: bool = False
    #: was EVER ready — the trip gate (a host that never came up is
    #: skipped, not tripped: the PR-15 not-yet-warmed semantics)
    was_ready: bool = False
    #: latched once the host is declared dead; never un-trips
    tripped: bool = False
    trip_cause: Optional[BaseException] = None
    #: last-polled host queue depth (the admission signal's slow term)
    depth: int = 0
    #: gateway-side in-flight count (the admission signal's live term)
    in_flight: int = 0
    #: EWMA of observed host service time (ms) — the deadline-shed
    #: queue-estimate multiplier; None until the first response
    ewma_ms: Optional[float] = None

    def conn(self, timeout: float) -> http.client.HTTPConnection:
        host, _, port = self.address.rpartition(":")
        return http.client.HTTPConnection(
            host, int(port), timeout=timeout
        )


@dataclass
class _Shed:
    """A typed edge rejection (never an exception: sheds are the
    gateway WORKING, not failing)."""

    reason: str  # 'admission' | 'deadline'
    host: str
    detail: Dict[str, Any] = field(default_factory=dict)


class Gateway:
    """The fleet front door: affinity routing + admission control +
    deadline shedding + health-checked membership over N host
    processes.

    :param cfg: a ``MAMLConfig`` for the gateway knobs
        (``serving_gateway_queue_budget`` / ``_priority_tiers`` /
        ``_health_interval_s``).
    :param hosts: the fleet membership — ``{host_id: "addr:port"}`` (or
        a sequence of ``"addr:port"`` strings, ids assigned
        ``host0..hostN-1`` in the given order). Membership is fixed for
        the gateway's lifetime; ring positions come from the SORTED
        host ids.
    :param sink: optional telemetry sink for the schema-v13 ``gateway``
        records (shed / rehome / rollup).
    :param start_health_loop: start the background /healthz poller
        (pass False in tests that drive ``poll_once()`` by hand).
    """

    def __init__(self, cfg, hosts, sink=None,
                 start_health_loop: bool = True,
                 connect_timeout_s: float = 2.0,
                 request_timeout_s: float = 600.0):
        if isinstance(hosts, dict):
            members = {str(k): str(v) for k, v in hosts.items()}
        else:
            members = {
                f"host{i}": str(addr) for i, addr in enumerate(hosts)
            }
        if not members:
            raise ValueError("Gateway needs at least one host")
        self.cfg = cfg
        self.sink = sink
        self.queue_budget = int(cfg.serving_gateway_queue_budget)
        self.priority_tiers = int(cfg.serving_gateway_priority_tiers)
        self.health_interval_s = float(
            cfg.serving_gateway_health_interval_s
        )
        self.connect_timeout_s = float(connect_timeout_s)
        self.request_timeout_s = float(request_timeout_s)
        #: ring order is the sorted host-id list — fixed at
        #: construction, so home assignments and re-homing are
        #: deterministic for the fleet's whole life
        self.ring: List[_HostHandle] = [
            _HostHandle(host_id=hid, address=members[hid])
            for hid in sorted(members)
        ]
        self._lock = threading.Lock()
        self.admitted = 0
        self.shed: Dict[str, int] = {"admission": 0, "deadline": 0}
        self.rehomes = 0
        self.forward_failures = 0
        self._stop = threading.Event()
        self._health_thread: Optional[threading.Thread] = None
        if start_health_loop:
            self._health_thread = threading.Thread(
                target=self._health_loop, name="gateway-health",
                daemon=True,
            )
            self._health_thread.start()

    # -- membership / health ---------------------------------------------

    def _record(self, **fields: Any) -> None:
        if self.sink is None:
            return
        from ..telemetry.sinks import make_record

        self.sink.write(make_record("gateway", **fields))

    def _get_json(self, h: _HostHandle, path: str,
                  timeout: float) -> Tuple[int, Any]:
        conn = h.conn(timeout)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            body = resp.read()
        finally:
            conn.close()
        try:
            return resp.status, json.loads(body)
        except (UnicodeDecodeError, json.JSONDecodeError):
            return resp.status, None

    def poll_once(self) -> None:
        """One health sweep: refresh readiness + queue depth for every
        untripped host; a host that stops answering AFTER it was ready
        is tripped (latched). Never-ready hosts are left unready, not
        tripped — they may still be warming up."""
        for h in self.ring:
            if h.tripped:
                continue
            try:
                status, payload = self._get_json(
                    h, "/healthz", self.connect_timeout_s
                )
            except (OSError, http.client.HTTPException) as e:
                if h.was_ready:
                    self._trip(h, e)
                continue
            with self._lock:
                h.ready = status == 200
                if h.ready:
                    h.was_ready = True
                if isinstance(payload, dict):
                    h.depth = int(payload.get("queue_depth", h.depth))

    def _health_loop(self) -> None:
        while not self._stop.is_set():
            self.poll_once()
            self._stop.wait(self.health_interval_s)

    def wait_ready(self, timeout_s: float = 60.0,
                   min_hosts: Optional[int] = None) -> None:
        """Block until ``min_hosts`` (default: all) hosts answer
        /healthz 200 — the fleet-level warmup barrier."""
        need = len(self.ring) if min_hosts is None else int(min_hosts)
        deadline = time.perf_counter() + float(timeout_s)
        while True:
            self.poll_once()
            ready = sum(1 for h in self.ring if h.ready)
            if ready >= need:
                return
            if time.perf_counter() >= deadline:
                raise TimeoutError(
                    f"only {ready}/{need} fleet hosts ready within "
                    f"{timeout_s}s: "
                    + ", ".join(
                        f"{h.host_id}={'ready' if h.ready else 'down'}"
                        for h in self.ring
                    )
                )
            time.sleep(min(0.05, self.health_interval_s))

    def _trip(self, h: _HostHandle, cause: BaseException) -> bool:
        """Latch ``h`` out of the ring; True only on the tripping
        transition (every later failure observes it already dead —
        exactly one ``rehome`` record per lost host)."""
        with self._lock:
            if h.tripped:
                return False
            h.tripped = True
            h.ready = False
            h.trip_cause = cause
            stranded = h.in_flight
            self.rehomes += 1
        self._record(
            event="rehome", host=h.host_id, cause=repr(cause),
            in_flight=stranded,
        )
        return True

    # -- routing + admission ---------------------------------------------

    def _pick(self, home_idx: int) -> Optional[_HostHandle]:
        """Ring walk from the home POSITION (computed over the full
        fixed membership, so healthy hosts' homes never reshuffle when
        another host trips) to the first ready host; None when the
        whole ring is down."""
        n = len(self.ring)
        with self._lock:
            for step in range(n):
                h = self.ring[(home_idx + step) % n]
                if h.ready and not h.tripped:
                    return h
        return None

    def _admission(self, h: _HostHandle, priority: int,
                   deadline_ms: Optional[float]) -> Optional[_Shed]:
        """The edge decision for one request against its home host:
        None admits; a ``_Shed`` names the typed rejection."""
        with self._lock:
            load = h.depth + h.in_flight
            ewma = h.ewma_ms
        budget = max(1, self.queue_budget >> priority)
        if load >= budget:
            return _Shed(
                reason="admission", host=h.host_id,
                detail={"load": load, "budget": budget},
            )
        if deadline_ms is not None and ewma is not None:
            est_ms = load * ewma
            if float(deadline_ms) <= est_ms:
                return _Shed(
                    reason="deadline", host=h.host_id,
                    detail={
                        "queue_est_ms": round(est_ms, 3),
                        "load": load,
                        "ewma_ms": round(ewma, 3),
                    },
                )
        return None

    def handle_serve(self, body: bytes) -> Tuple[int, str, bytes]:
        """Serve one wire-framed request end to end; returns
        ``(http_status, content_type, response_body)``. 200 carries the
        host's response frame verbatim; everything else is typed JSON
        (shed / host_down / bad_request) — a client can always tell WHY
        it was refused."""
        t_edge = time.perf_counter()
        try:
            request, header = decode_request(body)
            fingerprint = request_fingerprint(request)
        except (WireError, ValueError, TypeError) as e:
            return 400, "application/json", json.dumps(
                {"error": "bad_request", "detail": str(e)}
            ).encode()
        priority = int(header.get("priority") or 0)
        priority = min(max(priority, 0), self.priority_tiers - 1)
        deadline_ms = header.get("deadline_ms")
        home_idx = home_replica(fingerprint, len(self.ring))
        hlen = struct.unpack_from(">I", body)[0]
        blob = body[4 + hlen:]
        causes: List[BaseException] = []
        while True:
            host = self._pick(home_idx)
            if host is None:
                err = HostDownError(
                    "no ready fleet host left for this request (root "
                    "cause chained below)"
                )
                if causes:
                    err.__cause__ = causes[-1]
                return 503, "application/json", json.dumps({
                    "error": "host_down",
                    "detail": str(err),
                    "cause": repr(causes[-1]) if causes else None,
                    "causes": [repr(c) for c in causes],
                }).encode()
            shed = self._admission(host, priority, deadline_ms)
            if shed is not None:
                with self._lock:
                    self.shed[shed.reason] += 1
                self._record(
                    event="shed", reason=shed.reason,
                    tenant_id=header.get("tenant_id"),
                    priority=priority, deadline_ms=deadline_ms,
                    host=shed.host, **shed.detail,
                )
                return 429, "application/json", json.dumps({
                    "error": "shed", "reason": shed.reason,
                    "host": shed.host, **shed.detail,
                }).encode()
            # re-stamp the edge share per attempt (retries after a trip
            # have spent more of the budget) and forward the ORIGINAL
            # buffer bytes — the arrays are never re-encoded
            fwd_header = dict(header)
            fwd_header["priority"] = priority
            fwd_header["gateway_elapsed_ms"] = round(
                (time.perf_counter() - t_edge) * 1e3, 3
            )
            fwd = _encode_frame(fwd_header, [blob])
            with self._lock:
                host.in_flight += 1
            t_fwd = time.perf_counter()
            try:
                conn = host.conn(self.request_timeout_s)
                try:
                    conn.request(
                        "POST", "/v1/serve", body=fwd,
                        headers={"Content-Type": WIRE_CONTENT_TYPE},
                    )
                    resp = conn.getresponse()
                    status, payload = resp.status, resp.read()
                finally:
                    conn.close()
            except (OSError, http.client.HTTPException) as e:
                # the between-sweeps death path: fail fast, trip, and
                # re-home THIS request on the ring walk (idempotent by
                # construction) instead of stranding it on a socket
                with self._lock:
                    host.in_flight -= 1
                    self.forward_failures += 1
                causes.append(e)
                self._trip(host, e)
                continue
            rtt_ms = (time.perf_counter() - t_fwd) * 1e3
            with self._lock:
                host.in_flight -= 1
                if status == 200:
                    self.admitted += 1
                    host.ewma_ms = (
                        rtt_ms if host.ewma_ms is None
                        else 0.7 * host.ewma_ms + 0.3 * rtt_ms
                    )
            ctype = WIRE_CONTENT_TYPE if status == 200 else (
                "application/json"
            )
            return status, ctype, payload

    # -- fleet surfaces ---------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "hosts": [
                    {
                        "host_id": h.host_id,
                        "address": h.address,
                        "ready": h.ready,
                        "tripped": h.tripped,
                        "trip_cause": (
                            repr(h.trip_cause) if h.trip_cause else None
                        ),
                        "depth": h.depth,
                        "in_flight": h.in_flight,
                        "ewma_ms": (
                            round(h.ewma_ms, 3) if h.ewma_ms is not None
                            else None
                        ),
                    }
                    for h in self.ring
                ],
                "admitted": self.admitted,
                "shed": dict(self.shed),
                "rehomes": self.rehomes,
                "forward_failures": self.forward_failures,
            }

    def rollup(self) -> Dict[str, Any]:
        """The fleet aggregate: per-host rollups fetched live, their
        log histograms merged EXACTLY bucket-by-bucket (the same
        ladder, enforced by ``LogHistogram.merge``), plus the
        gateway-side admission counters. Emits one ``gateway``
        ``event='rollup'`` record when a sink is wired."""
        from .metrics import LogHistogram

        merged = {
            "adapt_ms_hist": LogHistogram(),
            "queue_ms_hist": LogHistogram(),
        }
        per_host: List[Dict[str, Any]] = []
        tenants = dispatches = 0
        for h in self.ring:
            if not h.ready or h.tripped:
                continue
            try:
                status, payload = self._get_json(
                    h, "/rollup", self.request_timeout_s
                )
            except (OSError, http.client.HTTPException) as e:
                self._trip(h, e)
                continue
            if status != 200 or not isinstance(payload, dict):
                continue
            per_host.append({"host_id": h.host_id, **payload})
            tenants += int(payload.get("tenants", 0))
            dispatches += int(payload.get("dispatches", 0))
            for key, hist in merged.items():
                if payload.get(key):
                    hist.merge(LogHistogram.from_dict(payload[key]))
        with self._lock:
            out: Dict[str, Any] = {
                "hosts": len(self.ring),
                "ready_hosts": sum(
                    1 for h in self.ring if h.ready and not h.tripped
                ),
                "tripped_hosts": [
                    h.host_id for h in self.ring if h.tripped
                ],
                "admitted": self.admitted,
                "shed": dict(self.shed),
                "rehomes": self.rehomes,
            }
        out.update(
            tenants=tenants,
            dispatches=dispatches,
            adapt_ms_p99=merged["adapt_ms_hist"].quantile(0.99),
            adapt_ms_hist=merged["adapt_ms_hist"].to_dict(),
            queue_ms_hist=merged["queue_ms_hist"].to_dict(),
            per_host=per_host,
        )
        rec = {k: v for k, v in out.items() if k != "per_host"}
        self._record(event="rollup", **rec)
        return out

    def close(self) -> None:
        self._stop.set()
        if self._health_thread is not None:
            self._health_thread.join(timeout=5)
            self._health_thread = None


# -- the gateway's own HTTP face ---------------------------------------------


class GatewayServer:
    """The one fleet endpoint: POST ``/v1/serve`` (wire frames in/out),
    GET ``/healthz`` (200 once >= 1 host is ready — the fleet is
    serving), GET ``/stats`` (membership + admission counters), GET
    ``/rollup`` (the exact-merge fleet aggregate). ``port=0`` binds an
    ephemeral port (the CI shape); stdlib ``ThreadingHTTPServer``, one
    thread per connection, same as serving/metrics.py."""

    def __init__(self, gateway: Gateway, port: int = 0,
                 host: str = "127.0.0.1"):
        from http.server import (
            BaseHTTPRequestHandler,
            ThreadingHTTPServer,
        )

        gw = gateway

        class Handler(BaseHTTPRequestHandler):
            def _send(self, status: int, ctype: str,
                      body: bytes) -> None:
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):  # noqa: N802 - http.server API
                if self.path != "/v1/serve":
                    self._send(404, "text/plain", b"not found\n")
                    return
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
                status, ctype, payload = gw.handle_serve(body)
                self._send(status, ctype, payload)

            def do_GET(self):  # noqa: N802 - http.server API
                if self.path == "/healthz":
                    ready = any(
                        h.ready and not h.tripped for h in gw.ring
                    )
                    body = json.dumps({
                        "ready": ready,
                        "hosts": {
                            h.host_id: h.ready and not h.tripped
                            for h in gw.ring
                        },
                    }).encode()
                    self._send(
                        200 if ready else 503, "application/json", body
                    )
                elif self.path == "/stats":
                    self._send(
                        200, "application/json",
                        json.dumps(gw.stats()).encode(),
                    )
                elif self.path == "/rollup":
                    self._send(
                        200, "application/json",
                        json.dumps(gw.rollup()).encode(),
                    )
                else:
                    self._send(404, "text/plain", b"not found\n")

            def log_message(self, fmt, *args):  # noqa: A003 - silence
                pass

        self.gateway = gateway
        self._server = ThreadingHTTPServer((host, int(port)), Handler)
        self.port = self._server.server_address[1]
        self.url = f"http://{host}:{self.port}"
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="gateway-http",
            daemon=True,
        )
        self._thread.start()

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5)


@dataclass
class GatewayReply:
    """One request's fate at the fleet edge, decoded."""

    status: int
    #: the decoded response frame (preds + scalars + host timings) on
    #: 200; None otherwise
    result: Optional[Dict[str, Any]] = None
    #: the typed JSON body on any non-200 (shed / host_down / ...)
    error: Optional[Dict[str, Any]] = None

    @property
    def ok(self) -> bool:
        return self.status == 200

    @property
    def shed_reason(self) -> Optional[str]:
        if self.error is not None and self.error.get("error") == "shed":
            return self.error.get("reason")
        return None


class GatewayClient:
    """A minimal wire client: encode, POST, decode — what serve-bench's
    ``--fleet`` driver and the tests speak."""

    def __init__(self, address: str, timeout_s: float = 600.0):
        self.address = str(address)
        self.timeout_s = float(timeout_s)

    def serve(self, request) -> GatewayReply:
        return self.serve_frame(encode_request(request))

    def serve_frame(self, body: bytes) -> GatewayReply:
        """POST an already-encoded wire frame (the open-loop driver
        encodes at SUBMISSION time, so a shared repeat-tenant request
        object's per-submission fields are captured correctly)."""
        host, _, port = self.address.rpartition(":")
        conn = http.client.HTTPConnection(
            host, int(port), timeout=self.timeout_s
        )
        try:
            conn.request(
                "POST", "/v1/serve", body=body,
                headers={"Content-Type": WIRE_CONTENT_TYPE},
            )
            resp = conn.getresponse()
            status, payload = resp.status, resp.read()
        finally:
            conn.close()
        if status == 200:
            return GatewayReply(
                status=status, result=decode_result(payload)
            )
        try:
            error = json.loads(payload)
        except (UnicodeDecodeError, json.JSONDecodeError):
            error = {"error": "opaque", "body": payload[:200].decode(
                "utf-8", "replace"
            )}
        return GatewayReply(status=status, error=error)
