"""One fleet HOST process: a ``ReplicaSet`` behind a wire-frame HTTP
server.

The :mod:`serving.gateway` front tier puts N of these behind one
endpoint. Each host is the full PR-15 serving stack — replicas,
micro-batchers, adapted-params LRUs, the cache-affinity
``ReplicaRouter`` — plus four HTTP surfaces:

* ``POST /v1/serve`` — one wire-framed request (serving/gateway.py
  codec) in, one framed ``TenantResult`` out. The host re-stamps the
  request's deadline with the budget REMAINING after the edge
  (``deadline_ms - gateway_elapsed_ms``) and records the edge share as
  ``gateway_ms`` on the request, so the micro-batcher's
  ``event='deadline'`` records attribute the network edge honestly
  without any cross-host clock (only DURATIONS cross the wire, never
  timestamps);
* ``GET /healthz`` — 200 with ``{"ready": true, "queue_depth": N}``
  once every replica is warmed (503 while warming) — the gateway's
  membership poll reads both fields: readiness gates routing, depth
  feeds admission control;
* ``GET /stats``  — the router's placement stats + live queue depth;
* ``GET /rollup`` — the pool rollup (per-replica breakdown + the
  mergeable ``adapt_ms_hist`` / ``queue_ms_hist`` the gateway's fleet
  rollup merges exactly).

``python -m howtotrainyourmamlpytorch_tpu.serving.fleet`` runs one host
standalone (the serve-bench ``--fleet N`` driver spawns N of them):
it prints one ``{"host_ready": true, "port": ..., "host_id": ...}``
JSON line on stdout once warmed, then serves until SIGTERM/SIGINT.

``FleetHost`` itself is jax-free (it duck-types the router/pool
surfaces), so the gateway tests drive it against stub pools; only
``main()`` builds real engines.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, List, Optional

from .gateway import (
    WIRE_CONTENT_TYPE,
    WireError,
    decode_request,
    encode_result,
)
from .router import AllReplicasUnhealthyError


class FleetHost:
    """The HTTP face of one host's router + pool.

    :param router: a ``ReplicaRouter`` (or stub) — ``submit(request)``
        returning a pending with ``get(timeout)``.
    :param pool: a ``ReplicaSet`` (or stub) — ``readiness()`` /
        ``rollup()`` / ``replicas`` with ``queue_depth()``.
    :param sink: optional telemetry sink (closed by the OWNER, not the
        host — the host only serves).
    :param host_id: this member's stable fleet identity (ring position
        comes from the gateway's sorted id list).
    """

    def __init__(self, router, pool, sink=None,
                 host_id: str = "host0", port: int = 0,
                 bind: str = "127.0.0.1",
                 default_timeout_s: float = 600.0):
        from http.server import (
            BaseHTTPRequestHandler,
            ThreadingHTTPServer,
        )

        self.router = router
        self.pool = pool
        self.sink = sink
        self.host_id = str(host_id)
        self.default_timeout_s = float(default_timeout_s)
        host_self = self

        class Handler(BaseHTTPRequestHandler):
            # HTTP/1.1 so the gateway's keep-alive connection pool can
            # actually reuse sockets (the 1.0 default closes per
            # request); every response already carries Content-Length,
            # which 1.1 persistence requires
            protocol_version = "HTTP/1.1"

            def _send(self, status: int, ctype: str,
                      body: bytes) -> None:
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _send_json(self, status: int, payload: Any) -> None:
                self._send(
                    status, "application/json",
                    json.dumps(payload).encode(),
                )

            def do_POST(self):  # noqa: N802 - http.server API
                if self.path != "/v1/serve":
                    self._send(404, "text/plain", b"not found\n")
                    return
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
                status, ctype, payload = host_self.handle_serve(body)
                self._send(status, ctype, payload)

            def do_GET(self):  # noqa: N802 - http.server API
                if self.path == "/healthz":
                    ready = host_self.ready()
                    self._send_json(200 if ready else 503, {
                        "ready": ready,
                        "host_id": host_self.host_id,
                        "queue_depth": host_self.queue_depth(),
                        # this host's own monotonic clock, for the
                        # gateway's Cristian offset estimator — the ONE
                        # place a raw timestamp crosses the wire, and
                        # only into an estimator that assumes nothing
                        # about either origin
                        "perf_ms": time.perf_counter() * 1e3,
                    })
                elif self.path == "/stats":
                    self._send_json(200, host_self.stats())
                elif self.path == "/rollup":
                    self._send_json(200, host_self.pool.rollup())
                else:
                    self._send(404, "text/plain", b"not found\n")

            def log_message(self, fmt, *args):  # noqa: A003 - silence
                pass

        self._server = ThreadingHTTPServer((bind, int(port)), Handler)
        self.port = self._server.server_address[1]
        self.url = f"http://{bind}:{self.port}"
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name=f"fleet-host-{self.host_id}", daemon=True,
        )
        self._thread.start()

    # -- surfaces ---------------------------------------------------------

    def ready(self) -> bool:
        readiness = getattr(self.pool, "readiness", None)
        if readiness is None:
            return True
        return all(readiness().values())

    def queue_depth(self) -> int:
        depth = 0
        for r in getattr(self.pool, "replicas", []):
            depth += int(r.queue_depth())
        return depth

    def stats(self) -> Dict[str, Any]:
        stats = getattr(self.router, "stats", None)
        out = dict(stats()) if stats is not None else {}
        out["host_id"] = self.host_id
        out["queue_depth"] = self.queue_depth()
        return out

    def handle_serve(self, body: bytes):
        """Decode, re-stamp the deadline with the post-edge remainder,
        submit through the affinity router, and frame the result.
        Typed failures: 400 (malformed frame/geometry), 429 (budget
        already spent at the edge — the gateway's shed estimate raced a
        slow forward), 503 (every replica tripped — the host is dying
        and the gateway's next contact trips it), 504 (timeout)."""
        t0 = time.perf_counter()
        try:
            request, header = decode_request(body)
        except WireError as e:
            return 400, "application/json", json.dumps(
                {"error": "bad_request", "detail": str(e)}
            ).encode()
        gateway_ms = header.get("gateway_elapsed_ms")
        if gateway_ms is not None:
            request.gateway_ms = float(gateway_ms)
        priority = header.get("priority")
        if priority is not None:
            request.priority = int(priority)
        trace_id = header.get("trace_id")
        parent_span_id = header.get("parent_span_id")
        if isinstance(trace_id, str) and isinstance(parent_span_id, str):
            # the gateway's trace baggage (only present while the edge
            # traces): the batcher adopts it so this host's span tree
            # parents under the gateway's forward span
            request.trace_ctx = {
                "trace_id": trace_id,
                "parent_span_id": parent_span_id,
                "request_id": header.get("request_id"),
                "clock_offset_ms": header.get("clock_offset_ms"),
            }
        if request.deadline_ms is not None and gateway_ms is not None:
            remaining = float(request.deadline_ms) - float(gateway_ms)
            if remaining <= 0:
                return 429, "application/json", json.dumps({
                    "error": "shed", "reason": "deadline",
                    "where": "host",
                    "detail": "deadline budget spent before arrival",
                }).encode()
            request.deadline_ms = remaining
        try:
            pending = self.router.submit(request)
        except (ValueError, TypeError) as e:
            return 400, "application/json", json.dumps(
                {"error": "bad_request", "detail": str(e)}
            ).encode()
        except AllReplicasUnhealthyError as e:
            return 503, "application/json", json.dumps({
                "error": "host_unhealthy",
                "detail": str(e),
                "causes": [repr(c) for c in e.causes],
            }).encode()
        timeout = self.default_timeout_s
        try:
            result = pending.get(timeout=timeout)
        except TimeoutError:
            return 504, "application/json", json.dumps({
                "error": "timeout",
                "detail": f"request not served within {timeout}s",
            }).encode()
        except Exception as e:  # noqa: BLE001 - relayed typed, chained
            return 500, "application/json", json.dumps({
                "error": "dispatch_failed",
                "detail": repr(e),
                "cause": repr(e.__cause__) if e.__cause__ else None,
            }).encode()
        host_ms = (time.perf_counter() - t0) * 1e3
        frame = encode_result(
            result, host_id=self.host_id, host_ms=round(host_ms, 3),
        )
        return 200, WIRE_CONTENT_TYPE, frame

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5)


# -- standalone host process -------------------------------------------------


def main(argv: Optional[List[str]] = None) -> int:
    """Run one fleet host standalone (the ``--fleet`` driver's child
    process). Prints a single readiness JSON line on stdout once the
    pool is warmed, then serves until SIGTERM/SIGINT."""
    import argparse
    import os
    import signal
    import sys

    parser = argparse.ArgumentParser(
        prog="fleet-host",
        description="One fleet host: a ReplicaSet + affinity router "
                    "behind the wire-frame HTTP serving endpoint",
    )
    parser.add_argument("--fast", action="store_true",
                        help="the small deterministic serving config "
                             "(the CI fleet shape)")
    parser.add_argument("--config", default=None,
                        help="experiment JSON supplying the geometry "
                             "and serving_* knobs")
    parser.add_argument("--host-id", default="host0",
                        help="this member's stable fleet identity")
    parser.add_argument("--port", type=int, default=0,
                        help="bind port (0 = ephemeral, printed on the "
                             "readiness line)")
    parser.add_argument("--replicas", type=int, default=1,
                        help="pool width on this host")
    parser.add_argument("--ingest", default=None,
                        choices=("f32", "uint8", "index"),
                        help="override cfg.serving_ingest")
    parser.add_argument("--cache-size", type=int, default=None,
                        help="override cfg.serving_adapted_cache_size")
    parser.add_argument("--emulate-device-ms", type=float, default=0.0,
                        help="per-dispatch device-occupancy emulation "
                             "(serving/bench.py shim)")
    parser.add_argument("--telemetry", default=None, metavar="PATH",
                        help="this host's telemetry JSONL (deadline/"
                             "serving records; `cli slo --fleet` merges "
                             "the per-host logs)")
    parser.add_argument("--trace", action="store_true",
                        help="emit span records into --telemetry "
                             "(process-labelled, gateway-adoptable; "
                             "`cli trace --fleet` merges them)")
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args(argv)
    if args.trace and not args.telemetry:
        parser.error("--trace requires --telemetry (spans are records)")
    if args.replicas < 1:
        parser.error(f"--replicas must be >= 1, got {args.replicas}")
    if args.emulate_device_ms < 0:
        parser.error("--emulate-device-ms must be >= 0, got "
                     f"{args.emulate_device_ms}")
    # one virtual CPU device per replica, forced before jax first loads
    # (the serve-bench --replicas pattern)
    if "jax" not in sys.modules:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count="
                        f"{args.replicas}"
            ).strip()

    from ..core import maml
    from .bench import (
        _bench_cfg,
        _DeviceOccupancyShim,
        _synth_store,
        bench_shots_buckets,
    )
    from .replica import ReplicaSet
    from .router import ReplicaRouter

    cfg = _bench_cfg(args)
    shots_buckets = bench_shots_buckets(cfg)
    state = maml.init_state(cfg)
    sink = None
    if args.telemetry:
        from ..telemetry.sinks import JsonlSink

        sink = JsonlSink(args.telemetry)
    ingest = args.ingest or cfg.serving_ingest
    cache_size = (
        cfg.serving_adapted_cache_size if args.cache_size is None
        else args.cache_size
    )
    store = _synth_store(cfg) if ingest == "index" else None
    tracer = None
    if args.trace and sink is not None:
        from ..telemetry.sinks import make_record
        from ..telemetry.tracing import Tracer

        span_sink = sink

        def _emit(**fields):
            span_sink.write(make_record("span", **fields))

        # process-labelled + id-prefixed so the merged fleet log keeps
        # span ids unique and `cli trace --fleet` gets its track label
        tracer = Tracer(emit=_emit, process=args.host_id,
                        span_prefix=f"{args.host_id}-")
    import jax

    pool_devices = None
    if (jax.default_backend() == "cpu"
            and len(jax.devices()) > args.replicas):
        pool_devices = list(jax.devices())[:args.replicas]
    pool = ReplicaSet(
        cfg, state, n_replicas=args.replicas, devices=pool_devices,
        shots_buckets=shots_buckets, sink=sink, strict_retrace=True,
        ingest=ingest, store=store, cache_size=cache_size,
        tracer=tracer,
    )
    pool.warmup()
    if args.emulate_device_ms:
        for r in pool.replicas:
            r.engine = _DeviceOccupancyShim(
                r.engine, args.emulate_device_ms
            )
    router = ReplicaRouter(
        pool, spill_depth=cfg.serving_router_spill_depth
    )
    host = FleetHost(
        router, pool, sink=sink, host_id=args.host_id, port=args.port
    )
    print(json.dumps({
        "host_ready": True,
        "host_id": host.host_id,
        "port": host.port,
        "replicas": args.replicas,
        "ingest": ingest,
    }), flush=True)

    stop = threading.Event()

    def _drain(signum, frame):  # noqa: ARG001 - signal API
        stop.set()

    signal.signal(signal.SIGTERM, _drain)
    signal.signal(signal.SIGINT, _drain)
    stop.wait()
    # graceful exit: final rollup record (histograms included) before
    # the pool drains — a SIGKILLed host simply doesn't get one, which
    # is exactly the forensic difference the fleet logs should show
    try:
        pool.rollup()
    except Exception:  # noqa: BLE001 - shutdown best-effort
        pass
    host.close()
    pool.close()
    if sink is not None:
        sink.close()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
