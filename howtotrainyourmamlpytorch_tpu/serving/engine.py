"""ServingEngine: the compiled adapt-then-predict hot path.

The engine owns a servable snapshot (a ``MetaState`` restored READ-ONLY
from a training checkpoint — no experiment-dir mutation, see
``experiment.checkpoint.load_checkpoint(readonly=True)``) and the jitted
``core.maml.make_serve_step`` program, dispatched at a fixed set of
static shapes:

* **tenant buckets** — every dispatch is padded up to the smallest
  ``serving_bucket_ladder`` entry >= its tenant count, with a float mask
  zeroing pad tenants out of the aggregate metrics (per-tenant outputs
  are independent of padding by vmap construction, tested bit-exact);
* **shots buckets** — one compiled signature per distinct support-shot
  count the engine is configured to serve (``shots_buckets``; default:
  the config's ``num_samples_per_class`` only). Shots are never padded —
  pad support samples would enter the inner-loop adaptation loss.

``warmup()`` compiles (and executes once, on zeros) every
(bucket, shots) program at startup, so the first real request pays no
compile; when the config points at a persistent compilation cache the
compiles warm-start from the training run's ``xla_cache``. A STRICT
``analysis.auditor.RetraceDetector`` watches every dispatch site: after
warmup, any new abstract signature — i.e. any mid-run retrace — raises
instead of silently paying a 20-40s TPU compile on a live request.

State donation: the serve program passes the state through as an output
and the jit donates it (``maml.SERVE_DONATE``) — the executable aliases
the state buffers input->output (the donation contract the auditor
checks), the engine re-binds its reference after every dispatch, and the
snapshot stays single-buffered in HBM like the train family's state.

Telemetry: every dispatch emits a schema-v8 ``serving`` record
(event='dispatch': tenants, bucket, shots, queue_ms, adapt_ms) through
``telemetry.sinks.make_record`` into an optional sink; ``rollup()``
condenses the run into an event='rollup' record (adapt_ms p50/p95,
tenants_per_sec) — the line ``cli inspect summary`` prints jax-free.
"""

from __future__ import annotations

import os
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Deque, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..config import MAMLConfig


@dataclass
class TenantResult:
    """One tenant's adapt-then-predict outcome.

    ``preds`` is the (way * targets, classes) softmax over the query set
    — the leading axis is the FLATTENED (class, target) query stream,
    class-major, matching the eval path's prediction layout; ``loss`` /
    ``accuracy`` are the query-set scalars, None when the request
    shipped no query labels (predictions are label-free).
    """

    tenant_id: Optional[str]
    preds: np.ndarray
    loss: Optional[float]
    accuracy: Optional[float]


@dataclass
class DispatchResult:
    """One dispatch's results + the latency the telemetry records."""

    results: List[TenantResult]
    tenants: int
    bucket: int
    shots: int
    queue_ms: float
    adapt_ms: float
    metrics: Dict[str, float]  # masked tenant-mean loss/accuracy over
    # the LABELED tenants (0 when the dispatch carried none)


def load_servable_snapshot(
    cfg: MAMLConfig,
    model_save_dir: str,
    model_idx="latest",
    model_name: str = "train_model",
    enable_cache: bool = True,
) -> Tuple[Any, Dict[str, Any]]:
    """Restore a training checkpoint into a servable (host) snapshot.

    READ-ONLY by contract: the restore never mutates the training run's
    directory — no ``.old`` recovery rename, no summary-CSV truncation,
    no experiment-state rewrite (the training-owned resume path in
    ``experiment/builder.py`` does all three; a serving process reading a
    LIVE run's directory must do none). Returns
    ``(MetaState, experiment_state)`` with host numpy leaves — the engine
    places them on device.

    ``enable_cache`` (default) also points this process's persistent
    compilation cache at the training run's ``xla_cache``
    (``resolve_serving_cache_dir`` — the one additive write serving may
    make under the experiment dir), so a subsequent ``warmup()``
    warm-starts from the training run's compiles instead of paying them
    again. Pass False to leave the process's cache setting untouched.

    The shape/dtype template comes from ``jax.eval_shape`` over
    ``maml.init_state``, so loading allocates nothing beyond the restored
    arrays themselves.
    """
    import jax

    from ..core import maml
    from ..experiment import checkpoint as ckpt

    if enable_cache:
        from ..experiment.system import enable_compilation_cache

        cache_dir = resolve_serving_cache_dir(cfg, model_save_dir)
        if cache_dir:
            enable_compilation_cache(cache_dir)
    template = jax.eval_shape(lambda: maml.init_state(cfg))
    return ckpt.load_checkpoint(
        model_save_dir, model_name, model_idx, template, readonly=True
    )


def _bucket_for(n: int, ladder: Sequence[int]) -> int:
    for b in ladder:
        if n <= b:
            return b
    raise ValueError(
        f"{n} tenants exceed the serving bucket ladder {list(ladder)}; "
        "the batcher must cap groups at serving_max_tenants_per_dispatch"
    )


class ServingEngine:
    """Multi-tenant adapt-on-request inference over one servable snapshot.

    :param cfg: fixes the task geometry (way / query targets / image
        shape) and the serving knobs (``serving_bucket_ladder``,
        ``serving_max_tenants_per_dispatch``).
    :param state: the servable ``MetaState`` (host numpy or device
        arrays) — from ``load_servable_snapshot`` or ``maml.init_state``.
    :param shots_buckets: support-shot counts to compile programs for
        (default: the config's ``num_samples_per_class`` only).
    :param sink: optional telemetry sink (``telemetry.sinks.JsonlSink``
        or anything with ``write(record)``); records are built through
        ``make_record`` (schema v8 ``serving`` kind).
    :param strict_retrace: raise ``RetraceError`` on any post-warmup
        recompile (the production default); False records events only.
    """

    #: latency-sample window for the rollup percentiles (last N
    #: dispatches) — bounds host memory on a long-lived server
    LATENCY_WINDOW = 4096

    def __init__(
        self,
        cfg: MAMLConfig,
        state,
        shots_buckets: Optional[Sequence[int]] = None,
        sink=None,
        strict_retrace: bool = True,
    ):
        import jax

        from ..analysis.auditor import RetraceDetector
        from ..core import maml

        self.cfg = cfg
        self.buckets: Tuple[int, ...] = tuple(cfg.serving_bucket_ladder)
        self.max_tenants: int = cfg.serving_max_tenants_per_dispatch
        self.shots_buckets: Tuple[int, ...] = tuple(
            shots_buckets
            if shots_buckets is not None
            else (cfg.num_samples_per_class,)
        )
        if any(s < 1 for s in self.shots_buckets):
            raise ValueError(
                f"shots buckets must be >= 1, got {self.shots_buckets}"
            )
        self.sink = sink
        # the engine OWNS its device snapshot: every dispatch donates the
        # state and re-binds to the (aliased) returned one, so the buffers
        # must be private — ``jnp.array(copy=True)`` (plain device_put is
        # a no-op for an already-committed array and would donate the
        # CALLER's buffers out from under it)
        import jax.numpy as jnp

        self._state = jax.tree_util.tree_map(
            lambda x: jnp.array(x, copy=True), state
        )
        self._step = jax.jit(
            maml.make_serve_step(cfg), donate_argnums=maml.SERVE_DONATE
        )
        self.retrace_detector = RetraceDetector(strict=strict_retrace)
        # a dispatch that fails AFTER donation leaves self._state pointing
        # at deleted buffers; the engine marks itself dead with the root
        # cause so later requests fail fast naming it, instead of a
        # stream of unrelated "buffer was donated/deleted" errors
        self._dead: Optional[BaseException] = None
        # rollup accumulators (per-dispatch samples, warmup excluded);
        # throughput is measured over the wall-clock SPAN from the first
        # real dispatch's start to the last one's end — summing per-
        # dispatch queue+adapt would double-count queue time that
        # overlaps the previous dispatch's device time under the
        # micro-batcher. Latency samples are a BOUNDED window (the last
        # LATENCY_WINDOW dispatches): a long-lived server must not grow
        # host memory per dispatch, and windowed p50/p95 track current
        # latency instead of a lifetime aggregate.
        self._adapt_ms: Deque[float] = deque(maxlen=self.LATENCY_WINDOW)
        self._queue_ms: Deque[float] = deque(maxlen=self.LATENCY_WINDOW)
        self._tenants_served = 0
        self._span_start: Optional[float] = None
        self._span_end: Optional[float] = None

    # -- shapes ------------------------------------------------------------

    def _zeros_batch(self, bucket: int, shots: int):
        n = self.cfg.num_classes_per_set
        t = self.cfg.num_target_samples
        h, w, c = self.cfg.im_shape
        return (
            np.zeros((bucket, n, shots, h, w, c), np.float32),
            np.zeros((bucket, n, shots), np.int32),
            np.zeros((bucket, n, t, h, w, c), np.float32),
            np.zeros((bucket, n, t), np.int32),
        )

    def _validate(self, req) -> int:
        """Check one request against the engine geometry; returns its
        shots count."""
        n = self.cfg.num_classes_per_set
        t = self.cfg.num_target_samples
        h, w, c = self.cfg.im_shape
        sx = np.asarray(req.support_x)
        if sx.ndim != 5 or sx.shape[0] != n or sx.shape[2:] != (h, w, c):
            raise ValueError(
                f"support_x must be ({n}, shots, {h}, {w}, {c}), got "
                f"{sx.shape}"
            )
        shots = int(sx.shape[1])
        if shots not in self.shots_buckets:
            raise ValueError(
                f"request shots={shots} not in the engine's shots buckets "
                f"{self.shots_buckets} (shots are never padded — they "
                "enter the adaptation loss)"
            )
        if tuple(np.asarray(req.support_y).shape) != (n, shots):
            raise ValueError(
                f"support_y must be ({n}, {shots}), got "
                f"{np.asarray(req.support_y).shape}"
            )
        qx = np.asarray(req.query_x)
        if qx.shape != (n, t, h, w, c):
            raise ValueError(
                f"query_x must be ({n}, {t}, {h}, {w}, {c}), got {qx.shape}"
            )
        if req.query_y is not None and tuple(
            np.asarray(req.query_y).shape
        ) != (n, t):
            raise ValueError(
                f"query_y must be ({n}, {t}) or None, got "
                f"{np.asarray(req.query_y).shape}"
            )
        return shots

    # -- compile management ------------------------------------------------

    def _site(self, bucket: int, shots: int) -> str:
        return f"serve_step[b={bucket},s={shots}]"

    def warmup(self) -> float:
        """Compile (and run once, on zeros) every (bucket, shots) program.

        Returns the wall seconds spent — the whole compile bill of the
        engine: after this, steady-state traffic of ANY mix of bucket
        sizes and configured shots dispatches with zero retraces (the
        strict detector enforces it). With a persistent compilation cache
        enabled the compiles warm-start from disk.
        """
        start = time.perf_counter()
        for shots in self.shots_buckets:
            for bucket in self.buckets:
                x_s, y_s, x_t, y_t = self._zeros_batch(bucket, shots)
                valid = np.zeros(bucket, np.float32)
                self._dispatch(bucket, shots, x_s, y_s, x_t, y_t, valid)
        return time.perf_counter() - start

    def _dispatch(self, bucket, shots, x_s, y_s, x_t, y_t, valid):
        """One device dispatch; returns (out, adapt_ms). ``adapt_ms`` is
        enqueue-to-host-fetch: it includes the H2D upload and the result
        readback — the latency a caller actually observes.

        A failure in here (device error, OOM, interrupt mid-readback) is
        TERMINAL for the engine: the dispatch may already have consumed
        the donated state buffers, so the engine marks itself dead with
        the root cause and every later call raises it — never a stream
        of unrelated donated-buffer errors masking the real failure.
        """
        if self._dead is not None:
            raise RuntimeError(
                "ServingEngine is dead: a previous dispatch failed after "
                "the state was donated (root cause chained below); build "
                "a fresh engine from the snapshot"
            ) from self._dead
        self.retrace_detector.observe(
            self._site(bucket, shots), (self._state, x_s, y_s, x_t, y_t, valid)
        )
        start = time.perf_counter()
        try:
            new_state, out = self._step(
                self._state, x_s, y_s, x_t, y_t, valid
            )
            # host-fetch every output the caller reads: the one sync that
            # provably blocks on every backend (see bench.py's sync note)
            out = {
                "preds": np.asarray(out["preds"]),
                "loss": np.asarray(out["loss"]),
                "accuracy": np.asarray(out["accuracy"]),
                "metrics": {
                    k: float(np.asarray(v))
                    for k, v in out["metrics"].items()
                },
            }
        except BaseException as e:
            self._dead = e
            raise
        adapt_ms = (time.perf_counter() - start) * 1e3
        # re-bind: the old state buffers were donated to (and alias) the
        # returned state — the previous reference is dead
        self._state = new_state
        return out, adapt_ms

    # -- serving -----------------------------------------------------------

    def serve_group(self, requests: Sequence[Any],
                    queue_ms: float = 0.0) -> DispatchResult:
        """Serve one group of same-shots requests as ONE padded dispatch.

        The group must fit ``serving_max_tenants_per_dispatch`` (the
        batcher's job); pad tenants up to the bucket are zeros, masked
        out of the aggregate metrics and — by vmap independence —
        incapable of touching real tenants' outputs.
        """
        if not requests:
            raise ValueError("serve_group needs at least one request")
        if len(requests) > self.max_tenants:
            raise ValueError(
                f"{len(requests)} requests exceed "
                f"serving_max_tenants_per_dispatch={self.max_tenants}"
            )
        shots_set = {self._validate(r) for r in requests}
        if len(shots_set) != 1:
            raise ValueError(
                f"one dispatch must carry one shots bucket, got {shots_set}"
            )
        shots = shots_set.pop()
        n_real = len(requests)
        bucket = _bucket_for(n_real, self.buckets)
        x_s, y_s, x_t, y_t = self._zeros_batch(bucket, shots)
        valid = np.zeros(bucket, np.float32)
        labeled = np.zeros(n_real, bool)
        for i, req in enumerate(requests):
            x_s[i] = np.asarray(req.support_x, np.float32)
            y_s[i] = np.asarray(req.support_y, np.int32)
            x_t[i] = np.asarray(req.query_x, np.float32)
            if req.query_y is not None:
                y_t[i] = np.asarray(req.query_y, np.int32)
                labeled[i] = True
                # the metric mask admits LABELED tenants only: a
                # label-free tenant's y_t slot is fabricated zeros, and
                # scoring it would poison the aggregate (its predictions
                # don't read labels and are unaffected)
                valid[i] = 1.0
        if self._span_start is None:
            self._span_start = time.perf_counter()
        out, adapt_ms = self._dispatch(
            bucket, shots, x_s, y_s, x_t, y_t, valid
        )
        self._span_end = time.perf_counter()
        results = [
            TenantResult(
                tenant_id=getattr(req, "tenant_id", None),
                preds=out["preds"][i],
                loss=float(out["loss"][i]) if labeled[i] else None,
                accuracy=float(out["accuracy"][i]) if labeled[i] else None,
            )
            for i, req in enumerate(requests)
        ]
        self._adapt_ms.append(adapt_ms)
        self._queue_ms.append(float(queue_ms))
        self._tenants_served += n_real
        self._record(
            event="dispatch", tenants=n_real, bucket=bucket, shots=shots,
            queue_ms=round(float(queue_ms), 3), adapt_ms=round(adapt_ms, 3),
        )
        return DispatchResult(
            results=results, tenants=n_real, bucket=bucket, shots=shots,
            queue_ms=float(queue_ms), adapt_ms=adapt_ms,
            metrics=out["metrics"],
        )

    # -- telemetry ---------------------------------------------------------

    def _record(self, **fields) -> None:
        if self.sink is None:
            return
        from ..telemetry.sinks import make_record

        self.sink.write(make_record("serving", **fields))

    def rollup(self) -> Dict[str, Any]:
        """Latency/throughput rollup; emits the event='rollup' telemetry
        record when a sink is attached. Percentiles cover the last
        ``LATENCY_WINDOW`` (non-warmup) dispatches (current latency, not
        a lifetime aggregate); ``tenants_per_sec`` is lifetime tenants
        over the wall-clock span from the first dispatch's start to the
        last one's end — the closed-loop number, and the ONE definition
        of this metric (serve-bench and bench.py report it verbatim); an
        open-loop server's throughput is additionally bounded by arrival
        rate."""
        adapt = np.asarray(self._adapt_ms, np.float64)
        queue = np.asarray(self._queue_ms, np.float64)
        span_s = (
            self._span_end - self._span_start
            if self._span_start is not None and self._span_end is not None
            else 0.0
        )
        out: Dict[str, Any] = {
            "dispatches": int(adapt.size),
            "tenants": int(self._tenants_served),
            "retraces": int(self.retrace_detector.retrace_count),
            "adapt_ms_p50": (
                round(float(np.percentile(adapt, 50)), 3) if adapt.size
                else None
            ),
            "adapt_ms_p95": (
                round(float(np.percentile(adapt, 95)), 3) if adapt.size
                else None
            ),
            "queue_ms_p50": (
                round(float(np.percentile(queue, 50)), 3) if queue.size
                else None
            ),
            "tenants_per_sec": (
                round(self._tenants_served / span_s, 3)
                if span_s > 0
                else None
            ),
        }
        self._record(event="rollup", **out)
        return out


def resolve_serving_cache_dir(cfg: MAMLConfig,
                              model_save_dir: str) -> Optional[str]:
    """The persistent-compilation-cache directory a serving process should
    warm-start from: an explicit ``compilation_cache_dir`` wins; 'auto'
    resolves to the training experiment's ``xla_cache`` SIBLING of the
    checkpoint directory (the same resolution the experiment builder
    makes); '' disables. The cache is content-addressed and additive —
    the one write a serving process may make under the experiment dir.
    """
    if cfg.compilation_cache_dir == "":
        return None
    if cfg.compilation_cache_dir != "auto":
        return cfg.compilation_cache_dir
    return os.path.join(
        os.path.dirname(os.path.abspath(model_save_dir)), "xla_cache"
    )
