"""ServingEngine: the compiled adapt-then-predict hot path.

The engine owns a servable snapshot (a ``MetaState`` restored READ-ONLY
from a training checkpoint — no experiment-dir mutation, see
``experiment.checkpoint.load_checkpoint(readonly=True)``) and a table of
AOT-compiled programs dispatched at a fixed set of static shapes:

* **tenant buckets** — every dispatch is padded up to the smallest
  ``serving_bucket_ladder`` entry >= its tenant count, with a float mask
  zeroing pad tenants out of the aggregate metrics (per-tenant outputs
  are independent of padding by vmap construction, tested bit-exact);
* **shots buckets** — one compiled signature per distinct support-shot
  count the engine is configured to serve (``shots_buckets``; default:
  the config's ``num_samples_per_class`` only). Shots are never padded —
  pad support samples would enter the inner-loop adaptation loss.

**Ingest tiers** (``serving_ingest`` / the ``ingest`` ctor arg): 'f32'
uploads host-assembled float32 pixels; 'uint8' uploads raw uint8 pixels
and decodes on device through the device-pipeline LUT (bit-exact with
the host decode by construction, ~4x less H2D per dispatch); 'index'
requires a registered uint8 ``FlatStore`` (resident in HBM, uploaded
once at engine construction) and ships only int32 store-row tensors per
dispatch (<1KB) — labels never cross H2D (slot iota, the training
index-path convention). Every dispatch's actual H2D byte count rides the
telemetry (``ingest_bytes``) and the rollup (``h2d_bytes_per_dispatch``).

**Adapted-params cache** (``serving_adapted_cache_size`` > 0): an LRU
keyed by tenant support-set fingerprint (content hash + shots + snapshot
id) storing each adapted tenant's post-inner-loop fast weights on the
host. Repeat tenants skip the inner loop entirely: their queries ride
the cheap predict-only program (``core.maml.make_predict_step`` —
forward GEMMs only, zero inner-loop gradient ops), bit-exact with full
re-adaptation at the same tenant width. Mixed hit/miss groups split
cleanly into (at most) one adapt dispatch + one predict dispatch, each
on its own bucket.

``warmup()`` compiles (AOT) and executes once, on zeros, every program
the engine can dispatch, so the first real request pays no compile —
or, when an artifact directory is configured (``serving_export_dir`` /
the ``artifact_dir`` argument / ``cli serve-export``), DESERIALIZES the
previously exported executables instead: zero XLA compilations, with a
compile-count assertion surface in ``warmup_stats`` (serving/export.py).
On any artifact mismatch warmup falls back to compile-then-save. A
STRICT ``analysis.auditor.RetraceDetector`` watches every dispatch site:
after warmup, any new abstract signature — i.e. any mid-run retrace —
raises instead of silently paying a 20-40s TPU compile on a live
request.

State donation: every serving program passes the state through as an
output and donates it (``maml.SERVE_DONATE`` / ``maml.PREDICT_DONATE``)
— the executable aliases the state buffers input->output (the donation
contract the auditor checks), the engine re-binds its reference after
every dispatch, and the snapshot stays single-buffered in HBM like the
train family's state.

Telemetry: every dispatch emits a schema-v10 ``serving`` record
(event='dispatch': tenants, bucket, shots, queue_ms, adapt_ms, program,
ingest, ingest_bytes, cache_hits — and the latency decomposition
batch_ms / dispatch_ms / sync_ms, which with queue_ms accounts for the
end-to-end request latency) through ``telemetry.sinks.make_record``
into an optional sink; warmup emits an event='warmup' record (mode,
warmup_ms, xla_compiles); ``rollup()`` condenses the run into an
event='rollup' record (adapt_ms p50/p95, tenants_per_sec,
h2d_bytes_per_dispatch, cache_hit_rate, batch/dispatch/sync
decomposition) — the line ``cli inspect summary`` prints jax-free,
with a per-(program, bucket, shots) breakdown. With a ``tracer``
attached, every dispatch additionally emits ``cache_lookup`` /
``assemble`` / ``dispatch`` / ``sync`` / ``realign`` spans
(telemetry/tracing.py) that ``cli trace`` renders as a Perfetto
timeline.
"""

from __future__ import annotations

import hashlib
import os
import time
from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import Any, Deque, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..config import MAMLConfig
from ..telemetry import tracing


@dataclass
class TenantResult:
    """One tenant's adapt-then-predict outcome.

    ``preds`` is the (way * targets, classes) softmax over the query set
    — the leading axis is the FLATTENED (class, target) query stream,
    class-major, matching the eval path's prediction layout; ``loss`` /
    ``accuracy`` are the query-set scalars, None when the request
    shipped no query labels (predictions are label-free).
    """

    tenant_id: Optional[str]
    preds: np.ndarray
    loss: Optional[float]
    accuracy: Optional[float]


@dataclass
class DispatchResult:
    """One group's results + the latency the telemetry records.

    With the adapted-params cache on, a group may have split into one
    adapt dispatch (misses) plus one predict dispatch (hits):
    ``adapt_ms`` is then the summed device latency, ``bucket`` the adapt
    dispatch's bucket (the predict bucket when the group was all hits),
    and ``cache_hits`` how many tenants skipped the inner loop.
    """

    results: List[TenantResult]
    tenants: int
    bucket: int
    shots: int
    queue_ms: float
    adapt_ms: float
    metrics: Dict[str, float]  # masked tenant-mean loss/accuracy over
    # the LABELED tenants (0 when the dispatch carried none)
    cache_hits: int = 0
    ingest_bytes: int = 0  # actual H2D payload bytes of the dispatches
    # the latency decomposition (schema v10): host batch assembly, device
    # dispatch enqueue, and host-blocking result fetch — with queue_ms
    # they sum to the end-to-end latency a request observed
    # (adapt_ms == dispatch_ms + sync_ms by construction)
    batch_ms: float = 0.0
    dispatch_ms: float = 0.0
    sync_ms: float = 0.0


def load_servable_snapshot(
    cfg: MAMLConfig,
    model_save_dir: str,
    model_idx="latest",
    model_name: str = "train_model",
    enable_cache: bool = True,
) -> Tuple[Any, Dict[str, Any]]:
    """Restore a training checkpoint into a servable (host) snapshot.

    READ-ONLY by contract: the restore never mutates the training run's
    directory — no ``.old`` recovery rename, no summary-CSV truncation,
    no experiment-state rewrite (the training-owned resume path in
    ``experiment/builder.py`` does all three; a serving process reading a
    LIVE run's directory must do none). Returns
    ``(MetaState, experiment_state)`` with host numpy leaves — the engine
    places them on device.

    ``enable_cache`` (default) also points this process's persistent
    compilation cache at the training run's ``xla_cache``
    (``resolve_serving_cache_dir`` — the one additive write serving may
    make under the experiment dir), so a subsequent ``warmup()``
    warm-starts from the training run's compiles instead of paying them
    again. Pass False to leave the process's cache setting untouched.

    The shape/dtype template comes from ``jax.eval_shape`` over
    ``maml.init_state``, so loading allocates nothing beyond the restored
    arrays themselves.
    """
    import jax

    from ..core import maml
    from ..experiment import checkpoint as ckpt

    if enable_cache:
        from ..experiment.system import enable_compilation_cache

        cache_dir = resolve_serving_cache_dir(cfg, model_save_dir)
        if cache_dir:
            enable_compilation_cache(cache_dir)
    template = jax.eval_shape(lambda: maml.init_state(cfg))
    return ckpt.load_checkpoint(
        model_save_dir, model_name, model_idx, template, readonly=True
    )


def _bucket_for(n: int, ladder: Sequence[int]) -> int:
    for b in ladder:
        if n <= b:
            return b
    raise ValueError(
        f"{n} tenants exceed the serving bucket ladder {list(ladder)}; "
        "the batcher must cap groups at serving_max_tenants_per_dispatch"
    )


class ServingEngine:
    """Multi-tenant adapt-on-request inference over one servable snapshot.

    :param cfg: fixes the task geometry (way / query targets / image
        shape) and the serving knobs (``serving_bucket_ladder``,
        ``serving_max_tenants_per_dispatch``, ``serving_ingest``,
        ``serving_adapted_cache_size``, ``serving_export_dir``).
    :param state: the servable ``MetaState`` (host numpy or device
        arrays) — from ``load_servable_snapshot`` or ``maml.init_state``.
    :param shots_buckets: support-shot counts to compile programs for
        (default: the config's ``num_samples_per_class`` only).
    :param sink: optional telemetry sink (``telemetry.sinks.JsonlSink``
        or anything with ``write(record)``); records are built through
        ``make_record`` (schema v9 ``serving`` kind).
    :param strict_retrace: raise ``RetraceError`` on any post-warmup
        recompile (the production default); False records events only.
    :param ingest: override ``cfg.serving_ingest`` for this engine.
    :param store: the registered uint8 image store for the 'index'
        ingest — a ``data.preprocess.FlatStore`` or a raw (N, h, w, c)
        uint8 array; uploaded to HBM ONCE here, then every dispatch
        gathers from it on device.
    :param cache_size: override ``cfg.serving_adapted_cache_size``.
    :param snapshot_id: identity of the served checkpoint for the
        adapted-params cache key (default: a content hash of the state —
        two engines over the same snapshot agree, a new checkpoint
        invalidates every cached tenant by construction).
    :param tracer: a ``telemetry.tracing.Tracer`` — when enabled, every
        dispatch emits ``assemble`` / ``dispatch`` / ``sync`` spans (the
        latency decomposition) plus a ``cache_lookup`` span, all
        host-side perf_counter intervals: tracing never adds a device
        sync and the compiled programs are independent of it by
        construction. Default: the shared disabled tracer.
    :param watchdog: a started ``telemetry.Watchdog`` — beaten once per
        device dispatch, so a wedged serving dispatch produces a
        ``watchdog_stall`` diagnostic instead of a silent hang (see
        ``attach_serving_watchdog``).
    :param profiler: a ``utils.profiling.OnDemandProfiler`` — polled
        once per (non-warmup) dispatch, so an operator can capture a
        ``jax.profiler`` trace of the next N serving dispatches by
        touching the trigger file, with no restart.
    :param device: pin this engine to ONE ``jax.Device`` (the
        multi-replica shape, serving/replica.py: each replica's engine
        owns a disjoint device). The snapshot (and registered store) are
        placed there, and every program is AOT-compiled against that
        device's sharding, so concurrent replicas dispatch onto
        concurrent devices. Default (None) keeps the process-default
        device — the single-engine shape, byte-for-byte unchanged.
    :param replica_id: tag every telemetry record this engine emits
        with a ``replica_id`` (schema v11) so a multi-replica pool's
        merged record stream stays attributable per replica. Default
        (None) omits the field — single-engine logs are unchanged.
    """

    #: latency-sample window for the rollup percentiles (last N
    #: dispatches) — bounds host memory on a long-lived server
    LATENCY_WINDOW = 4096

    def __init__(
        self,
        cfg: MAMLConfig,
        state,
        shots_buckets: Optional[Sequence[int]] = None,
        sink=None,
        strict_retrace: bool = True,
        ingest: Optional[str] = None,
        store=None,
        cache_size: Optional[int] = None,
        snapshot_id: Optional[str] = None,
        tracer: Optional[tracing.Tracer] = None,
        watchdog=None,
        profiler=None,
        device=None,
        replica_id: Optional[int] = None,
    ):
        import jax
        import jax.numpy as jnp

        from ..analysis.auditor import RetraceDetector
        from . import export as export_lib

        # counting XLA compiles is warmup's acceptance surface; install
        # the listener before any serving program can compile
        export_lib.install_compile_counter()
        self.cfg = cfg
        self.device = device
        self.replica_id = replica_id
        self.buckets: Tuple[int, ...] = tuple(cfg.serving_bucket_ladder)
        self.max_tenants: int = cfg.serving_max_tenants_per_dispatch
        self.shots_buckets: Tuple[int, ...] = tuple(
            shots_buckets
            if shots_buckets is not None
            else (cfg.num_samples_per_class,)
        )
        if any(s < 1 for s in self.shots_buckets):
            raise ValueError(
                f"shots buckets must be >= 1, got {self.shots_buckets}"
            )
        self.ingest: str = cfg.serving_ingest if ingest is None else ingest
        if self.ingest not in ("f32", "uint8", "index"):
            raise ValueError(
                f"ingest must be 'f32', 'uint8' or 'index', got "
                f"{self.ingest!r}"
            )
        self.cache_size: int = (
            cfg.serving_adapted_cache_size
            if cache_size is None else int(cache_size)
        )
        if self.cache_size < 0:
            raise ValueError(
                f"cache_size must be >= 0, got {self.cache_size}"
            )
        self.sink = sink
        # the engine OWNS its device snapshot: every dispatch donates the
        # state and re-binds to the (aliased) returned one, so the buffers
        # must be private — ``jnp.array(copy=True)`` (plain device_put is
        # a no-op for an already-committed array and would donate the
        # CALLER's buffers out from under it). A device-pinned engine
        # routes the copy through the host so the private buffers land on
        # ITS device regardless of where the caller's snapshot lives.
        if device is not None:
            self._state = jax.tree_util.tree_map(
                lambda x: jax.device_put(np.array(np.asarray(x)), device),
                state,
            )
        else:
            self._state = jax.tree_util.tree_map(
                lambda x: jnp.array(x, copy=True), state
            )
        # 'index' ingest: the registered store is uploaded ONCE and is a
        # program parameter of every dispatch (never donated — the
        # resident invariant, exactly like the indexed train factories)
        self._store = None
        self._store_rows = 0
        store_fp = ""
        if self.ingest == "index":
            if store is None:
                raise ValueError(
                    "ingest='index' requires a registered store (a "
                    "data.preprocess.FlatStore or a (N, h, w, c) uint8 "
                    "array): index requests reference its rows"
                )
            data = np.asarray(getattr(store, "data", store))
            if data.dtype != np.uint8 or data.shape[1:] != cfg.im_shape:
                raise ValueError(
                    f"registered store must be (N, {cfg.im_shape[0]}, "
                    f"{cfg.im_shape[1]}, {cfg.im_shape[2]}) uint8, got "
                    f"{data.shape} {data.dtype}"
                )
            self._store_rows = int(data.shape[0])
            if self.cache_size > 0:
                # the store content hash is a cache-key component only —
                # never pay a full-store SHA1 when the cache is off
                store_fp = hashlib.sha1(
                    np.ascontiguousarray(data)
                ).hexdigest()
            self._store = (
                jax.device_put(data, device) if device is not None
                else jnp.asarray(data)
            )
        elif store is not None:
            raise ValueError(
                f"a registered store only applies to ingest='index' "
                f"(this engine is ingest={self.ingest!r})"
            )
        self.retrace_detector = RetraceDetector(strict=strict_retrace)
        self.tracer = tracer if tracer is not None else tracing.NULL_TRACER
        self.watchdog = watchdog
        self.profiler = profiler
        # warmup dispatches are compile/prime traffic: excluded from the
        # rollup already, and excluded from spans/profiling so a timeline
        # or an on-demand profile never mistakes the compile bill for
        # steady-state latency
        self._warming = False
        # a dispatch that fails AFTER donation leaves self._state pointing
        # at deleted buffers; the engine marks itself dead with the root
        # cause so later requests fail fast naming it, instead of a
        # stream of unrelated "buffer was donated/deleted" errors
        self._dead: Optional[BaseException] = None
        # AOT program table: (family, bucket, shots) -> compiled
        # executable; filled by warmup() (artifact load or AOT compile),
        # lazily completed for unwarmed points (a first compile at a NEW
        # site is legal; a SECOND signature at one site is the retrace
        # the strict detector kills)
        self._programs: Dict[Tuple[str, int, int], Any] = {}
        self.warmup_stats: Dict[str, Any] = {}
        # adapted-params cache: support-set fingerprint -> host fast
        # weights (the LRU the predict-only program serves hits from)
        self._cache: "OrderedDict[str, Dict[str, np.ndarray]]" = (
            OrderedDict()
        )
        self.cache_hits = 0
        self.cache_misses = 0
        self._cache_salt = b""
        if self.cache_size > 0:
            # the snapshot fingerprint (a full host fetch + SHA1 over the
            # state) is a cache-key component only — skipped when the
            # cache is off, so default engines pay nothing for it
            if snapshot_id is None:
                snapshot_id = self._state_fingerprint()
            self._cache_salt = (
                f"{snapshot_id}|{self.ingest}|{store_fp}|".encode()
            )
        # rollup accumulators (per-dispatch samples, warmup excluded);
        # throughput is measured over the wall-clock SPAN from the first
        # real dispatch's start to the last one's end — summing per-
        # dispatch queue+adapt would double-count queue time that
        # overlaps the previous dispatch's device time under the
        # micro-batcher. Latency samples are a BOUNDED window (the last
        # LATENCY_WINDOW dispatches): a long-lived server must not grow
        # host memory per dispatch, and windowed p50/p95 track current
        # latency instead of a lifetime aggregate.
        self._adapt_ms: Deque[float] = deque(maxlen=self.LATENCY_WINDOW)
        self._queue_ms: Deque[float] = deque(maxlen=self.LATENCY_WINDOW)
        self._h2d_bytes: Deque[int] = deque(maxlen=self.LATENCY_WINDOW)
        # the latency decomposition's per-dispatch samples (schema v10):
        # host batch assembly / device dispatch enqueue / blocking fetch
        self._batch_ms: Deque[float] = deque(maxlen=self.LATENCY_WINDOW)
        self._dispatch_ms: Deque[float] = deque(maxlen=self.LATENCY_WINDOW)
        self._sync_ms: Deque[float] = deque(maxlen=self.LATENCY_WINDOW)
        # log-bucketed histograms over the SAME stage samples — unlike
        # the deques these never drop history (fixed ~129-bucket ladder,
        # O(1) memory regardless of run length), merge exactly across
        # replicas and engine swaps, and back the rollup's hist fields
        # (schema v12). The windowed deques stay for the "current
        # latency" percentiles; window_dropped in the rollup counts what
        # they shed.
        from .metrics import LogHistogram

        self._hist: Dict[str, LogHistogram] = {
            stage: LogHistogram()
            for stage in (
                "adapt_ms", "queue_ms", "batch_ms", "dispatch_ms",
                "sync_ms",
            )
        }
        self._tenants_served = 0
        self._span_start: Optional[float] = None
        self._span_end: Optional[float] = None

    # -- identity ----------------------------------------------------------

    def _state_fingerprint(self) -> str:
        """Content hash of the served snapshot (cache-key component): a
        one-time pass over the state leaves at engine construction."""
        import jax

        h = hashlib.sha1()
        for leaf in jax.tree_util.tree_leaves(self._state):
            arr = np.ascontiguousarray(np.asarray(leaf))
            h.update(str(arr.shape).encode())
            h.update(str(arr.dtype).encode())
            h.update(arr)
        return h.hexdigest()

    # -- shapes ------------------------------------------------------------

    @property
    def _pixel_dtype(self):
        return np.uint8 if self.ingest == "uint8" else np.float32

    def _zeros_batch(self, bucket: int, shots: int):
        n = self.cfg.num_classes_per_set
        t = self.cfg.num_target_samples
        h, w, c = self.cfg.im_shape
        return (
            np.zeros((bucket, n, shots, h, w, c), self._pixel_dtype),
            np.zeros((bucket, n, shots), np.int32),
            np.zeros((bucket, n, t, h, w, c), self._pixel_dtype),
            np.zeros((bucket, n, t), np.int32),
        )

    def _fast_template(self) -> Dict[str, Any]:
        """Shapes/dtypes of ONE tenant's fast weights (the adapted subset
        of ``state.net`` — ``core.partition.split_inner``)."""
        from ..core import partition

        adapted, _ = partition.split_inner(self.cfg, self._state.net)
        return {
            k: (tuple(v.shape), np.dtype(v.dtype)) for k, v in adapted.items()
        }

    def _validate(self, req) -> int:
        """Check one request against the engine geometry + ingest tier;
        returns its shots count."""
        n = self.cfg.num_classes_per_set
        t = self.cfg.num_target_samples
        if self.ingest == "index":
            return self._validate_index(req, n, t)
        h, w, c = self.cfg.im_shape
        sx = np.asarray(req.support_x)
        if sx.ndim != 5 or sx.shape[0] != n or sx.shape[2:] != (h, w, c):
            raise ValueError(
                f"support_x must be ({n}, shots, {h}, {w}, {c}), got "
                f"{sx.shape}"
            )
        qx = np.asarray(req.query_x)
        if self.ingest == "uint8" and not (
            sx.dtype == np.uint8 and qx.dtype == np.uint8
        ):
            # silent float->uint8 casting would corrupt pixels; the uint8
            # tier's contract is RAW ENCODED pixels, decoded on device
            raise ValueError(
                f"ingest='uint8' requires uint8 support_x/query_x, got "
                f"{sx.dtype}/{qx.dtype}"
            )
        shots = int(sx.shape[1])
        if shots not in self.shots_buckets:
            raise ValueError(
                f"request shots={shots} not in the engine's shots buckets "
                f"{self.shots_buckets} (shots are never padded — they "
                "enter the adaptation loss)"
            )
        if tuple(np.asarray(req.support_y).shape) != (n, shots):
            raise ValueError(
                f"support_y must be ({n}, {shots}), got "
                f"{np.asarray(req.support_y).shape}"
            )
        if qx.shape != (n, t, h, w, c):
            raise ValueError(
                f"query_x must be ({n}, {t}, {h}, {w}, {c}), got {qx.shape}"
            )
        if req.query_y is not None and tuple(
            np.asarray(req.query_y).shape
        ) != (n, t):
            raise ValueError(
                f"query_y must be ({n}, {t}) or None, got "
                f"{np.asarray(req.query_y).shape}"
            )
        return shots

    def _validate_index(self, req, n: int, t: int) -> int:
        si = np.asarray(getattr(req, "support_idx", None))
        qi = np.asarray(getattr(req, "query_idx", None))
        if si.dtype == object or si.ndim != 2 or si.shape[0] != n:
            raise ValueError(
                f"ingest='index' requires IndexRequest support_idx of "
                f"shape ({n}, shots), got {getattr(req, 'support_idx', None)!r}"
            )
        shots = int(si.shape[1])
        if shots not in self.shots_buckets:
            raise ValueError(
                f"request shots={shots} not in the engine's shots buckets "
                f"{self.shots_buckets} (shots are never padded — they "
                "enter the adaptation loss)"
            )
        if qi.dtype == object or qi.shape != (n, t):
            raise ValueError(
                f"query_idx must be ({n}, {t}), got "
                f"{getattr(req, 'query_idx', None)!r}"
            )
        for name, arr in (("support_idx", si), ("query_idx", qi)):
            if not np.issubdtype(arr.dtype, np.integer):
                raise ValueError(f"{name} must be integer store rows")
            if arr.size and (
                int(arr.min()) < 0 or int(arr.max()) >= self._store_rows
            ):
                raise ValueError(
                    f"{name} rows out of range [0, {self._store_rows}) "
                    f"for the registered store"
                )
        return shots

    # -- program table -----------------------------------------------------

    def _site(self, family: str, bucket: int, shots: int) -> str:
        if family == "predict":
            return f"predict_step[i={self.ingest},b={bucket}]"
        return f"serve_step[i={self.ingest},b={bucket},s={shots}]"

    def _abstract(self, tree):
        import jax

        if self.device is not None:
            # a device-pinned engine AOT-compiles against ITS device:
            # the sharding on the abstract args is what targets the
            # executable (uncommitted numpy dispatch args then follow
            # the executable's device, committed state/store must match)
            sharding = jax.sharding.SingleDeviceSharding(self.device)
            return jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(
                    tuple(x.shape), x.dtype, sharding=sharding
                ),
                tree,
            )
        return jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(tuple(x.shape), x.dtype), tree
        )

    def _program_spec(self, family: str, bucket: int, shots: int):
        """(traceable fn, donate argnums, abstract args) for one program
        table entry — the single source of the serving program family."""
        import jax

        from ..core import maml

        n = self.cfg.num_classes_per_set
        t = self.cfg.num_target_samples
        cache_on = self.cache_size > 0
        state_sds = self._abstract(self._state)
        if family == "adapt":
            if self.ingest == "index":
                fn = maml.make_serve_step_indexed(
                    self.cfg, shots, return_adapted=cache_on
                )
                args = (
                    state_sds,
                    self._abstract(self._store),
                    jax.ShapeDtypeStruct((bucket, n, shots + t), np.int32),
                    jax.ShapeDtypeStruct((bucket,), np.float32),
                )
            else:
                fn = maml.make_serve_step(
                    self.cfg, self.ingest, return_adapted=cache_on
                )
                args = (
                    state_sds,
                    *self._abstract(self._zeros_batch(bucket, shots)),
                    jax.ShapeDtypeStruct((bucket,), np.float32),
                )
            return fn, maml.SERVE_DONATE, args
        fast_sds = {
            k: jax.ShapeDtypeStruct((bucket,) + shape, dtype)
            for k, (shape, dtype) in self._fast_template().items()
        }
        if self.ingest == "index":
            fn = maml.make_predict_step_indexed(self.cfg)
            args = (
                state_sds,
                fast_sds,
                self._abstract(self._store),
                jax.ShapeDtypeStruct((bucket, n, t), np.int32),
                jax.ShapeDtypeStruct((bucket,), np.float32),
            )
        else:
            h, w, c = self.cfg.im_shape
            fn = maml.make_predict_step(self.cfg, self.ingest)
            args = (
                state_sds,
                fast_sds,
                jax.ShapeDtypeStruct(
                    (bucket, n, t, h, w, c), self._pixel_dtype
                ),
                jax.ShapeDtypeStruct((bucket, n, t), np.int32),
                jax.ShapeDtypeStruct((bucket,), np.float32),
            )
        return fn, maml.PREDICT_DONATE, args

    def _build_program(self, family: str, bucket: int, shots: int):
        import jax

        fn, donate, args = self._program_spec(family, bucket, shots)
        return jax.jit(fn, donate_argnums=donate).lower(*args).compile()

    def _program(self, family: str, bucket: int, shots: int):
        key = (family, bucket, shots)
        prog = self._programs.get(key)
        if prog is None:
            prog = self._build_program(family, bucket, shots)
            self._programs[key] = prog
        return prog

    def _program_names(self) -> Dict[str, Tuple[str, int, int]]:
        """Artifact name -> program-table key, for every program this
        engine can dispatch (the export/warmup ladder)."""
        names: Dict[str, Tuple[str, int, int]] = {}
        for shots in self.shots_buckets:
            for bucket in self.buckets:
                names[f"adapt_b{bucket}_s{shots}"] = ("adapt", bucket, shots)
        if self.cache_size > 0:
            for bucket in self.buckets:
                names[f"predict_b{bucket}"] = ("predict", bucket, 0)
        return names

    # -- warmup ------------------------------------------------------------

    def warmup(self, artifact_dir: Optional[str] = None) -> float:
        """Materialize (and run once, on zeros) every serving program.

        Returns the wall seconds spent — the whole compile bill of the
        engine: after this, steady-state traffic of ANY mix of bucket
        sizes and configured shots dispatches with zero retraces (the
        strict detector enforces it).

        ``artifact_dir`` (default: ``cfg.serving_export_dir``) switches
        warmup to the AOT-artifact path: previously exported executables
        (``serving/export.py`` / ``cli serve-export``) are DESERIALIZED —
        zero XLA compilations — and any mismatch (device kind, dtype,
        config fingerprint, jax version, ladder, ingest, cache flag,
        index-store rows) falls back to compile-then-save, so the next
        start loads. ``warmup_stats`` records the outcome: ``mode``
        ('artifacts' | 'compile'), ``seconds``, ``xla_compiles`` (the
        process-wide backend-compile delta — 0 on the artifact path) and
        ``programs``; a telemetry event='warmup' record mirrors it.
        With a persistent compilation cache enabled the compile path
        itself warm-starts from disk.
        """
        from . import export as export_lib

        if artifact_dir is None:
            artifact_dir = self.cfg.serving_export_dir or None
        start = time.perf_counter()
        compiles0 = export_lib.xla_compile_count()
        self._warming = True
        cache_on = self.cache_size > 0
        names = self._program_names()
        extra: Dict[str, Any] = {}
        if self.ingest == "index":
            extra["store_rows"] = self._store_rows
        if self.device is not None:
            # serialized executables record their device assignment by
            # id; a device-pinned engine must only deserialize artifacts
            # written for ITS device (replicas keep per-replica artifact
            # roots — serving/replica.py), never another replica's
            extra["device_id"] = int(self.device.id)
        extra = extra or None
        mode = "compile"
        if artifact_dir:
            loaded = export_lib.load_artifacts(
                self.cfg, artifact_dir, self.ingest, cache_on,
                self.buckets, self.shots_buckets, extra,
            )
            if loaded is not None and set(loaded) >= set(names):
                for name, key in names.items():
                    self._programs[key] = loaded[name]
                mode = "artifacts"
        if mode == "compile":
            for key in names.values():
                self._program(*key)
            if artifact_dir:
                export_lib.save_artifacts(
                    self.cfg, artifact_dir, self.ingest, cache_on,
                    self.buckets, self.shots_buckets,
                    {name: self._programs[key]
                     for name, key in names.items()},
                    extra,
                )
        # execute each program once on zeros: proves it dispatches, warms
        # the allocator, and primes the retrace detector's sites
        for shots in self.shots_buckets:
            for bucket in self.buckets:
                x_s, y_s, x_t, y_t = self._zeros_batch(bucket, shots)
                valid = np.zeros(bucket, np.float32)
                if self.ingest == "index":
                    n = self.cfg.num_classes_per_set
                    t = self.cfg.num_target_samples
                    gather = np.zeros((bucket, n, shots + t), np.int32)
                    args = (self._state, self._store, gather, valid)
                else:
                    args = (self._state, x_s, y_s, x_t, y_t, valid)
                self._raw_dispatch("adapt", bucket, shots, args)
        if cache_on:
            for bucket in self.buckets:
                self._raw_dispatch(
                    "predict", bucket, 0,
                    self._predict_args([], [], bucket),
                )
        self._warming = False
        seconds = time.perf_counter() - start
        self.warmup_stats = {
            "mode": mode,
            "seconds": round(seconds, 3),
            "xla_compiles": export_lib.xla_compile_count() - compiles0,
            "programs": len(names),
        }
        self._record(
            event="warmup", mode=mode,
            warmup_ms=round(seconds * 1e3, 3),
            xla_compiles=self.warmup_stats["xla_compiles"],
            programs=len(names), ingest=self.ingest,
        )
        return seconds

    # -- dispatch ----------------------------------------------------------

    def _raw_dispatch(self, family: str, bucket: int, shots: int, args):
        """One device dispatch; returns ``(out, adapt_ms, dispatch_ms,
        sync_ms)``. ``adapt_ms`` is enqueue-to-host-fetch: it includes
        the H2D upload and the result readback — the latency a caller
        actually observes; ``dispatch_ms`` is the asynchronous enqueue
        (program call return), ``sync_ms`` the host-blocking fetch of
        every output — the two halves sum to ``adapt_ms``, which is what
        makes the serving latency decomposition add up.

        A failure in here (device error, OOM, interrupt mid-readback) is
        TERMINAL for the engine: the dispatch may already have consumed
        the donated state buffers, so the engine marks itself dead with
        the root cause and every later call raises it — never a stream
        of unrelated donated-buffer errors masking the real failure.
        """
        if self._dead is not None:
            raise RuntimeError(
                "ServingEngine is dead: a previous dispatch failed after "
                "the state was donated (root cause chained below); build "
                "a fresh engine from the snapshot"
            ) from self._dead
        site = self._site(family, bucket, shots)
        if self.watchdog is not None:
            # one beat per dispatch: a wedged dispatch stalls the beat
            # stream and the watchdog names this site in its diagnostic
            self.watchdog.beat(site)
        if self.profiler is not None and not self._warming:
            # on-demand device profiling: the trigger file / SIGUSR2 arms
            # a jax.profiler window over the next N dispatches
            self.profiler.step()
        prog = self._program(family, bucket, shots)
        self.retrace_detector.observe(site, args)
        tracer = self.tracer if not self._warming else tracing.NULL_TRACER
        span_attrs = {"program": family, "bucket": bucket, "shots": shots}
        start = time.perf_counter()
        try:
            new_state, out = prog(*args)
            enqueued = time.perf_counter()
            # host-fetch every output the caller reads: the one sync that
            # provably blocks on every backend (see bench.py's sync note)
            fetched = {
                "preds": np.asarray(out["preds"]),
                "loss": np.asarray(out["loss"]),
                "accuracy": np.asarray(out["accuracy"]),
                "metrics": {
                    k: float(np.asarray(v))
                    for k, v in out["metrics"].items()
                },
            }
            if "adapted" in out:
                fetched["adapted"] = {
                    k: np.asarray(v) for k, v in out["adapted"].items()
                }
        except BaseException as e:
            self._dead = e
            raise
        end = time.perf_counter()
        adapt_ms = (end - start) * 1e3
        dispatch_ms = (enqueued - start) * 1e3
        sync_ms = (end - enqueued) * 1e3
        if tracer.enabled:
            # emit the dispatch/sync spans from the stamps, AFTER the
            # timed interval: the span records' own serialization and
            # sink write must never inflate the decomposition (or the
            # SLO adapt_ms) they exist to report
            sp = tracer.start_span("dispatch", cat="serving",
                                   start_ms=start * 1e3, **span_attrs)
            tracer.end_span(sp, end_ms=enqueued * 1e3)
            sp = tracer.start_span("sync", cat="serving",
                                   start_ms=enqueued * 1e3, **span_attrs)
            tracer.end_span(sp, end_ms=end * 1e3)
        # re-bind: the old state buffers were donated to (and alias) the
        # returned state — the previous reference is dead
        self._state = new_state
        return fetched, adapt_ms, dispatch_ms, sync_ms

    def _adapt_args(self, requests, bucket: int, shots: int):
        """Assemble one adapt dispatch's args for this ingest tier."""
        n = self.cfg.num_classes_per_set
        t = self.cfg.num_target_samples
        valid = np.zeros(bucket, np.float32)
        if self.ingest == "index":
            gather = np.zeros((bucket, n, shots + t), np.int32)
            for i, req in enumerate(requests):
                gather[i, :, :shots] = np.asarray(req.support_idx, np.int32)
                gather[i, :, shots:] = np.asarray(req.query_idx, np.int32)
                if req.labeled:
                    valid[i] = 1.0
            return (self._state, self._store, gather, valid)
        dtype = self._pixel_dtype
        x_s, y_s, x_t, y_t = self._zeros_batch(bucket, shots)
        for i, req in enumerate(requests):
            x_s[i] = np.asarray(req.support_x, dtype)
            y_s[i] = np.asarray(req.support_y, np.int32)
            x_t[i] = np.asarray(req.query_x, dtype)
            if req.query_y is not None:
                y_t[i] = np.asarray(req.query_y, np.int32)
                # the metric mask admits LABELED tenants only: a
                # label-free tenant's y_t slot is fabricated zeros, and
                # scoring it would poison the aggregate (its predictions
                # don't read labels and are unaffected)
                valid[i] = 1.0
        return (self._state, x_s, y_s, x_t, y_t, valid)

    def _predict_args(self, fasts, requests, bucket: int):
        """Assemble one predict (cache-hit) dispatch's args; ``fasts`` is
        the per-tenant cached fast-weight list aligned with ``requests``
        (both may be empty: warmup's zeros dispatch)."""
        n = self.cfg.num_classes_per_set
        t = self.cfg.num_target_samples
        template = self._fast_template()
        fast = {
            k: np.zeros((bucket,) + shape, dtype)
            for k, (shape, dtype) in template.items()
        }
        for i, fw in enumerate(fasts):
            for k in fast:
                fast[k][i] = fw[k]
        valid = np.zeros(bucket, np.float32)
        if self.ingest == "index":
            gather = np.zeros((bucket, n, t), np.int32)
            for i, req in enumerate(requests):
                gather[i] = np.asarray(req.query_idx, np.int32)
                if req.labeled:
                    valid[i] = 1.0
            return (self._state, fast, self._store, gather, valid)
        h, w, c = self.cfg.im_shape
        x_t = np.zeros((bucket, n, t, h, w, c), self._pixel_dtype)
        y_t = np.zeros((bucket, n, t), np.int32)
        for i, req in enumerate(requests):
            x_t[i] = np.asarray(req.query_x, self._pixel_dtype)
            if req.query_y is not None:
                y_t[i] = np.asarray(req.query_y, np.int32)
                valid[i] = 1.0
        return (self._state, fast, x_t, y_t, valid)

    @staticmethod
    def _args_h2d_bytes(args) -> int:
        """Actual H2D payload of a dispatch: every HOST (numpy) argument
        uploads; device-resident args (the donated state, the registered
        store) do not."""
        total = 0
        import jax

        for leaf in jax.tree_util.tree_leaves(args):
            if isinstance(leaf, np.ndarray):
                total += int(leaf.nbytes)
        return total

    def _labeled_of(self, req) -> bool:
        if self.ingest == "index":
            return bool(req.labeled)
        return req.query_y is not None

    # -- the adapted-params cache ------------------------------------------

    def _cache_key(self, req, shots: int) -> str:
        """Tenant support-set fingerprint: content hash + shots +
        snapshot id (the salt). A changed support set, shots count,
        checkpoint, ingest tier or registered store produces a different
        key by construction. The support-content recipe is SHARED with
        the router's affinity fingerprint (``update_support_digest``) —
        affinity routing only preserves pool hit rates while the two
        identities match, so they hash the same bytes by construction."""
        from .batcher import update_support_digest

        h = hashlib.sha1(self._cache_salt)
        h.update(str(shots).encode())
        update_support_digest(h, req)
        return h.hexdigest()

    def _cache_insert(self, key: str, fast: Dict[str, np.ndarray]) -> None:
        self._cache[key] = fast
        self._cache.move_to_end(key)
        while len(self._cache) > self.cache_size:
            self._cache.popitem(last=False)

    # -- serving -----------------------------------------------------------

    def serve_group(self, requests: Sequence[Any],
                    queue_ms: float = 0.0) -> DispatchResult:
        """Serve one group of same-shots requests.

        The group must fit ``serving_max_tenants_per_dispatch`` (the
        batcher's job); pad tenants up to the bucket are zeros, masked
        out of the aggregate metrics and — by vmap independence —
        incapable of touching real tenants' outputs.

        With the adapted-params cache on, the group splits into cache
        MISSES (full adapt dispatch, whose per-tenant fast weights are
        inserted into the LRU) and HITS (predict-only dispatch over the
        cached fast weights — no inner loop); results come back aligned
        with the input order regardless of the split.
        """
        if not requests:
            raise ValueError("serve_group needs at least one request")
        if len(requests) > self.max_tenants:
            raise ValueError(
                f"{len(requests)} requests exceed "
                f"serving_max_tenants_per_dispatch={self.max_tenants}"
            )
        shots_set = {self._validate(r) for r in requests}
        if len(shots_set) != 1:
            raise ValueError(
                f"one dispatch must carry one shots bucket, got {shots_set}"
            )
        shots = shots_set.pop()
        n_real = len(requests)
        cache_on = self.cache_size > 0
        keys: List[Optional[str]] = [None] * n_real
        hit_idx: List[int] = []
        hit_fasts: List[Dict[str, np.ndarray]] = []
        miss_idx: List[int] = list(range(n_real))
        if cache_on:
            with self.tracer.span(
                "cache_lookup", cat="serving", shots=shots, tenants=n_real,
            ):
                keys = [self._cache_key(r, shots) for r in requests]
                hit_idx, miss_idx = [], []
                for i, key in enumerate(keys):
                    if key in self._cache:
                        self._cache.move_to_end(key)
                        hit_idx.append(i)
                        # snapshot the fast weights NOW: inserting this
                        # group's misses below may evict the hit entries
                        # from a small LRU before the predict dispatch
                        # reads them (entries are immutable once
                        # inserted, so the reference stays valid past
                        # eviction)
                        hit_fasts.append(self._cache[key])
                    else:
                        miss_idx.append(i)
            self.cache_hits += len(hit_idx)
            self.cache_misses += len(miss_idx)
        if self._span_start is None:
            self._span_start = time.perf_counter()
        results: List[Optional[TenantResult]] = [None] * n_real
        total_ms = 0.0
        total_h2d = 0
        total_batch_ms = 0.0
        total_dispatch_ms = 0.0
        total_sync_ms = 0.0
        metric_parts: List[Tuple[Dict[str, float], int]] = []
        bucket: Optional[int] = None

        def _assemble(program, dispatch_bucket, fn):
            """Time (and span) one dispatch's host batch assembly."""
            with self.tracer.span(
                "assemble", cat="serving", program=program,
                bucket=dispatch_bucket, shots=shots,
            ):
                t0 = time.perf_counter()
                args = fn()
                return args, (time.perf_counter() - t0) * 1e3

        def _fill(idxs, out, timings, args, program, dispatch_bucket,
                  batch_ms):
            nonlocal total_ms, total_h2d, bucket
            nonlocal total_batch_ms, total_dispatch_ms, total_sync_ms
            adapt_ms, dispatch_ms, sync_ms = timings
            h2d = self._args_h2d_bytes(args)
            total_ms += adapt_ms
            total_h2d += h2d
            total_batch_ms += batch_ms
            total_dispatch_ms += dispatch_ms
            total_sync_ms += sync_ms
            if bucket is None or program == "adapt":
                bucket = dispatch_bucket
            labeled_count = 0
            with self.tracer.span(
                "realign", cat="serving", program=program,
                bucket=dispatch_bucket, shots=shots,
            ):
                for j, i in enumerate(idxs):
                    req = requests[i]
                    lab = self._labeled_of(req)
                    labeled_count += int(lab)
                    results[i] = TenantResult(
                        tenant_id=getattr(req, "tenant_id", None),
                        preds=out["preds"][j],
                        loss=float(out["loss"][j]) if lab else None,
                        accuracy=float(out["accuracy"][j]) if lab else None,
                    )
            metric_parts.append((out["metrics"], labeled_count))
            self._adapt_ms.append(adapt_ms)
            self._h2d_bytes.append(h2d)
            self._batch_ms.append(batch_ms)
            self._dispatch_ms.append(dispatch_ms)
            self._sync_ms.append(sync_ms)
            self._hist["adapt_ms"].observe(adapt_ms)
            self._hist["batch_ms"].observe(batch_ms)
            self._hist["dispatch_ms"].observe(dispatch_ms)
            self._hist["sync_ms"].observe(sync_ms)
            fields = dict(
                event="dispatch", tenants=len(idxs),
                bucket=dispatch_bucket, shots=shots,
                queue_ms=round(float(queue_ms), 3),
                adapt_ms=round(adapt_ms, 3), program=program,
                ingest=self.ingest, ingest_bytes=h2d,
                batch_ms=round(batch_ms, 3),
                dispatch_ms=round(dispatch_ms, 3),
                sync_ms=round(sync_ms, 3),
            )
            if self.cache_size > 0:
                # present only when the cache exists, so downstream
                # hit-rate quotients (metrics endpoint) agree with the
                # rollup's cache_hit_rate=None on cache-less engines
                fields["cache_hits"] = (
                    len(idxs) if program == "predict" else 0
                )
            self._record(**fields)

        if miss_idx:
            group = [requests[i] for i in miss_idx]
            b = _bucket_for(len(group), self.buckets)
            args, batch_ms = _assemble(
                "adapt", b, lambda: self._adapt_args(group, b, shots)
            )
            out, *timings = self._raw_dispatch("adapt", b, shots, args)
            if cache_on and "adapted" in out:
                for j, i in enumerate(miss_idx):
                    self._cache_insert(
                        keys[i],
                        {k: np.array(v[j])
                         for k, v in out["adapted"].items()},
                    )
            _fill(miss_idx, out, timings, args, "adapt", b, batch_ms)
        if hit_idx:
            group = [requests[i] for i in hit_idx]
            b = _bucket_for(len(group), self.buckets)
            args, batch_ms = _assemble(
                "predict", b,
                lambda: self._predict_args(hit_fasts, group, b),
            )
            out, *timings = self._raw_dispatch("predict", b, 0, args)
            _fill(hit_idx, out, timings, args, "predict", b, batch_ms)
        self._span_end = time.perf_counter()
        self._queue_ms.append(float(queue_ms))
        self._hist["queue_ms"].observe(float(queue_ms))
        self._tenants_served += n_real
        # combine the per-dispatch masked means, weighted by how many
        # LABELED tenants each dispatch carried (each mean is already
        # over its labeled tenants only)
        total_labeled = sum(nlab for _, nlab in metric_parts)
        if total_labeled:
            metrics = {
                key: sum(m[key] * nlab for m, nlab in metric_parts)
                / total_labeled
                for key in ("loss", "accuracy")
            }
        else:
            metrics = {"loss": 0.0, "accuracy": 0.0}
        return DispatchResult(
            results=results, tenants=n_real,
            bucket=int(bucket), shots=shots,
            queue_ms=float(queue_ms), adapt_ms=total_ms,
            metrics=metrics, cache_hits=len(hit_idx),
            ingest_bytes=total_h2d,
            batch_ms=total_batch_ms,
            dispatch_ms=total_dispatch_ms,
            sync_ms=total_sync_ms,
        )

    # -- telemetry ---------------------------------------------------------

    def _record(self, **fields) -> None:
        if self.sink is None:
            return
        from ..telemetry.sinks import make_record

        if self.replica_id is not None:
            # schema v11: a pooled engine tags its records so the merged
            # stream stays attributable per replica (single-engine logs
            # are unchanged — the field is simply absent)
            fields.setdefault("replica_id", self.replica_id)
        self.sink.write(make_record("serving", **fields))

    def adopt_serving_history(self, old) -> None:
        """Carry a retired engine's serving-history counters into this
        one (the checkpoint-rollover swap, serving/replica.py): the
        per-replica rollup describes the REPLICA's serving history, so
        tenants served, the latency windows, the cache hit/miss
        counters and the wall-clock span must survive an engine swap
        instead of resetting with each snapshot — without it a
        mid-load rollover silently discards every pre-swap dispatch
        from the bench line. Called under the replica's swap lock
        (both engines quiescent)."""
        self._tenants_served += old._tenants_served
        for name in ("_adapt_ms", "_queue_ms", "_h2d_bytes",
                     "_batch_ms", "_dispatch_ms", "_sync_ms"):
            dst = getattr(self, name)
            merged = list(getattr(old, name)) + list(dst)
            dst.clear()
            dst.extend(merged)  # deque maxlen keeps the window honest
        # the log-bucketed histograms merge EXACTLY (no window, no
        # truncation): the pool rollup's distribution survives the swap
        # sample-for-sample, which is what makes pool-hist == merge of
        # replica-hists hold across a mid-run rollover
        for stage, hist in self._hist.items():
            hist.merge(old._hist[stage])
        self.cache_hits += old.cache_hits
        self.cache_misses += old.cache_misses
        # the retrace history survives too: a pre-swap retrace must not
        # vanish from the rollup's 'retraces == 0 in any healthy run'
        # surface just because the snapshot rolled
        self.retrace_detector.events.extend(
            old.retrace_detector.events
        )
        if old._span_start is not None and (
            self._span_start is None
            or old._span_start < self._span_start
        ):
            self._span_start = old._span_start
        if old._span_end is not None and (
            self._span_end is None or old._span_end > self._span_end
        ):
            self._span_end = old._span_end

    def rollup(self) -> Dict[str, Any]:
        """Latency/throughput rollup; emits the event='rollup' telemetry
        record when a sink is attached. Percentiles cover the last
        ``LATENCY_WINDOW`` (non-warmup) dispatches (current latency, not
        a lifetime aggregate); ``tenants_per_sec`` is lifetime tenants
        over the wall-clock span from the first dispatch's start to the
        last one's end — the closed-loop number, and the ONE definition
        of this metric (serve-bench and bench.py report it verbatim); an
        open-loop server's throughput is additionally bounded by arrival
        rate. ``h2d_bytes_per_dispatch`` is the windowed mean of actual
        uploaded bytes (the ingest tier's acceptance metric);
        ``cache_hit_rate`` is lifetime hits over lookups (None when the
        adapted-params cache is off)."""
        adapt = np.asarray(self._adapt_ms, np.float64)
        queue = np.asarray(self._queue_ms, np.float64)
        h2d = np.asarray(self._h2d_bytes, np.float64)
        batch = np.asarray(self._batch_ms, np.float64)
        disp = np.asarray(self._dispatch_ms, np.float64)
        syncs = np.asarray(self._sync_ms, np.float64)
        span_s = (
            self._span_end - self._span_start
            if self._span_start is not None and self._span_end is not None
            else 0.0
        )
        lookups = self.cache_hits + self.cache_misses
        out: Dict[str, Any] = {
            "dispatches": int(adapt.size),
            "tenants": int(self._tenants_served),
            "retraces": int(self.retrace_detector.retrace_count),
            "ingest": self.ingest,
            "adapt_ms_p50": (
                round(float(np.percentile(adapt, 50)), 3) if adapt.size
                else None
            ),
            "adapt_ms_p95": (
                round(float(np.percentile(adapt, 95)), 3) if adapt.size
                else None
            ),
            "queue_ms_p50": (
                round(float(np.percentile(queue, 50)), 3) if queue.size
                else None
            ),
            # the latency decomposition (schema v10): with queue_ms these
            # account for a request's whole end-to-end latency —
            # queue + batch + dispatch + sync ≈ e2e (tested within
            # tolerance); adapt_ms == dispatch_ms + sync_ms exactly
            "batch_ms_mean": (
                round(float(np.mean(batch)), 3) if batch.size else None
            ),
            "dispatch_ms_p50": (
                round(float(np.percentile(disp, 50)), 3) if disp.size
                else None
            ),
            "sync_ms_p50": (
                round(float(np.percentile(syncs, 50)), 3) if syncs.size
                else None
            ),
            "tenants_per_sec": (
                round(self._tenants_served / span_s, 3)
                if span_s > 0
                else None
            ),
            "h2d_bytes_per_dispatch": (
                round(float(np.mean(h2d)), 1) if h2d.size else None
            ),
            "cache_hit_rate": (
                round(self.cache_hits / lookups, 4)
                if self.cache_size > 0 and lookups else None
            ),
            # rollup honesty (schema v12): how many dispatch samples the
            # bounded percentile window has shed — 0 means the windowed
            # p50/p95 above cover the whole run, > 0 means they describe
            # only the last LATENCY_WINDOW dispatches
            "window_dropped": max(
                0, self._hist["adapt_ms"].count - len(self._adapt_ms)
            ),
            # the full-history log-bucketed distributions (sparse bucket
            # counts; serving/metrics.py LogHistogram.to_dict) — the
            # mergeable, never-truncated complement to the windowed
            # percentiles, and what the jax-free `cli slo`/inspect path
            # recomputes quantiles from offline
            "adapt_ms_hist": self._hist["adapt_ms"].to_dict(),
            "queue_ms_hist": self._hist["queue_ms"].to_dict(),
        }
        self._record(event="rollup", **out)
        return out


def attach_serving_watchdog(engine: "ServingEngine", timeout_s: float,
                            sink=None, recorder=None,
                            replica_id: Optional[int] = None):
    """Wire the hang ``Watchdog`` to a serving engine and start it.

    The engine beats the watchdog once per device dispatch
    (``_raw_dispatch``); when a dispatch wedges — a stuck collective, a
    hung device transport — the stall produces the SAME forensic surface
    a wedged train loop gets: one loud stderr line, a schema-valid
    ``watchdog_stall`` telemetry record (into ``sink``, when given,
    carrying the stage = the wedged dispatch site, all-thread stacks and
    the flight-recorder tail) and a flight-recorder incident directory
    (``recorder``, when given) surfaced as an ``incident`` record.
    ``replica_id`` (the pooled shape, ``ReplicaSet.attach_watchdogs``)
    tags the stall and incident records so a fleet's merged stream
    attributes the stall to the wedged replica; default (None) keeps
    single-engine records unchanged. Returns the STARTED watchdog;
    callers own ``stop()``.
    """
    import sys as _sys

    from ..telemetry.sinks import make_record
    from ..telemetry.watchdog import Watchdog

    if replica_id is None:
        replica_id = getattr(engine, "replica_id", None)

    def on_stall(record):
        tag = "" if replica_id is None else f" replica={replica_id}"
        print(
            f"[serving-watchdog{tag}] no dispatch progress for "
            f"{record['seconds_since_progress']:.1f}s "
            f"(stage={record['stage']!r}, beats={record['beat_count']})",
            file=_sys.stderr,
            flush=True,
        )
        context = {}
        if replica_id is not None:
            context["replica_id"] = replica_id
        if recorder is not None:
            context["recorder_tail"] = recorder.snapshot()[-8:]
        if sink is not None:
            sink.write(make_record("watchdog_stall", **record, **context))
        if recorder is not None:
            try:
                path = recorder.dump(
                    "watchdog_stall",
                    0,  # serving has no train iteration counter
                    details={
                        "stage": record["stage"],
                        "seconds_since_progress":
                            record["seconds_since_progress"],
                        "beat_count": record["beat_count"],
                    },
                    state_dump_fn=None,
                    force=True,
                )
            except Exception as e:  # noqa: BLE001 - forensics must never
                # kill the serving process they document
                print(f"[serving-watchdog] ring dump failed: {e!r}",
                      file=_sys.stderr, flush=True)
                path = None
            if path is not None and sink is not None:
                incident = {
                    "iter": 0, "reason": "watchdog_stall", "path": path,
                }
                if replica_id is not None:
                    incident["replica_id"] = replica_id
                sink.write(make_record("incident", **incident))

    watchdog = Watchdog(timeout_s, on_stall=on_stall)
    engine.watchdog = watchdog
    watchdog.start()
    return watchdog


def resolve_serving_cache_dir(cfg: MAMLConfig,
                              model_save_dir: str) -> Optional[str]:
    """The persistent-compilation-cache directory a serving process should
    warm-start from: an explicit ``compilation_cache_dir`` wins; 'auto'
    resolves to the training experiment's ``xla_cache`` SIBLING of the
    checkpoint directory (the same resolution the experiment builder
    makes); '' disables. The cache is content-addressed and additive —
    the one write a serving process may make under the experiment dir.
    """
    if cfg.compilation_cache_dir == "":
        return None
    if cfg.compilation_cache_dir != "auto":
        return cfg.compilation_cache_dir
    return os.path.join(
        os.path.dirname(os.path.abspath(model_save_dir)), "xla_cache"
    )
