"""Artifact prefetch / refresh daemon: zero-downtime checkpoint rollover.

A long-lived serving pool must follow the training run it serves: when
the experiment writes a new checkpoint, the pool has to move to it
WITHOUT dropping requests and WITHOUT paying XLA compiles on the hot
path. The refresh daemon is that lifecycle:

1. **watch** — poll the experiment checkpoint dir
   (``peek_experiment_state``: the iter is readable without paying a
   restore; the checkpoint swap itself is atomic, so a mid-write poll
   sees either the old or the new snapshot, never a torn one) every
   ``serving_rollover_poll_s``;
2. **prefetch + pre-warm** — on a new snapshot, restore it READ-ONLY
   (``load_servable_snapshot``) and, one replica at a time, build a
   STANDBY engine on that replica's device slice and warm it off the
   hot path — compile, or (with a pool ``export_root``) deserialize the
   replica's existing AOT artifacts: the serving programs depend on
   shapes, never on snapshot values, so the artifact fingerprint is
   REUSED across rollovers and the standby warms with zero XLA
   compiles;
3. **swap** — ``Replica.swap_engine``: a pointer exchange under the
   replica's dispatch lock. In-flight dispatches complete on the old
   snapshot, queued requests flow onto the new one — zero dropped
   requests — and the swap performs zero XLA compiles (the compile-
   counter delta rides the swap stats and the ``rollover`` telemetry
   record). Replicas swap one at a time, so the pool never loses more
   than one replica's worth of standby headroom and always has every
   replica serving.

The adapted-params cache invalidates for free: its key embeds the
snapshot content hash, so a genuinely-new checkpoint misses every old
entry (and an identical re-save keeps them — content, not mtime).

Telemetry: every per-replica swap emits a schema-v11 ``serving`` record
with ``event='rollover'`` (replica_id, old/new iter markers, standby
warmup mode/seconds, swap_ms, xla_compiles_at_swap) that ``cli inspect
summary`` counts.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Any, Dict, List, Optional

from ..config import MAMLConfig
from .replica import ReplicaSet


class RefreshDaemon:
    """Watch a checkpoint dir and roll the pool onto new snapshots.

    :param pool: the ``ReplicaSet`` to keep fresh.
    :param cfg: the serving config (geometry for the restore template;
        ``serving_rollover_poll_s`` is the default poll cadence).
    :param model_save_dir: the training run's ``saved_models`` dir.
    :param model_name: checkpoint family name (default ``train_model``).
    :param model_idx: which checkpoint to follow (default ``latest``).
    :param poll_s: poll cadence override.
    :param sink: optional telemetry sink for the ``rollover`` records.

    ``poll_once()`` is the synchronous unit (None when nothing changed,
    else the per-replica swap stats) — what the tests drive;
    ``start()``/``stop()`` wrap it in a daemon thread.
    """

    def __init__(
        self,
        pool: ReplicaSet,
        cfg: MAMLConfig,
        model_save_dir: str,
        model_name: str = "train_model",
        model_idx: str = "latest",
        poll_s: Optional[float] = None,
        sink=None,
    ):
        self.pool = pool
        self.cfg = cfg
        self.model_save_dir = model_save_dir
        self.model_name = model_name
        self.model_idx = model_idx
        self.poll_s = (
            float(cfg.serving_rollover_poll_s) if poll_s is None
            else float(poll_s)
        )
        if self.poll_s <= 0:
            raise ValueError(f"poll_s must be > 0, got {self.poll_s}")
        self.sink = sink
        self.rollovers = 0
        self.last_error: Optional[BaseException] = None
        self._served_marker: Optional[int] = None
        # mid-pool failure bookkeeping: which replicas already swapped
        # onto the in-progress marker, so the retry after a partial
        # rollover (replica k's standby warmup failed) resumes at
        # replica k instead of re-rolling — and double-counting
        # rollover records for — the ones that already swapped
        self._pending_marker: Optional[int] = None
        self._rolled_replicas: set = set()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._roll_lock = threading.Lock()

    # -- watch -------------------------------------------------------------

    def current_marker(self) -> Optional[int]:
        """The checkpoint's identity marker (its ``current_iter``) —
        readable without a restore; None when absent/corrupt."""
        from ..experiment.checkpoint import peek_experiment_state

        st = peek_experiment_state(
            self.model_save_dir, self.model_name, self.model_idx,
            readonly=True,
        )
        if not isinstance(st, dict):
            return None
        marker = st.get("current_iter")
        return int(marker) if isinstance(marker, int) else None

    def prime(self, marker: Optional[int] = None) -> None:
        """Adopt the currently-served snapshot's marker so the first
        poll doesn't re-roll onto what the pool already serves. Call
        after the pool's initial warmup."""
        self._served_marker = (
            self.current_marker() if marker is None else marker
        )

    # -- roll --------------------------------------------------------------

    def poll_once(self) -> Optional[List[Dict[str, Any]]]:
        """One watch step: roll over iff the checkpoint marker moved.
        Returns the per-replica swap stats, or None when nothing
        changed. Transient errors (a checkpoint mid-write on a remote
        filesystem, a briefly-unreadable dir) are latched on
        ``last_error`` and retried next poll — the daemon must never
        kill the serving process it refreshes."""
        try:
            marker = self.current_marker()
            if marker is None or marker == self._served_marker:
                return None
            return self._rollover(marker)
        except Exception as e:  # noqa: BLE001 - refresh is best-effort
            self.last_error = e
            print(
                f"[serving-refresh] rollover attempt failed (will retry "
                f"next poll): {e!r}",
                file=sys.stderr,
                flush=True,
            )
            return None

    def _rollover(self, marker: int) -> List[Dict[str, Any]]:
        from .engine import load_servable_snapshot

        with self._roll_lock:
            old_marker = self._served_marker
            if marker != self._pending_marker:
                # a NEW target marker resets the partial-rollover
                # bookkeeping (incl. the case where the checkpoint
                # advanced again mid-retry: every replica re-rolls onto
                # the newest snapshot)
                self._pending_marker = marker
                self._rolled_replicas = set()
            # READ-ONLY restore; the cache was already pointed at the
            # experiment's xla_cache by the initial snapshot load (when
            # the operator enabled it) — don't re-point per rollover
            state, _ = load_servable_snapshot(
                self.cfg,
                self.model_save_dir,
                self.model_idx,
                self.model_name,
                enable_cache=False,
            )
            stats: List[Dict[str, Any]] = []
            for replica in self.pool.replicas:
                if replica.replica_id in self._rolled_replicas:
                    continue  # already swapped onto this marker
                start = time.perf_counter()
                standby = self.pool.build_standby_engine(
                    replica.replica_id, state
                )
                warm_s = standby.warmup(
                    artifact_dir=self.pool.artifact_dir_for(
                        replica.replica_id
                    )
                )
                swap = replica.swap_engine(standby)
                swap.update(
                    old_iter=old_marker,
                    new_iter=marker,
                    standby_warmup_s=round(warm_s, 3),
                    standby_warmup_mode=standby.warmup_stats.get("mode"),
                    rollover_s=round(time.perf_counter() - start, 3),
                )
                self._record(swap)
                stats.append(swap)
                self._rolled_replicas.add(replica.replica_id)
            self._served_marker = marker
            self._pending_marker = None
            self._rolled_replicas = set()
            self.rollovers += 1
            self.last_error = None
            return stats

    def _record(self, swap: Dict[str, Any]) -> None:
        if self.sink is None:
            return
        from ..telemetry.sinks import make_record

        self.sink.write(
            make_record("serving", event="rollover", **swap)
        )

    # -- daemon ------------------------------------------------------------

    def start(self) -> "RefreshDaemon":
        if self._thread is not None:
            raise RuntimeError("RefreshDaemon already started")
        self._stop.clear()

        def _run():
            while not self._stop.wait(self.poll_s):
                self.poll_once()

        self._thread = threading.Thread(
            target=_run, name="serving-refresh", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None
