"""Adapt-on-request meta-inference serving (ROADMAP item 1).

MAML's value at inference time is per-request adaptation: every request
carries a small support set, and the server must run a compiled inner
loop — not a plain forward — before it can predict on the query set.
This package turns the training stack's vmap task axis into a
concurrent-TENANT axis and serves that adapt-then-predict program as a
request-driven hot path:

* :mod:`serving.engine`  — ``ServingEngine``: loads a training checkpoint
  (read-only) into a servable snapshot, pre-compiles the donated serving
  program family for every (tenant-bucket, shots) point of the static
  bucket ladder at startup (or DESERIALIZES it from AOT export
  artifacts — zero XLA compiles), and dispatches padded, masked
  multi-tenant batches under a strict ``RetraceDetector``. Three ingest
  tiers (f32 / uint8 / store-index — ``serving_ingest``) and an
  adapted-params LRU cache (``serving_adapted_cache_size``) that routes
  repeat tenants to the inner-loop-free predict program;
* :mod:`serving.batcher` — the host-side micro-batching front end:
  per-shots-bucket queues with ``serving_max_wait_ms`` /
  ``serving_max_tenants_per_dispatch`` knobs (``MicroBatcher``), plus the
  synchronous ``serve_requests`` API; pixel requests (``AdaptRequest``)
  and store-row requests (``IndexRequest``);
* :mod:`serving.export`  — the ``cli serve-export`` AOT artifact writer
  (``jax.experimental.serialize_executable`` payloads keyed by
  device-kind/dtype/config-fingerprint);
* :mod:`serving.replica` — horizontal scale-out: ``ReplicaSet``
  partitions the visible devices into disjoint slices and runs one
  full engine (+ micro-batcher + adapted-params cache) per slice,
  each tagging its telemetry with a ``replica_id``;
* :mod:`serving.router`  — the shared-nothing front tier:
  cache-affinity routing (stable support-set fingerprint -> home
  replica, so LRU hit rates survive scale-out), queue-depth spillover
  and per-replica circuit breaking;
* :mod:`serving.refresh` — the checkpoint-rollover refresh daemon:
  watches the experiment dir, pre-warms each new snapshot into a
  standby engine off the hot path and swaps replicas one at a time
  (zero dropped requests, zero XLA compiles at swap time);
* :mod:`serving.bench`   — the ``cli serve-bench`` closed-loop load
  generator (latency p50/p95 + tenants/sec + H2D bytes + cache hit
  rate + ``--replicas`` pool scaling, telemetry ``serving`` records).
"""

from .batcher import AdaptRequest, IndexRequest, MicroBatcher, serve_requests
from .engine import (
    ServingEngine,
    attach_serving_watchdog,
    load_servable_snapshot,
)
from .metrics import FanoutSink, MetricsServer, ServingMetrics
from .refresh import RefreshDaemon
from .replica import Replica, ReplicaSet, partition_devices
from .router import ReplicaRouter, home_replica, request_fingerprint

__all__ = [
    "AdaptRequest",
    "FanoutSink",
    "IndexRequest",
    "MetricsServer",
    "MicroBatcher",
    "RefreshDaemon",
    "Replica",
    "ReplicaRouter",
    "ReplicaSet",
    "ServingEngine",
    "ServingMetrics",
    "attach_serving_watchdog",
    "home_replica",
    "load_servable_snapshot",
    "partition_devices",
    "request_fingerprint",
    "serve_requests",
]
