"""Adapt-on-request meta-inference serving (ROADMAP item 1).

MAML's value at inference time is per-request adaptation: every request
carries a small support set, and the server must run a compiled inner
loop — not a plain forward — before it can predict on the query set.
This package turns the training stack's vmap task axis into a
concurrent-TENANT axis and serves that adapt-then-predict program as a
request-driven hot path:

* :mod:`serving.engine`  — ``ServingEngine``: loads a training checkpoint
  (read-only) into a servable snapshot, pre-compiles the donated serving
  program family for every (tenant-bucket, shots) point of the static
  bucket ladder at startup (or DESERIALIZES it from AOT export
  artifacts — zero XLA compiles), and dispatches padded, masked
  multi-tenant batches under a strict ``RetraceDetector``. Three ingest
  tiers (f32 / uint8 / store-index — ``serving_ingest``) and an
  adapted-params LRU cache (``serving_adapted_cache_size``) that routes
  repeat tenants to the inner-loop-free predict program;
* :mod:`serving.batcher` — the host-side micro-batching front end:
  per-shots-bucket queues with ``serving_max_wait_ms`` /
  ``serving_max_tenants_per_dispatch`` knobs (``MicroBatcher``), plus the
  synchronous ``serve_requests`` API; pixel requests (``AdaptRequest``)
  and store-row requests (``IndexRequest``);
* :mod:`serving.export`  — the ``cli serve-export`` AOT artifact writer
  (``jax.experimental.serialize_executable`` payloads keyed by
  device-kind/dtype/config-fingerprint);
* :mod:`serving.replica` — horizontal scale-out: ``ReplicaSet``
  partitions the visible devices into disjoint slices and runs one
  full engine (+ micro-batcher + adapted-params cache) per slice,
  each tagging its telemetry with a ``replica_id``;
* :mod:`serving.router`  — the shared-nothing front tier:
  cache-affinity routing (stable support-set fingerprint -> home
  replica, so LRU hit rates survive scale-out), queue-depth spillover
  and per-replica circuit breaking;
* :mod:`serving.gateway` — the networked fleet front tier: a framed
  binary wire schema reusing the ingest encodings (uint8/index
  compression applies on the wire too), fleet-wide consistent-hash
  cache affinity over the same support-digest fingerprint, admission
  control + deadline-aware load shedding + priority tiers at the edge,
  health-checked host membership with deterministic re-homing, and the
  exact-merge fleet histogram rollup;
* :mod:`serving.fleet`   — one fleet HOST process: a ``ReplicaSet`` +
  affinity router behind the wire-frame HTTP endpoint
  (``python -m ...serving.fleet`` runs one standalone; serve-bench
  ``--fleet N`` spawns N behind one gateway);
* :mod:`serving.refresh` — the checkpoint-rollover refresh daemon:
  watches the experiment dir, pre-warms each new snapshot into a
  standby engine off the hot path and swaps replicas one at a time
  (zero dropped requests, zero XLA compiles at swap time);
* :mod:`serving.bench`   — the ``cli serve-bench`` closed-loop load
  generator (latency p50/p95 + tenants/sec + H2D bytes + cache hit
  rate + ``--replicas`` pool scaling, telemetry ``serving`` records).
"""

from .batcher import AdaptRequest, IndexRequest, MicroBatcher, serve_requests
from .engine import (
    ServingEngine,
    attach_serving_watchdog,
    load_servable_snapshot,
)
# NOTE: serving.fleet is NOT imported here — it is runnable as
# ``python -m ...serving.fleet`` (one host process), and a package-level
# import would shadow runpy's fresh __main__ execution of the module.
from .gateway import (
    Gateway,
    GatewayClient,
    GatewayServer,
    home_host,
)
from .metrics import FanoutSink, MetricsServer, ServingMetrics
from .refresh import RefreshDaemon
from .replica import Replica, ReplicaSet, partition_devices
from .router import ReplicaRouter, home_replica, request_fingerprint

__all__ = [
    "AdaptRequest",
    "FanoutSink",
    "Gateway",
    "GatewayClient",
    "GatewayServer",
    "IndexRequest",
    "MetricsServer",
    "MicroBatcher",
    "RefreshDaemon",
    "Replica",
    "ReplicaRouter",
    "ReplicaSet",
    "ServingEngine",
    "ServingMetrics",
    "attach_serving_watchdog",
    "home_host",
    "home_replica",
    "load_servable_snapshot",
    "partition_devices",
    "request_fingerprint",
    "serve_requests",
]
