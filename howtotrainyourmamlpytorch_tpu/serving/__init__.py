"""Adapt-on-request meta-inference serving (ROADMAP item 1).

MAML's value at inference time is per-request adaptation: every request
carries a small support set, and the server must run a compiled inner
loop — not a plain forward — before it can predict on the query set.
This package turns the training stack's vmap task axis into a
concurrent-TENANT axis and serves that adapt-then-predict program as a
request-driven hot path:

* :mod:`serving.engine`  — ``ServingEngine``: loads a training checkpoint
  (read-only) into a servable snapshot, pre-compiles the donated
  ``core.maml.make_serve_step`` program for every (tenant-bucket, shots)
  point of the static bucket ladder at startup (warm-started from the
  persistent ``xla_cache`` when configured), and dispatches padded,
  masked multi-tenant batches under a strict ``RetraceDetector``;
* :mod:`serving.batcher` — the host-side micro-batching front end:
  per-shots-bucket queues with ``serving_max_wait_ms`` /
  ``serving_max_tenants_per_dispatch`` knobs (``MicroBatcher``), plus the
  synchronous ``serve_requests`` API;
* :mod:`serving.bench`   — the ``cli serve-bench`` closed-loop load
  generator (latency p50/p95 + tenants/sec, telemetry ``serving``
  records).
"""

from .batcher import AdaptRequest, MicroBatcher, serve_requests
from .engine import ServingEngine, load_servable_snapshot

__all__ = [
    "AdaptRequest",
    "MicroBatcher",
    "ServingEngine",
    "load_servable_snapshot",
    "serve_requests",
]
