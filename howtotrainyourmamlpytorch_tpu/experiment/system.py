"""The meta-learning system facade: host-side driver of the jitted steps.

The interface mirrors the reference's ``MAMLFewShotClassifier``
(few_shot_learning_system.py:26-424) — ``run_train_iter`` /
``run_validation_iter`` / ``save_model`` / ``load_model`` — so the experiment
builder layer maps one-to-one. Per-iteration host logic (all cheap scalars):

* cosine LR from the integer epoch (ref scheduler.step(epoch), :345-346);
* MSL weight vector for the epoch (ref :83-103, gate :232);
* first/second-order selection (ref :304-305) — picks between two compiled
  step variants;
* batch conversion NCHW->NHWC if needed and task-axis sharding over the mesh.

Everything heavy is inside the two jitted step functions (core.maml).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from ..config import MAMLConfig
from ..core import maml, msl
from ..data.loader import IndexBatch
from ..parallel import mesh as mesh_lib
from . import checkpoint as ckpt


def _to_nhwc(
    x: np.ndarray,
    layout: str = "auto",
    im_shape: Optional[Tuple[int, int, int]] = None,
) -> np.ndarray:
    """Accept reference-layout (..., c, h, w) batches and convert to NHWC.

    ``layout`` is ``cfg.input_layout``: 'nhwc' and 'nchw' are explicit and
    never guess; 'auto' first matches the trailing three dims against the
    config's ``im_shape`` (h, w, c) — exact and unambiguous whenever the
    batch is the configured dataset — then falls back to a channels-position
    heuristic (channels is whichever of dim -1 / dim -3 is 1 or 3), erroring
    when both positions qualify with different results or neither does.
    """
    if layout == "nhwc":
        return x
    if layout == "nchw":
        return np.moveaxis(x, -3, -1)
    if im_shape is not None:
        h, w, c = im_shape
        if x.shape[-3:] == (h, w, c):
            return x
        if x.shape[-3:] == (c, h, w) and (h, w, c) != (c, h, w):
            return np.moveaxis(x, -3, -1)
    nhwc_like = x.shape[-1] in (1, 3)
    nchw_like = x.shape[-3] in (1, 3)
    if nhwc_like and nchw_like:
        raise ValueError(
            f"batch shape {x.shape} is ambiguous between NHWC and NCHW; "
            "set input_layout='nhwc' or 'nchw' in the config"
        )
    if nhwc_like:
        return x
    if nchw_like:
        return np.moveaxis(x, -3, -1)
    raise ValueError(
        f"cannot infer layout of batch with shape {x.shape}; "
        "set input_layout='nhwc' or 'nchw' in the config"
    )


def enable_compilation_cache(cache_dir: Optional[str]) -> None:
    """Point jax's persistent compilation cache at ``cache_dir`` (falsy =>
    disabled). The 1-second min-compile-time floor keeps trivial CPU-test
    programs out of the cache while every real train/eval step (20-40s TPU
    compiles) is persisted — repeated runs and kill-safe resumes then load
    the executable instead of recompiling."""
    jax.config.update("jax_compilation_cache_dir", cache_dir or None)
    if cache_dir:
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


class MAMLFewShotClassifier:
    """Host-side system object owning state + compiled steps.

    Every train-step executable donates the state argument
    (``maml.TRAIN_DONATE``): the old state buffers alias the returned
    state's, so params + LSLR + BN + Adam moments are single-buffered in
    HBM across dispatches. ``self.state`` is re-bound to the returned state
    at every dispatch site, and checkpoint saves copy device->host before
    returning, so no consumer can observe a donated buffer. Eval donates
    nothing (see the contract note in core/maml.py)."""

    def __init__(self, cfg: MAMLConfig, use_mesh: bool = True):
        self.cfg = cfg
        # persistent XLA compile cache: a resumed (kill-safe) run reuses the
        # previous run's compiled train/eval steps. 'auto' (the default) is
        # resolved by the experiment builder to <experiment_dir>/xla_cache
        # once the experiment folder exists (the builder is constructed
        # after this and overrides) — until then 'auto' resets the cache to
        # disabled, so standalone system users (bench, tests) run uncached
        # and a prior instance's setting never leaks into this one.
        enable_compilation_cache(
            None
            if cfg.compilation_cache_dir == "auto"
            else cfg.compilation_cache_dir
        )
        self.current_epoch = 0
        self.state = maml.init_state(cfg)
        self.mesh = None
        self.multihost = jax.process_count() > 1
        if self.multihost:
            # pod-scale: hybrid (hosts, tasks) mesh, DCN x ICI; every process
            # init'd the same state (deterministic from cfg.seed), replicated
            # over the global mesh
            from ..parallel import distributed

            total_tasks = cfg.global_tasks_per_batch
            n_dev = len(jax.devices())
            if total_tasks % n_dev != 0:
                raise ValueError(
                    f"global meta-batch of {total_tasks} tasks must divide "
                    f"the {n_dev} devices of the pod mesh; adjust batch_size"
                )
            self.mesh = distributed.hybrid_task_mesh()
            self.state = mesh_lib.replicate_state(self.mesh, self.state)
        elif use_mesh and len(jax.devices()) > 1:
            n = cfg.num_devices if cfg.num_devices > 0 else len(jax.devices())
            # the mesh size must divide the meta-batch. Sized from the SAME
            # task count the loader stacks (cfg.global_tasks_per_batch) —
            # sizing from batch_size alone would quietly under-shard a
            # num_of_gpus>1 config.
            total_tasks = cfg.global_tasks_per_batch
            while n > 1 and total_tasks % n != 0:
                n -= 1
            if n > 1:
                self.mesh = mesh_lib.task_mesh(n)
                self.state = mesh_lib.replicate_state(self.mesh, self.state)
        if self.mesh is not None and cfg.task_axis_mode == "map":
            # numerically fine but lax.map serializes the sharded task axis,
            # collapsing N-device throughput to ~1 device
            print(
                "[system] WARNING: task_axis_mode='map' on a multi-device "
                "mesh runs tasks sequentially; use 'vmap' (the default) on "
                "TPU meshes — 'map' is the single-core CPU fast path",
                flush=True,
            )
        self._train_steps: Dict[bool, Any] = {}
        self._train_multi_steps: Dict[Any, Any] = {}
        self._eval_step = jax.jit(maml.make_eval_step(cfg))
        self._eval_multi_steps: Dict[bool, Any] = {}
        # device-resident data path (data_placement='device'): host uint8
        # stores registered via register_flat_stores, uploaded to HBM lazily
        # on first use per set; per-batch H2D is then index tensors only
        self._host_stores: Dict[str, np.ndarray] = {}
        self._device_stores: Dict[str, Any] = {}
        # elastic sharded-store tier (store_sharding='hosts'): when the
        # mesh has a >1 host (DCN) axis, resident stores shard their row
        # axis over it instead of replicating — per-host HBM drops to
        # store/n_hosts; the indexed steps switch to the masked-gather +
        # hosts-psum expansion (ops/device_pipeline.make_sharded_gather),
        # bit-exact with the replicated gather. _store_mesh is None when
        # inactive (replicated stores, the pre-elastic programs verbatim).
        self._store_mesh = None
        self._resolve_store_sharding()
        # XLA:CPU's gloo collectives pair ops between processes by channel
        # id, and DIFFERENT executables number their channels from the
        # same base — so two programs in flight at once (or in different
        # orders on different processes) corrupt the TCP pairs ("preamble
        # length" aborts). On the CPU test rig every multihost dispatch is
        # therefore fully synchronous: exactly one program's collectives
        # on the wire at any instant. Real accelerator pods keep the
        # one-step-lag pipeline (their collectives are stream-ordered).
        self._serialize_dispatches = (
            self.multihost and jax.default_backend() == "cpu"
        )
        self._train_steps_indexed: Dict[Any, Any] = {}
        self._train_multi_steps_indexed: Dict[Any, Any] = {}
        self._eval_steps_indexed: Dict[Any, Any] = {}
        self._eval_multi_steps_indexed: Dict[Any, Any] = {}
        # 1-step-lag sync handle: bounds device run-ahead to one in-flight
        # step (backpressure against queued-input OOM) while still
        # overlapping host work with device compute
        self._pending_sync = None
        # dispatch-overlap at phase transitions (single-host): the lag
        # block exists as input backpressure WITHIN a phase; at a
        # train->eval or eval->train boundary the next program's inputs
        # are already staged and the device stream orders execution, so
        # the host skips the block — the fused eval dispatch is enqueued
        # while the epoch's last train dispatch still runs, and the next
        # epoch's first train dispatch while the eval tail still runs
        # (over a networked device transport each skipped block is one
        # ~0.5s host round-trip off the epoch boundary). Run-ahead stays
        # bounded: the next SAME-phase dispatch blocks on this one's sync
        # handle as usual. Multihost keeps strict one-at-a-time ordering
        # (its collectives must never overlap — see _serialize_dispatches).
        self._pending_phase: Optional[str] = None
        self._overlap_boundary = not self.multihost
        self._boundary_overlaps = 0
        # runtime retrace detector (analysis/auditor.py), installed by the
        # experiment builder when cfg.analysis_level != 'off'; None keeps
        # every dispatch at a single attribute check (same off-path
        # discipline as resilience.faults)
        self.retrace_detector = None

    def _resolve_store_sharding(self) -> None:
        """Decide whether the sharded-store tier is active: requested by
        ``store_sharding='hosts'`` AND the mesh actually has a >1 host
        axis to shard over. Single-host meshes (no DCN axis) degrade to
        replication with a log line — the knob is a pod-scale memory
        optimisation, not a correctness switch (the sharded gather is
        bit-exact with the replicated one). Re-callable: tests that
        install a simulated hybrid mesh re-resolve before first dispatch."""
        self._store_mesh = None
        if self.cfg.store_sharding != "hosts":
            return
        from ..parallel.distributed import DATA_AXIS

        if (
            self.mesh is not None
            and DATA_AXIS in self.mesh.axis_names
            and self.mesh.shape[DATA_AXIS] > 1
        ):
            self._store_mesh = self.mesh
        else:
            print(
                "[system] store_sharding='hosts' requested but the mesh has "
                "no multi-host axis; resident stores stay replicated",
                flush=True,
            )

    def _sync_handle(self, metrics):
        """What the one-step-lag sync blocks on for this dispatch.

        Single-host: the loss scalar — ready status is a proxy for "the
        device is one step behind", the cheapest backpressure signal.
        Multi-host: the FULL metrics dict. Cross-process collectives on
        the CPU backend (gloo) share one tag space per process pair, so no
        program's collectives may still be in flight when the next
        program's start; blocking on every metric output guarantees the
        dispatch's last all-reduce has landed before anything else is
        enqueued. On real pods the extra wait is the tail of the metric
        psums — negligible next to the step itself."""
        return metrics if self.multihost else metrics["loss"]

    def _sync_before_dispatch(self, phase: str) -> None:
        """The one-step-lag block, phase-aware: wait for the previous
        dispatch before enqueuing the next — EXCEPT at a single-host phase
        transition (train<->eval), where the block is skipped and two
        dispatches overlap in flight (see the contract note on
        ``_overlap_boundary`` in ``__init__``)."""
        if self._pending_sync is not None:
            if (
                self._overlap_boundary
                and self._pending_phase is not None
                and self._pending_phase != phase
            ):
                self._boundary_overlaps += 1
            else:
                jax.block_until_ready(self._pending_sync)
        self._pending_phase = phase

    def pop_overlap_stats(self) -> Dict[str, int]:
        """Boundary-overlap counters since the last pop (the builder's
        per-epoch ``dispatch`` telemetry record carries them)."""
        out = {"boundary_overlaps": self._boundary_overlaps}
        self._boundary_overlaps = 0
        return out

    def _maybe_serialize(self, *trees) -> None:
        """CPU-multihost only (see ``_serialize_dispatches``): force every
        output of the dispatch just enqueued, so no two programs ever
        overlap on the gloo transport."""
        if self._serialize_dispatches:
            jax.block_until_ready(trees)

    def _observe_dispatch(self, site: str, args: tuple) -> None:
        """Hash the abstract signature of a dispatch for the retrace
        detector. ``site`` carries every static variant key of the jitted
        program (second_order/augment/k/preds — and the dataset split for
        indexed dispatches, whose per-set resident stores legitimately
        differ in shape), so within one site any NEW signature is a
        genuine mid-run retrace. Callers guard on ``retrace_detector is
        not None`` BEFORE building the site string/args tuple, so the
        'off' dispatch path stays a single attribute check."""
        self.retrace_detector.observe(site, args)

    # -- step selection ---------------------------------------------------

    def _train_step(self, second_order: bool):
        if second_order not in self._train_steps:
            self._train_steps[second_order] = jax.jit(
                maml.make_train_step(self.cfg, second_order),
                donate_argnums=maml.TRAIN_DONATE,
            )
        return self._train_steps[second_order]

    def _train_multi_step(self, second_order: bool, k: int):
        key = (second_order, k)
        if key not in self._train_multi_steps:
            self._train_multi_steps[key] = jax.jit(
                maml.make_train_multi_step(self.cfg, second_order),
                donate_argnums=maml.TRAIN_DONATE,
            )
        return self._train_multi_steps[key]

    def _eval_multi_step(self, with_preds: bool):
        if with_preds not in self._eval_multi_steps:
            self._eval_multi_steps[with_preds] = jax.jit(
                maml.make_eval_multi_step(self.cfg, with_preds)
            )
        return self._eval_multi_steps[with_preds]

    def _train_step_indexed(self, second_order: bool, augment: bool):
        key = (second_order, augment)
        if key not in self._train_steps_indexed:
            self._train_steps_indexed[key] = jax.jit(
                maml.make_train_step_indexed(
                    self.cfg, second_order, augment,
                    store_mesh=self._store_mesh,
                ),
                # state only — never the resident store (argnum 1)
                donate_argnums=maml.TRAIN_DONATE,
            )
        return self._train_steps_indexed[key]

    def _train_multi_step_indexed(self, second_order: bool, augment: bool, k: int):
        key = (second_order, augment, k)
        if key not in self._train_multi_steps_indexed:
            self._train_multi_steps_indexed[key] = jax.jit(
                maml.make_train_multi_step_indexed(
                    self.cfg, second_order, augment,
                    store_mesh=self._store_mesh,
                ),
                donate_argnums=maml.TRAIN_DONATE,
            )
        return self._train_multi_steps_indexed[key]

    def _eval_step_indexed(self, augment: bool):
        if augment not in self._eval_steps_indexed:
            self._eval_steps_indexed[augment] = jax.jit(
                maml.make_eval_step_indexed(
                    self.cfg, augment, store_mesh=self._store_mesh
                )
            )
        return self._eval_steps_indexed[augment]

    def _eval_multi_step_indexed(self, with_preds: bool, augment: bool):
        key = (with_preds, augment)
        if key not in self._eval_multi_steps_indexed:
            self._eval_multi_steps_indexed[key] = jax.jit(
                maml.make_eval_multi_step_indexed(
                    self.cfg, with_preds, augment,
                    store_mesh=self._store_mesh,
                )
            )
        return self._eval_multi_steps_indexed[key]

    # -- device-resident store management ---------------------------------

    def register_flat_stores(self, stores: Dict[str, np.ndarray]) -> None:
        """Register per-set host uint8 image stores (``FlatStore.data``) for
        ``data_placement='device'``. Upload happens lazily on first batch of
        each set, so sets never evaluated cost no HBM."""
        self._host_stores.update(stores)
        self._device_stores.clear()

    def _device_store(self, set_name: str):
        if set_name not in self._device_stores:
            if set_name not in self._host_stores:
                raise ValueError(
                    f"data_placement='device' but no flat store registered "
                    f"for set {set_name!r}; call register_flat_stores with "
                    "the dataset's FlatStore data (the experiment builder "
                    "does this automatically)"
                )
            store = self._host_stores[set_name]
            if self._store_mesh is not None:
                arr = self._place_sharded_store(store)
            elif self.multihost:
                # every host holds the full (deterministically built) store;
                # replicate it over the global mesh — index batches are what
                # shard over the task axis (see parallel.mesh.replicate_array)
                sharding = mesh_lib.replicated(self.mesh)
                arr = jax.make_array_from_process_local_data(
                    sharding, np.asarray(store), store.shape
                )
            elif self.mesh is not None:
                arr = mesh_lib.replicate_array(self.mesh, store)
            else:
                arr = jax.device_put(store)
            self._device_stores[set_name] = arr
        return self._device_stores[set_name]

    def _place_sharded_store(self, store: np.ndarray):
        """Place one flat store with its row axis sharded over the mesh's
        host (DCN) axis (``store_sharding='hosts'``): each host uploads
        only its 1/n_hosts row block — rows zero-padded to shard evenly;
        padding is unreachable (gather indices stay < the logical row
        count) and masked in the sharded gather anyway."""
        from ..ops.device_pipeline import pad_store_rows
        from ..parallel import distributed

        mesh = self._store_mesh
        n_shards = mesh.shape[distributed.DATA_AXIS]
        padded = pad_store_rows(np.asarray(store), n_shards)
        sharding = distributed.store_row_sharding(mesh)
        if self.multihost:
            rows_per = padded.shape[0] // n_shards
            h = jax.process_index()
            local = np.ascontiguousarray(
                padded[h * rows_per:(h + 1) * rows_per]
            )
            return jax.make_array_from_process_local_data(
                sharding, local, padded.shape
            )
        # simulated-hosts mesh (tests): one process holds every shard
        return jax.device_put(padded, sharding)

    def _prepare_index_batch(self, batch: IndexBatch):
        """Place one IndexBatch's (gather, rot_k) tensors — the task axis
        shards exactly like the pixel path's, just a few KB instead of MB."""
        gather = np.ascontiguousarray(batch.gather, np.int32)
        rot_k = np.ascontiguousarray(batch.rot_k, np.int32)
        if self.multihost:
            from ..parallel import distributed

            sharding = distributed.global_batch_sharding(self.mesh)
            n_hosts = jax.process_count()
            out = []
            for a in (gather, rot_k):
                global_shape = (a.shape[0] * n_hosts,) + a.shape[1:]
                out.append(
                    jax.make_array_from_process_local_data(
                        sharding, a, global_shape
                    )
                )
            return tuple(out)
        if self.mesh is not None:
            return mesh_lib.shard_batch(self.mesh, gather, rot_k)
        return jax.device_put((gather, rot_k))

    def _upload_stacked_indices(self, batches):
        """Stack per-iteration IndexBatches along a leading k axis and start
        the (async) upload — the index twin of ``_upload_stacked``."""
        gather = np.stack([np.asarray(b.gather, np.int32) for b in batches])
        rot_k = np.stack([np.asarray(b.rot_k, np.int32) for b in batches])
        if self.mesh is not None:
            return mesh_lib.shard_stacked_batch(self.mesh, gather, rot_k)
        return jax.device_put((gather, rot_k))

    def _stage_indexed(self, batch_or_batches, stacked: bool,
                       phase: str = "train"):
        """The shared prelude of every indexed dispatch: enqueue the (tiny)
        index upload and resolve the resident store FIRST, then apply the
        phase-aware one-step-lag sync — the index H2D is always in flight
        before the pending-dispatch block fires, so the upload overlaps the
        still-running previous dispatch (same ordering as the pixel
        paths). Returns (store, (gather, rot_k), augment)."""
        if stacked:
            placed = self._upload_stacked_indices(batch_or_batches)
            first = batch_or_batches[0]
        else:
            placed = self._prepare_index_batch(batch_or_batches)
            first = batch_or_batches
        store = self._device_store(first.set_name)
        self._sync_before_dispatch(phase)
        return store, placed, first.augment

    def _convert_batch(self, data_batch):
        """Layout/dtype conversion only (no device placement):
        (x_s, y_s, x_t, y_t) as host numpy arrays."""
        x_s, x_t, y_s, y_t = data_batch[:4]
        layout, shape = self.cfg.input_layout, self.cfg.im_shape
        if self.cfg.data_placement == "uint8_stream":
            # raw integer pixels cross H2D; the jitted step decodes them.
            # A float batch here would be silently truncated by a uint8
            # cast — refuse instead (the loader's uint8 tier is the only
            # legitimate source of these batches)
            for a in (x_s, x_t):
                if np.asarray(a).dtype != np.uint8:
                    raise ValueError(
                        "data_placement='uint8_stream' expects uint8 image "
                        f"batches from the loader, got {np.asarray(a).dtype}"
                    )
            x_s = _to_nhwc(np.asarray(x_s), layout, shape)
            x_t = _to_nhwc(np.asarray(x_t), layout, shape)
        else:
            x_s = _to_nhwc(np.asarray(x_s, np.float32), layout, shape)
            x_t = _to_nhwc(np.asarray(x_t, np.float32), layout, shape)
        y_s = np.asarray(y_s, np.int32)
        y_t = np.asarray(y_t, np.int32)
        return x_s, y_s, x_t, y_t

    def _prepare_batch(self, data_batch):
        x_s, y_s, x_t, y_t = self._convert_batch(data_batch)
        if self.multihost:
            # each host holds its slice of the global task axis; assemble the
            # global sharded arrays without any cross-host copy
            from ..parallel import distributed

            sharding = distributed.global_batch_sharding(self.mesh)
            n_hosts = jax.process_count()
            out = []
            for a in (x_s, y_s, x_t, y_t):
                global_shape = (a.shape[0] * n_hosts,) + a.shape[1:]
                out.append(
                    jax.make_array_from_process_local_data(
                        sharding, a, global_shape
                    )
                )
            return tuple(out)
        if self.mesh is not None:
            return mesh_lib.shard_batch(self.mesh, x_s, y_s, x_t, y_t)
        # explicit async upload (device_put enqueues and returns): callers
        # prepare the batch BEFORE blocking on _pending_sync, so the H2D
        # transfer overlaps the still-running previous dispatch instead of
        # serializing behind it at jit-call time (double-buffered uploads)
        return jax.device_put((x_s, y_s, x_t, y_t))

    def _upload_stacked(self, prepared):
        """Stack per-iteration batches along a leading k axis and start the
        (async) upload — sharded task axis on a mesh, plain device_put
        otherwise. Called before the one-step-lag sync so the H2D transfer
        overlaps the in-flight dispatch (see _prepare_batch)."""
        stacked = tuple(np.stack(parts) for parts in zip(*prepared))
        if self.mesh is not None:
            return mesh_lib.shard_stacked_batch(self.mesh, *stacked)
        return jax.device_put(stacked)

    # -- public API (reference-shaped) ------------------------------------

    def _epoch_schedule(self, epoch: int):
        """Everything the outer step needs that is a pure function of the
        epoch: (lr, msl_weights, second_order, per-step anneal log values).
        The single definition shared by the per-iteration and chunked
        dispatch paths so their math can never diverge."""
        cfg = self.cfg
        lr = maml.cosine_lr(cfg, epoch)
        weights = msl.loss_weights_for(
            cfg.number_of_training_steps_per_iter,
            cfg.use_multi_step_loss_optimization,
            True,
            epoch,
            cfg.multi_step_loss_num_epochs,
        )
        second_order = bool(
            cfg.second_order and epoch > cfg.first_order_to_second_order_epoch
        )
        anneal = msl.per_step_loss_importance(
            cfg.number_of_training_steps_per_iter,
            cfg.multi_step_loss_num_epochs,
            epoch,
        )
        return lr, weights, second_order, anneal

    def run_train_iter(self, data_batch, epoch) -> Dict[str, Any]:
        """One outer-loop update (ref :338-369). Returns the losses dict with
        the reference's keys (loss, accuracy, loss_importance_vector_i,
        learning_rate). loss/accuracy are DEVICE arrays (convert at summary
        time — per-step float() would serialize the pipeline); the schedule
        entries are host floats."""
        epoch = int(epoch)
        self.current_epoch = epoch
        lr, weights, second_order, anneal = self._epoch_schedule(epoch)
        if isinstance(data_batch, IndexBatch):
            # device-resident tier: upload a few KB of indices, gather /
            # decode / rot90 run inside the jitted step against the
            # resident store
            store, (gather, rot_k), augment = self._stage_indexed(
                data_batch, stacked=False
            )
            if self.retrace_detector is not None:
                self._observe_dispatch(
                    f"train_step_indexed[so={int(second_order)},"
                    f"aug={int(augment)},set={data_batch.set_name}]",
                    (self.state, store, gather, rot_k, weights, lr),
                )
            self.state, metrics = self._train_step_indexed(
                second_order, augment
            )(self.state, store, gather, rot_k, weights, lr)
            self._pending_sync = self._sync_handle(metrics)
            self._maybe_serialize(self.state, metrics)
            losses = dict(metrics)
            for i, w in enumerate(anneal):
                losses[f"loss_importance_vector_{i}"] = float(w)
            losses["learning_rate"] = float(lr)
            return losses
        x_s, y_s, x_t, y_t = self._prepare_batch(data_batch)
        # wait for the PREVIOUS step before enqueuing the next: a one-step
        # pipeline. (Zero sync would let the host run an epoch ahead, pinning
        # every queued input batch in device memory; per-step float() would
        # serialize host and device completely.)
        self._sync_before_dispatch("train")
        if self.retrace_detector is not None:
            self._observe_dispatch(
                f"train_step[so={int(second_order)}]",
                (self.state, x_s, y_s, x_t, y_t, weights, lr),
            )
        self.state, metrics = self._train_step(second_order)(
            self.state, x_s, y_s, x_t, y_t, weights, lr
        )
        self._pending_sync = self._sync_handle(metrics)
        self._maybe_serialize(self.state, metrics)
        # metrics stay device arrays — the float() happens when the builder
        # summarizes an epoch; through a networked device transport every
        # forced per-step sync would be a round-trip
        losses = dict(metrics)
        # per-step MSL weights logged each iteration (ref :260-262)
        for i, w in enumerate(anneal):
            losses[f"loss_importance_vector_{i}"] = float(w)
        losses["learning_rate"] = float(lr)  # ref :365
        return losses

    def run_train_iters(self, data_batches, epoch) -> Dict[str, Any]:
        """len(data_batches) outer updates in ONE device dispatch
        (``steps_per_dispatch``) — identical math to calling
        ``run_train_iter`` that many times at the same epoch (LR, MSL
        weights and the order flag are epoch-functions; the builder flushes
        chunks at epoch boundaries so a chunk never spans one).

        Returns ONE losses dict whose device metrics are (k,)-stacked —
        NOT sliced per iteration: slicing would enqueue 2k tiny gather
        programs per chunk and re-introduce the per-item dispatches this
        path exists to amortize. The builder's epoch summary flattens the
        stacks (one device fetch per chunk per key).

        Multi-host runs fall back to per-iteration dispatch: their batch
        assembly builds global sharded arrays per iteration and the
        per-dispatch overhead this path amortizes is a single-host tunnel
        artifact anyway.
        """
        if self.multihost or len(data_batches) == 1:
            # merge the per-iter dicts into the same stacked-value contract
            per_iter = [self.run_train_iter(b, epoch) for b in data_batches]
            return {
                key: (
                    per_iter[0][key]
                    if np.isscalar(per_iter[0][key])
                    else [d[key] for d in per_iter]
                )
                for key in per_iter[0]
            }
        epoch = int(epoch)
        self.current_epoch = epoch
        lr, weights, second_order, anneal = self._epoch_schedule(epoch)
        k = len(data_batches)
        if isinstance(data_batches[0], IndexBatch):
            store, placed, augment = self._stage_indexed(
                data_batches, stacked=True
            )
            if self.retrace_detector is not None:
                self._observe_dispatch(
                    f"train_multi_step_indexed[so={int(second_order)},"
                    f"aug={int(augment)},k={k},"
                    f"set={data_batches[0].set_name}]",
                    (self.state, store, *placed, weights, lr),
                )
            self.state, metrics = self._train_multi_step_indexed(
                second_order, augment, k
            )(self.state, store, *placed, weights, lr)
            self._pending_sync = self._sync_handle(metrics)
            losses = dict(metrics)  # values are (k,) device arrays
            for j, w in enumerate(anneal):
                losses[f"loss_importance_vector_{j}"] = float(w)
            losses["learning_rate"] = float(lr)
            return losses
        prepared = [self._convert_batch(b) for b in data_batches]
        stacked = self._upload_stacked(prepared)
        # upload already enqueued above — blocking here only bounds run-ahead
        # to one in-flight dispatch while this chunk's H2D streams in
        self._sync_before_dispatch("train")
        if self.retrace_detector is not None:
            self._observe_dispatch(
                f"train_multi_step[so={int(second_order)},k={k}]",
                (self.state, *stacked, weights, lr),
            )
        self.state, metrics = self._train_multi_step(second_order, k)(
            self.state, *stacked, weights, lr
        )
        self._pending_sync = self._sync_handle(metrics)
        losses: Dict[str, Any] = dict(metrics)  # values are (k,) device arrays
        for j, w in enumerate(anneal):
            losses[f"loss_importance_vector_{j}"] = float(w)
        losses["learning_rate"] = float(lr)
        return losses

    def run_validation_iter(
        self, data_batch, return_preds: bool = False
    ) -> Tuple[Dict[str, Any], Optional[np.ndarray]]:
        """One evaluation pass (ref :371-397). Returns (losses, preds);
        losses values are device arrays (see run_train_iter).

        ``return_preds=True`` materialises the per-task softmax predictions
        on the host (cross-host allgather in multihost mode) — only the test
        ensemble needs them; plain validation skips the transfer entirely.
        """
        if isinstance(data_batch, IndexBatch):
            store, (gather, rot_k), augment = self._stage_indexed(
                data_batch, stacked=False, phase="eval"
            )
            if self.retrace_detector is not None:
                self._observe_dispatch(
                    f"eval_step_indexed[aug={int(augment)},"
                    f"set={data_batch.set_name}]",
                    (self.state, store, gather, rot_k),
                )
            metrics, preds = self._eval_step_indexed(augment)(
                self.state, store, gather, rot_k
            )
        else:
            x_s, y_s, x_t, y_t = self._prepare_batch(data_batch)
            # same one-step pipeline as train; phase-aware at the boundary
            self._sync_before_dispatch("eval")
            if self.retrace_detector is not None:
                self._observe_dispatch(
                    "eval_step", (self.state, x_s, y_s, x_t, y_t)
                )
            metrics, preds = self._eval_step(self.state, x_s, y_s, x_t, y_t)
        self._pending_sync = self._sync_handle(metrics)
        self._maybe_serialize(metrics, preds)
        metrics = dict(metrics)  # device arrays; caller converts on summary
        out_preds = None
        if return_preds:
            if self.multihost:
                # preds are sharded over the global task axis; the ensemble
                # needs them all on every host. Drain the eval dispatch
                # FIRST: the allgather is its own program, and running its
                # collective while the eval step's metric all-reduces are
                # still in flight corrupts backends whose collectives share
                # one tag space per process pair (XLA:CPU gloo aborts with
                # a preamble-length mismatch); on real pods this wait is
                # subsumed by the d2h fetch below anyway
                jax.block_until_ready(metrics["loss"])
                from jax.experimental import multihost_utils

                preds = multihost_utils.process_allgather(preds, tiled=True)
            out_preds = np.asarray(preds)
        return metrics, out_preds

    def run_validation_iters(
        self, data_batches, return_preds: bool = False
    ) -> Tuple[Dict[str, Any], Optional[np.ndarray]]:
        """len(data_batches) evaluation passes in ONE device dispatch
        (``eval_batches_per_dispatch``) — identical math to calling
        ``run_validation_iter`` once per batch.

        Returns ONE (losses, preds) pair: device metrics come back
        (k,)-stacked (the builder's epoch summary flattens them, same
        contract as ``run_train_iters``); preds — only with
        ``return_preds=True`` — as a host (k, tasks, targets, classes)
        array the ensemble slices per batch.

        Multi-host runs fall back to per-iteration dispatch (their batch
        assembly is per-iteration and the preds allgather lives in
        ``run_validation_iter``).
        """
        if self.multihost or len(data_batches) == 1:
            per_iter = [
                self.run_validation_iter(b, return_preds)
                for b in data_batches
            ]
            losses = {
                key: [m[key] for m, _ in per_iter] for key in per_iter[0][0]
            }
            preds = (
                np.stack([p for _, p in per_iter]) if return_preds else None
            )
            return losses, preds
        if isinstance(data_batches[0], IndexBatch):
            store, placed, augment = self._stage_indexed(
                data_batches, stacked=True, phase="eval"
            )
            if self.retrace_detector is not None:
                self._observe_dispatch(
                    f"eval_multi_step_indexed[preds={int(return_preds)},"
                    f"aug={int(augment)},k={len(data_batches)},"
                    f"set={data_batches[0].set_name}]",
                    (self.state, store, *placed),
                )
            metrics, preds = self._eval_multi_step_indexed(
                return_preds, augment
            )(self.state, store, *placed)
        else:
            prepared = [self._convert_batch(b) for b in data_batches]
            stacked = self._upload_stacked(prepared)
            # same one-step pipeline as train; phase-aware at the boundary
            self._sync_before_dispatch("eval")
            if self.retrace_detector is not None:
                self._observe_dispatch(
                    f"eval_multi_step[preds={int(return_preds)},"
                    f"k={len(data_batches)}]",
                    (self.state, *stacked),
                )
            metrics, preds = self._eval_multi_step(return_preds)(
                self.state, *stacked
            )
        self._pending_sync = self._sync_handle(metrics)
        out_preds = np.asarray(preds) if return_preds else None
        return dict(metrics), out_preds

    def dump_state(
        self, dump_dir: str, experiment_state: Optional[Dict[str, Any]] = None
    ) -> None:
        """Synchronous postmortem state dump for the flight recorder: write
        the live ``MetaState`` (params + LSLR + BN + Adam moments) as an
        orbax checkpoint under ``<dump_dir>/state`` plus the experiment
        state as JSON — the same on-disk layout a regular checkpoint
        directory has, so ``checkpoint.load_checkpoint``-style tooling can
        restore it for inspection or a pre-divergence resume.

        Single-host only: the monitor triggers on every host, and a
        collective orbax save initiated from an anomaly path could
        deadlock a mesh that is itself the thing misbehaving.
        """
        import json
        import os

        import orbax.checkpoint as ocp

        if self.multihost:
            raise RuntimeError(
                "incident state dumps are single-host only; multihost runs "
                "dump the flight-recorder ring without the state checkpoint"
            )
        ckpt.wait_for_pending()  # never interleave with an async epoch save
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(
            os.path.join(os.path.abspath(dump_dir), "state"),
            self.state._asdict(),
        )
        ckptr.wait_until_finished()
        if experiment_state is not None:
            with open(
                os.path.join(dump_dir, "experiment_state.json"), "w"
            ) as f:
                json.dump(experiment_state, f, cls=ckpt._NumpyEncoder)

    def device_memory_stats(self) -> Dict[str, Any]:
        """Per-epoch device-memory telemetry: live HBM stats (when the
        backend exposes them — TPU does, CPU reports nothing) next to the
        store registry's *expectation* (bytes of every flat uint8 store
        already made resident via ``_device_store``). A growing gap between
        ``bytes_in_use`` and the expected resident set is the leak signal
        the telemetry sink records each epoch."""
        # sharded stores resident per HOST at 1/n_hosts of the full bytes
        # (plus negligible row padding) — the expectation must match what
        # this host actually holds or the leak signal would always fire
        shards = 1
        if self._store_mesh is not None:
            from ..parallel.distributed import DATA_AXIS

            shards = int(self._store_mesh.shape[DATA_AXIS])
        out: Dict[str, Any] = {
            "store_bytes_expected": sum(
                int(self._host_stores[name].nbytes) // shards
                for name in self._device_stores
            ),
            "stores_resident": sorted(self._device_stores),
            "store_sharding": (
                "replicated" if self._store_mesh is None else "hosts"
            ),
        }
        try:
            stats = jax.local_devices()[0].memory_stats()
        except Exception:  # noqa: BLE001 - backend may not implement it
            stats = None
        if stats:
            for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                        "largest_alloc_size"):
                if key in stats:
                    out[key] = int(stats[key])
        return out

    def gather_across_hosts(self, a: np.ndarray) -> np.ndarray:
        """Concatenate per-host arrays along axis 0 (identity single-host).

        Used by the test ensemble to pair globally-gathered predictions with
        the matching targets when each host only loaded its batch slice.
        """
        if not self.multihost:
            return np.asarray(a)
        # same discipline as the preds allgather in run_validation_iter: no
        # other program's collectives may be in flight when this one runs
        # (XLA:CPU gloo shares a tag space per process pair)
        if self._pending_sync is not None:
            jax.block_until_ready(self._pending_sync)
        from jax.experimental import multihost_utils

        return np.asarray(
            multihost_utils.process_allgather(np.asarray(a), tiled=True)
        )

    # -- checkpointing (ref :399-424) -------------------------------------

    def save_model(self, model_save_dir: str, model_idx,
                   experiment_state: Dict[str, Any],
                   also_latest: bool = False) -> str:
        """Checkpoint the current state as ``train_model_<model_idx>``.

        ``also_latest=True`` additionally materialises ``train_model_latest``
        from the same write — single-host via the async path's host-side
        clone (ONE device->host serialization, disk write overlapping the
        next epoch's training; the barrier lives in checkpoint.py), multi-host
        via a second collective save (the async path is single-host only).
        """
        if self.multihost:
            # drain the in-flight dispatch first: orbax's collective save
            # synchronizes with a small device psum of its own, and no
            # other program's collectives may be in flight when it runs
            # (XLA:CPU gloo shares a tag space per process pair)
            if self._pending_sync is not None:
                jax.block_until_ready(self._pending_sync)
            timeout = float(self.cfg.ckpt_follower_timeout_s)
            path = ckpt.save_checkpoint(
                model_save_dir, "train_model", model_idx, self.state,
                experiment_state, barrier_timeout_s=timeout,
            )
            if also_latest:
                ckpt.save_checkpoint(
                    model_save_dir, "train_model", "latest", self.state,
                    experiment_state, barrier_timeout_s=timeout,
                )
            return path
        return ckpt.save_checkpoint_async(
            model_save_dir, "train_model", model_idx, self.state,
            experiment_state,
            clone_to="latest" if also_latest else None,
        )

    def load_model(self, model_save_dir: str, model_idx) -> Dict[str, Any]:
        if self.multihost and self._pending_sync is not None:
            # same discipline as the multihost save: the collective restore
            # must not overlap an in-flight dispatch's collectives (the
            # test ensemble hops checkpoints with an eval still pending)
            jax.block_until_ready(self._pending_sync)
        self.state, experiment_state = ckpt.load_checkpoint(
            model_save_dir, "train_model", model_idx, self.state
        )
        if self.mesh is not None:
            self.state = mesh_lib.replicate_state(self.mesh, self.state)
        return experiment_state
