"""Fault-tolerant experiment orchestration.

Re-implementation of the reference's ``ExperimentBuilder``
(experiment_builder.py:10-371): an iteration-counted train loop with

* validation every ``total_iter_per_epoch`` iterations over
  ``num_evaluation_tasks`` fixed tasks (:327-337);
* best-val tracking (:339-344) and per-epoch + ``latest`` checkpoints
  (:190-206, 352);
* kill-safe resume from ``latest`` (default) / ``from_scratch`` / an epoch
  index (:32-51), incl. fast-forwarding the deterministic task stream
  (:53 -> data.py:583-588);
* per-epoch mean/std of every metric appended to
  ``logs/summary_statistics.csv`` and mirrored to ``summary_statistics.json``
  (:208-245, 354-365);
* controlled pause for preemptible clusters after
  ``total_epochs_before_pause`` epochs (:367-370);
* final test = ensemble of the top-5 validation checkpoints: mean of
  per-model softmax preds, argmax, accuracy ± std -> ``test_summary.csv``
  (:247-300).
"""

from __future__ import annotations

import dataclasses
import os
import signal
import sys
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ..config import MAMLConfig
from ..resilience import (
    PREEMPT_EXIT_CODE,
    DrainCoordinator,
    PreemptedError,
    RetriesExhaustedError,
    RetryPolicy,
    elastic,
    faults,
)
from ..telemetry import FlightRecorder, HealthMonitor, Telemetry, Watchdog
from ..telemetry import tracing
from ..utils.profiling import (
    PROFILE_REQUEST_FILENAME,
    OnDemandProfiler,
    StepTimer,
    TraceWindow,
)
from ..utils.storage import (
    build_experiment_folder,
    save_statistics,
    save_to_json,
)
from .checkpoint import (
    checkpoint_exists,
    peek_experiment_state,
    remove_checkpoint,
    wait_for_pending,
)
from .system import MAMLFewShotClassifier


class ExperimentBuilder:
    def __init__(
        self,
        cfg: MAMLConfig,
        model: MAMLFewShotClassifier,
        data_loader_cls,
        experiment_root: str = ".",
        verbose: bool = True,
    ):
        self.cfg = cfg
        self.model = model
        self.verbose = verbose
        # fault injection (resilience/faults.py): installed process-wide
        # BEFORE any I/O seam below can run, from the config knob or (when
        # empty) the MAML_FAULT_SPEC env var the chaos CI drives
        # subprocesses through; empty installs nothing and every seam is a
        # single attribute check
        faults.install(
            cfg.fault_spec or os.environ.get("MAML_FAULT_SPEC", "")
        )
        # retry/backoff for the checkpoint + statistics I/O seams; the
        # observer turns every failed attempt into a telemetry `retry`
        # record + flight-recorder note (wired below, after telemetry
        # exists — events before that go to stderr only)
        self.retry = RetryPolicy.from_config(cfg, observer=self._on_retry)
        # preemption: the signal number latched by the SIGTERM/SIGINT
        # handler run_experiment installs; drained at the next train
        # dispatch boundary (_preempt_exit)
        self._preempt_signum: Optional[int] = None
        (
            self.saved_models_filepath,
            self.logs_filepath,
            self.samples_filepath,
        ) = build_experiment_folder(cfg.experiment_name, root=experiment_root)

        # persistent XLA compilation cache: 'auto' (default) lives under the
        # experiment dir just created, so reruns and kill-safe resumes of an
        # experiment load compiled executables instead of repaying the
        # 20-40s TPU step/eval compiles. Resolved here (not in the system
        # facade) because only the builder knows the experiment root; the
        # first compile happens at the first dispatch, well after this.
        cache_dir = cfg.compilation_cache_dir
        if cache_dir == "auto":
            cache_dir = os.path.join(
                os.path.dirname(self.logs_filepath), "xla_cache"
            )
        from .system import enable_compilation_cache

        enable_compilation_cache(cache_dir)

        self.total_losses: Dict[str, List[float]] = {}
        self.state: Dict = {"best_val_acc": 0.0, "best_val_iter": 0, "current_iter": 0}
        self.start_epoch = 0
        self.create_summary_csv = False
        # column order of summary_statistics.csv: set at header-create time,
        # or read back from the existing file on resume so appended rows
        # always align with the on-disk header even when newer code grew
        # extra metrics (which then go to telemetry/JSON only)
        self._csv_keys: Optional[List[str]] = None

        # resume logic (experiment_builder.py:32-51)
        cont = str(cfg.continue_from_epoch)
        if cont == "from_scratch":
            self.create_summary_csv = True
        elif cont == "latest":
            resume_idx = self._pick_latest_resume_point()
            if resume_idx is not None:
                # transient restore failures retried; corruption surfaces
                # as CheckpointCorruptError naming the surviving fallbacks
                self.state = self.retry.call(
                    lambda: self.model.load_model(
                        self.saved_models_filepath, resume_idx
                    ),
                    site="ckpt_restore",
                )
                self._rehydrate_inflight()
                self.start_epoch = int(
                    self.state["current_iter"] // cfg.total_iter_per_epoch
                )
                if resume_idx == "emergency":
                    self._log(
                        "[resilience] resuming from the preemption "
                        "emergency checkpoint at iter "
                        f"{int(self.state['current_iter'])} (newer than "
                        "'latest')"
                    )
            else:
                self.create_summary_csv = True
        elif int(cont) >= 0:
            if not checkpoint_exists(
                self.saved_models_filepath, "train_model", int(cont)
            ):
                # max_models_to_save pruning keeps only the top-K epochs, so
                # an explicit epoch resume can target a deleted checkpoint —
                # name the cause instead of surfacing a raw orbax error
                raise FileNotFoundError(
                    f"checkpoint train_model_{int(cont)} not found in "
                    f"{self.saved_models_filepath}; it was most likely "
                    f"deleted by max_models_to_save="
                    f"{cfg.max_models_to_save} pruning (only the top-K "
                    "epochs by validation accuracy are kept). Resume with "
                    "continue_from_epoch='latest' or from a surviving "
                    "epoch checkpoint."
                )
            self.state = self.retry.call(
                lambda: self.model.load_model(
                    self.saved_models_filepath, int(cont)
                ),
                site="ckpt_restore",
            )
            self._rehydrate_inflight()
            self.start_epoch = int(
                self.state["current_iter"] // cfg.total_iter_per_epoch
            )
        # data stream fast-forwarded to the resume point
        # (experiment_builder.py:53): the checkpointed GLOBAL episode
        # cursor (resilience/elastic.py) is handed to the loader, which
        # validates it against the iteration-derived value — a resume on a
        # different process count replays the identical global episode
        # sequence, re-partitioned (old checkpoints without the key fall
        # back to the derived cursor)
        self.data = data_loader_cls(
            cfg,
            current_iter=self.state["current_iter"],
            cache_dir=cfg.cache_dir or self.logs_filepath,
            episode_cursor=self.state.get("episode_cursor"),
        )
        if cfg.data_placement == "device":
            # hand the model the per-set flat uint8 stores so it can make
            # them device-resident (uploaded lazily, once per set); the
            # loader then ships index-only batches
            self.model.register_flat_stores(
                {
                    name: fs.data
                    for name, fs in self.data.dataset.flat_stores.items()
                }
            )

        self.epoch = int(self.state["current_iter"] // cfg.total_iter_per_epoch)
        self.state["best_epoch"] = int(
            self.state.get("best_val_iter", 0) // cfg.total_iter_per_epoch
        )
        # train-time augmentation only for omniglot (experiment_builder.py:60)
        self.augment_flag = "omniglot" in cfg.dataset_name.lower()
        # perf_counter, not time.time(): epoch_run_time is a DURATION and
        # must survive wall-clock steps (NTP slew, DST) — lint rule MP007
        self.start_time = time.perf_counter()
        self.epochs_done_in_this_run = 0
        # per-step timing as first-class metrics (SURVEY.md §5 — the
        # reference only records epoch_run_time)
        self.step_timer = StepTimer()
        # epoch-boundary overlap bookkeeping (ISSUE 11): the train-summary
        # wall time spent under the in-flight eval tail, and that
        # summary's result (run_validation_epoch computes it mid-overlap)
        self._last_overlap_ms: Optional[float] = None
        self._pre_summary_result: Optional[Dict[str, float]] = None
        self._active_pbar = None
        self._pbar_sums: Dict[str, tuple] = {}
        self._steps_this_run = 0
        # multi-host: checkpoint saves are collective (orbax), but metric
        # files are written by the primary process only
        import jax

        self.is_primary = jax.process_index() == 0
        # coordinated preemption drain (resilience/elastic.py): in
        # multi-process runs ONE worker's SIGTERM must drain EVERY process
        # at the same iteration (the emergency checkpoint is collective).
        # The coordination directory lives in the experiment dir — the
        # shared-filesystem rendezvous the collective checkpoints already
        # rely on. Single-process runs keep the immediate drain-at-next-
        # boundary behaviour and never touch this.
        self._drain_coordinator: Optional[DrainCoordinator] = None
        self._drain_commit_logged = False
        if jax.process_count() > 1:
            self._drain_coordinator = DrainCoordinator(
                os.path.join(self.logs_filepath, "elastic"),
                jax.process_index(),
                jax.process_count(),
                margin_iters=cfg.drain_margin_iters,
                # run-scoped: every process derives the same tag from the
                # same resumed checkpoint, so a previous incarnation's
                # consumed (or crash-stranded) drain files cannot preempt
                # this run
                run_tag=f"i{int(self.state['current_iter'])}",
            )
        if not self.create_summary_csv:
            # resumed: drop CSV rows from epochs beyond the checkpoint — a
            # killed run can have appended the row for an epoch whose
            # checkpoint never finalized; the resumed run re-trains that
            # epoch and would otherwise append a contradicting duplicate
            self._truncate_stats_to_resume_point()
        # structured telemetry (telemetry/): JSONL event log + optional
        # TensorBoard, no-op at telemetry_level='off' / non-primary hosts
        self.telemetry = Telemetry(
            cfg, self.logs_filepath, is_primary=self.is_primary
        )
        self.telemetry.event(
            "run_start",
            experiment_name=cfg.experiment_name,
            telemetry_level=cfg.telemetry_level,
            resume_iter=int(self.state["current_iter"]),
            start_epoch=int(self.start_epoch),
            process_index=jax.process_index(),
            process_count=jax.process_count(),
            # the full config snapshot: what `telemetry_cli diff` diffs so
            # two runs' logs explain their own divergence
            config=dataclasses.asdict(cfg),
        )
        # causal tracing (telemetry/tracing.py, schema v10): span records
        # for train dispatch / eval chunk / epoch summary / checkpoint
        # intervals plus the loader's producer/consumer spans, riding the
        # telemetry JSONL sink. 'off' (default) installs the shared
        # disabled tracer: no span objects, no records, and — tracing
        # being host-side only — the jitted programs are untouched either
        # way (tested to the telemetry-off bit-identity standard).
        self.tracer = tracing.NULL_TRACER
        if cfg.tracing_level != "off" and self.telemetry.enabled:
            self.tracer = tracing.Tracer(
                emit=lambda **f: self.telemetry.event("span", **f)
            )
        # the loader's producer/consumer seams share the run tracer (the
        # loader was constructed before telemetry existed)
        self.data.tracer = self.tracer
        # elastic resume record (schema v6): a checkpoint written by a
        # different topology resumes deterministically — say so in the log
        # (old -> new process count + the episode-cursor re-entry point)
        saved_pc = self.state.get("process_count")
        if saved_pc is not None and int(self.state["current_iter"]) > 0:
            cursor = elastic.episode_cursor_for_iter(
                int(self.state["current_iter"]), cfg.global_tasks_per_batch
            )
            self.telemetry.event(
                "elastic",
                event="resume",
                old_process_count=int(saved_pc),
                new_process_count=int(jax.process_count()),
                iter=int(self.state["current_iter"]),
                episode_cursor=int(cursor),
            )
            if int(saved_pc) != jax.process_count():
                self._log(
                    f"[elastic] resuming a checkpoint written by "
                    f"{int(saved_pc)} process(es) on {jax.process_count()} "
                    f"process(es): global episode cursor {cursor} "
                    "re-partitioned over the new topology"
                )
        # training-health monitor: host-side ring of recent step health
        # (flight recorder) + anomaly detection over the on-device probes
        # (health_level='monitor'|'halt'), dumping ring + state to
        # logs/incidents/ on a trigger — see telemetry/health.py
        self.flight_recorder = None
        if cfg.flight_recorder_steps > 0:
            self.flight_recorder = FlightRecorder(
                cfg.flight_recorder_steps,
                os.path.join(self.logs_filepath, "incidents"),
                max_state_dumps=cfg.max_state_dumps,
                cooldown_steps=cfg.anomaly_cooldown_steps,
                is_primary=self.is_primary,
            )
        self.health_monitor = None
        if cfg.health_level != "off":
            self.health_monitor = HealthMonitor(
                cfg,
                telemetry=self.telemetry,
                recorder=self.flight_recorder,
                # multihost: ring + manifest only — a collective orbax save
                # from the anomaly path could deadlock a wedged mesh
                state_dump_fn=(
                    None if self.model.multihost
                    else self._dump_state_for_incident
                ),
            )
        # static analysis (analysis/): program-contract audit at build time
        # + runtime retrace detector on the dispatch sites. 'off' (default)
        # installs nothing — the system's dispatch paths keep a single
        # attribute check and the jitted programs are bit-identical to a
        # pre-analysis build (tested).
        self.retrace_detector = None
        if cfg.analysis_level != "off":
            self._install_analysis()
        # on-device dynamics stacks (telemetry_level='dynamics') buffered as
        # DEVICE arrays per dispatch; converted + flushed at epoch-summary
        # time so collection never adds a host sync to the hot loop
        self._dyn_pending: List[tuple] = []
        # scheduled profiler trace window (profile_epoch/profile_start_step/
        # profile_num_steps on top of profile_trace_dir)
        self.trace_window = TraceWindow(
            cfg.profile_trace_dir,
            num_steps=cfg.profile_num_steps,
            epoch=cfg.profile_epoch,
            start_step=cfg.profile_start_step,
            on_event=lambda action, **f: self.telemetry.event(
                "trace", action=action, **f
            ),
        )
        # on-demand device profiling: `echo N > logs/PROFILE_REQUEST` (or
        # SIGUSR2) captures a jax.profiler trace of the NEXT N train
        # dispatches into logs/profile_traces/ — no restart, no config
        # change; the emitted trace records carry the causal-tracing
        # trace_id so the device profile links to the host span timeline
        self.ondemand_profiler = OnDemandProfiler(
            os.path.join(self.logs_filepath, PROFILE_REQUEST_FILENAME),
            os.path.join(self.logs_filepath, "profile_traces"),
            default_steps=cfg.profile_num_steps,
            on_event=lambda action, **f: self.telemetry.event(
                "trace", action=action, **f
            ),
            # NULL_TRACER's id is a module-global shared by every run in
            # the process — only a live tracer's id is run-scoped enough
            # to link a device profile to this run's span timeline
            trace_id=(self.tracer.trace_id
                      if self.tracer.enabled else None),
        )
        # heartbeat hang watchdog: every host runs one (a multihost hang is
        # typically visible from every process except the one that caused
        # it); stall records go to stderr on every host and to the primary's
        # telemetry log
        self.watchdog = None
        if cfg.watchdog_timeout_s > 0:
            self.watchdog = Watchdog(
                cfg.watchdog_timeout_s, on_stall=self._on_watchdog_stall
            )

    # -- helpers (experiment_builder.py:66-100) ---------------------------

    @staticmethod
    def build_summary_dict(total_losses, phase, summary_losses=None):
        """Per-phase mean/std of every accumulated metric. Values may be
        device arrays (the per-step metrics are left unconverted so the train
        loop never blocks on device->host sync); the np.asarray here is the
        one synchronization point, at summary time."""
        if summary_losses is None:
            summary_losses = {}
        for key in total_losses:
            # entries are per-iteration scalars OR (k,)-stacked chunk
            # arrays (steps_per_dispatch) — flatten to one value stream
            vals = np.concatenate(
                [np.atleast_1d(np.asarray(v)) for v in total_losses[key]]
            )
            summary_losses[f"{phase}_{key}_mean"] = float(np.mean(vals))
            summary_losses[f"{phase}_{key}_std"] = float(np.std(vals))
        return summary_losses

    def _log(self, msg: str):
        if self.verbose:
            print(msg, flush=True)

    def _pbar(self, total: int, desc: str):
        """A live tqdm progress bar with loss postfixes, mirroring the
        reference's per-phase bars (experiment_builder.py:131-132,160-162,
        184-186). Only on an interactive primary process — batch logs get the
        per-epoch summary lines instead."""
        if not (self.verbose and self.is_primary and sys.stderr.isatty()):
            return None
        try:
            from tqdm import tqdm
        except ImportError:  # optional dependency: degrade to summary lines
            return None

        return tqdm(total=total, desc=desc, leave=False)

    @staticmethod
    def _running_summary(sums, total_losses, phase) -> Dict[str, float]:
        """Incremental per-epoch running mean for the interactive postfix.

        ``build_summary_dict`` re-reduces the full metric history on every
        call, which made the per-tick postfix O(n²) over an epoch; this
        consumes only the entries appended since the previous tick."""
        for key, vals in total_losses.items():
            s, n, seen = sums.get(key, (0.0, 0, 0))
            for v in vals[seen:]:
                a = np.atleast_1d(np.asarray(v))  # chunked entries are (k,)
                s += float(a.sum())
                n += a.size
                seen += 1
            sums[key] = (s, n, seen)
        return {
            f"{phase}_{k}_mean": s / n for k, (s, n, _) in sums.items() if n
        }

    @staticmethod
    def _pbar_tick(pbar, summary: Dict[str, float], phase: str):
        if pbar is None:
            return
        pbar.update(1)
        pbar.set_postfix_str(
            ", ".join(
                f"{k.removeprefix(phase + '_')}: {v:.4f}"
                for k, v in summary.items()
                if k in (f"{phase}_loss_mean", f"{phase}_accuracy_mean")
            )
        )

    def _accumulate(self, losses: Dict[str, float], total_losses):
        # values may be device arrays; conversion is deferred to summary time
        for key, value in losses.items():
            total_losses.setdefault(key, []).append(value)

    # -- resilience plumbing (resilience/) ---------------------------------

    def _pick_latest_resume_point(self) -> Optional[str]:
        """Resolve ``continue_from_epoch='latest'`` to an actual checkpoint:
        ``'emergency'`` when a *preemption* emergency checkpoint is newer
        than ``latest`` (a SIGTERM mid-epoch saved more progress than the
        last epoch boundary), ``'latest'`` otherwise, None when neither
        exists. Only preemption emergencies are auto-resumed — a
        ``health_level='halt'`` emergency is the *divergent* state, kept
        for postmortem and never silently trained on."""
        have_latest = checkpoint_exists(
            self.saved_models_filepath, "train_model", "latest"
        )
        emerg = peek_experiment_state(
            self.saved_models_filepath, "train_model", "emergency"
        )
        if (
            emerg is not None
            and emerg.get("emergency_reason") == "preemption"
            and checkpoint_exists(
                self.saved_models_filepath, "train_model", "emergency"
            )
        ):
            latest_iter = -1
            if have_latest:
                latest_state = peek_experiment_state(
                    self.saved_models_filepath, "train_model", "latest"
                )
                if latest_state is not None:
                    latest_iter = int(latest_state.get("current_iter", -1))
            if int(emerg.get("current_iter", -1)) > latest_iter:
                return "emergency"
        return "latest" if have_latest else None

    def _rehydrate_inflight(self) -> None:
        """Restore the partial epoch's metric history a preemption
        checkpoint carried (``inflight``), so the epoch summary of the
        resumed run reduces over exactly the same value stream an
        uninterrupted run would have — the per-epoch statistics half of
        the kill/resume bit-equivalence guarantee. Preemption bookkeeping
        keys are popped either way so they never leak into later epoch
        checkpoints or the CSV."""
        inflight = self.state.pop("inflight", None)
        self.state.pop("emergency_reason", None)
        self.state.pop("preempt_signal", None)
        if (
            inflight
            and int(self.state["current_iter"])
            % self.cfg.total_iter_per_epoch != 0
        ):
            self.total_losses = self._restore_total_losses(
                inflight.get("total_losses", {})
            )

    def _serialize_total_losses(self) -> Dict[str, List[Dict]]:
        """The in-epoch metric history as (dtype-tagged) JSON: float32
        device scalars, (k,)-stacked chunk arrays and host floats all
        round-trip exactly (every float32/float64 is exactly representable
        in JSON's shortest-roundtrip encoding), so the restored stream is
        bit-identical to the one the preempted run accumulated. The
        np.asarray here is a device->host sync — we are stopping anyway."""
        out: Dict[str, List[Dict]] = {}
        for key, vals in self.total_losses.items():
            out[key] = [
                {"dtype": str(np.asarray(v).dtype),
                 "value": np.asarray(v).tolist()}
                for v in vals
            ]
        return out

    @staticmethod
    def _restore_total_losses(serialized) -> Dict[str, List]:
        out: Dict[str, List] = {}
        for key, entries in serialized.items():
            vals = []
            for e in entries:
                try:
                    dt = np.dtype(e["dtype"])
                except TypeError:
                    dt = np.float64  # dtype from a newer build: values win
                vals.append(np.array(e["value"], dtype=dt))
            out[key] = vals
        return out

    def _truncate_stats_to_resume_point(self) -> None:
        """Rewrite ``summary_statistics.csv`` keeping only rows with
        ``epoch <= epochs covered by the resumed checkpoint``. The CSV row
        for an epoch lands before that epoch's async checkpoint finalizes,
        so a kill in between leaves a row from the dead run's future; the
        resumed run re-trains that epoch and the final CSV must read as if
        the kill never happened (the kill/resume equivalence tests compare
        it row-for-row against an uninterrupted run). Atomic tmp+replace,
        line-based so surviving rows keep their exact bytes."""
        if not self.is_primary:
            return
        path = os.path.join(self.logs_filepath, "summary_statistics.csv")
        if not os.path.isfile(path):
            return
        epochs_done = (
            int(self.state["current_iter"]) // self.cfg.total_iter_per_epoch
        )
        with open(path) as f:
            lines = f.readlines()
        if not lines:
            return
        header = lines[0].rstrip("\n").split(",")
        try:
            epoch_col = header.index("epoch")
        except ValueError:
            return
        kept = [lines[0]]
        dropped = 0
        for line in lines[1:]:
            fields = line.rstrip("\n").split(",")
            try:
                row_epoch = int(float(fields[epoch_col]))
            except (IndexError, ValueError):
                dropped += 1  # malformed (torn write at the kill): drop too
                continue
            if row_epoch <= epochs_done:
                kept.append(line)
            else:
                dropped += 1
        if not dropped:
            return
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.writelines(kept)
        os.replace(tmp, path)
        self._log(
            f"[resilience] dropped {dropped} summary CSV row(s) beyond "
            f"the resumed checkpoint (epoch > {epochs_done})"
        )

    def _on_retry(self, site: str, attempt: int, max_attempts: int,
                  error: str, backoff_s: float) -> None:
        """RetryPolicy observer: one loud stderr line + a telemetry
        ``retry`` record + a flight-recorder note per failed attempt, so a
        run that limped through transient I/O faults documents it."""
        print(
            f"[resilience] {site} attempt {attempt}/{max_attempts} failed "
            f"({error}); retrying in {backoff_s:.2f}s",
            file=sys.stderr,
            flush=True,
        )
        # the first retryable seam (resume restore) runs before telemetry
        # exists — stderr carries those
        telemetry = getattr(self, "telemetry", None)
        if telemetry is not None:
            telemetry.event(
                "retry",
                site=site,
                attempt=int(attempt),
                max_attempts=int(max_attempts),
                error=error,
                backoff_s=float(backoff_s),
            )
        recorder = getattr(self, "flight_recorder", None)
        if recorder is not None:
            recorder.note_event(
                "retry", site=site, attempt=int(attempt), error=error,
            )

    def _write_stats(self, fn, site: str):
        """Retry a NON-essential metrics write; on an exhausted budget skip
        it with a warning instead of killing the run — the telemetry twin
        of the row (and the checkpoint's experiment state) still carry the
        epoch, and the stats/checkpoint register sanity check tolerates the
        hole. Essential writes (checkpoints) go through ``self.retry``
        directly so exhaustion halts the run cleanly."""
        try:
            return self.retry.call(fn, site=site)
        except RetriesExhaustedError as e:
            print(
                f"[resilience] {site} write skipped after exhausted "
                f"retries: {e}",
                file=sys.stderr,
                flush=True,
            )
            return None

    def _prune_consumed_emergency(self) -> None:
        """Best-effort hygiene after an epoch checkpoint lands: a
        *preemption* emergency checkpoint whose iteration the run has now
        passed is fully superseded by ``latest`` — drop it so operators
        don't mistake a stale emergency for pending trouble. (The resume
        preference compares iterations, so leaving it behind would be
        harmless; a halt emergency is never touched.)"""
        if not self.is_primary:
            return
        try:
            emerg = peek_experiment_state(
                self.saved_models_filepath, "train_model", "emergency"
            )
            if (
                emerg is not None
                and emerg.get("emergency_reason") == "preemption"
                and int(emerg.get("current_iter", -1))
                <= int(self.state["current_iter"])
            ):
                remove_checkpoint(
                    self.saved_models_filepath, "train_model", "emergency"
                )
        except OSError:
            pass  # hygiene only — never load-bearing

    def _stamp_elastic_state(self) -> None:
        """Stamp the topology-portable resume keys into the experiment
        state just before any checkpoint write: the GLOBAL episode cursor
        (a pure function of the iteration and the global batch size —
        resilience/elastic.py) and the process count that wrote the
        checkpoint. A resume on a different host count re-enters the
        episode stream at exactly the cursor and logs the topology
        change."""
        import jax

        self.state["episode_cursor"] = elastic.episode_cursor_for_iter(
            int(self.state["current_iter"]), self.cfg.global_tasks_per_batch
        )
        self.state["process_count"] = int(jax.process_count())

    def _check_drain(self) -> None:
        """The dispatch-boundary preemption check. Single-process: a
        latched SIGTERM/SIGINT drains immediately (PR 6 behaviour).
        Multi-process: the latch only *publishes a drain request*; every
        process keeps training until the primary's drain commit names an
        iteration all processes can reach, then drains THERE — so the
        collective emergency checkpoint sees every process at the same
        step and is written exactly once (resilience/elastic.py)."""
        coordinator = self._drain_coordinator
        if coordinator is None:
            if self._preempt_signum is not None:
                self._preempt_exit()
            return
        import jax

        it = int(self.state["current_iter"])
        if self._preempt_signum is not None:
            if coordinator.request_drain(self._preempt_signum, it):
                print(
                    f"[elastic] process {jax.process_index()} published a "
                    f"drain request (signal {self._preempt_signum}, iter "
                    f"{it})",
                    file=sys.stderr,
                    flush=True,
                )
                self.telemetry.event(
                    "elastic",
                    event="drain_request",
                    iter=it,
                    signal=int(self._preempt_signum),
                )
        commit = coordinator.poll(it)
        if commit is not None and not self._drain_commit_logged:
            self._drain_commit_logged = True
            self.telemetry.event(
                "elastic",
                event="drain_commit",
                iter=it,
                drain_iter=int(commit["drain_iter"]),
                signal=int(commit.get("signal", signal.SIGTERM)),
                requested_by=int(commit.get("requested_by", -1)),
            )
        commit = coordinator.should_drain(it) if commit is not None else None
        if commit is not None:
            if self._preempt_signum is None:
                # this process never saw the scheduler's signal; the commit
                # carries it (the drain must still exit PREEMPT_EXIT_CODE)
                self._preempt_signum = int(
                    commit.get("signal", signal.SIGTERM)
                )
            print(
                f"[elastic] process {jax.process_index()} draining at "
                f"agreed iter {it} (commit drain_iter="
                f"{int(commit['drain_iter'])})",
                file=sys.stderr,
                flush=True,
            )
            self.telemetry.event(
                "elastic",
                event="drain_ack",
                iter=it,
                drain_iter=int(commit["drain_iter"]),
            )
            self._preempt_exit()

    def _install_signal_handlers(self) -> Optional[Dict]:
        """Install the graceful-preemption SIGTERM/SIGINT handlers for the
        duration of ``run_experiment`` (restored by the caller). Returns the
        previous handlers, or None when disabled / not on the main thread
        (signal.signal is main-thread-only; a builder driven from a worker
        thread keeps the process defaults)."""
        if not self.cfg.handle_preemption_signals:
            return None
        if threading.current_thread() is not threading.main_thread():
            return None
        previous = {}
        for sig in (signal.SIGTERM, signal.SIGINT):
            previous[sig] = signal.signal(sig, self._on_preempt_signal)
        return previous

    def _on_preempt_signal(self, signum, frame) -> None:
        """Latches the preemption request; the train loop drains it at the
        next dispatch boundary. A SECOND SIGINT raises KeyboardInterrupt —
        the operator escape hatch when the drain itself is stuck. (A first
        SIGINT after a scheduler SIGTERM only re-latches: one stray Ctrl-C
        must not abort the drain mid-emergency-checkpoint.)"""
        if (
            self._preempt_signum == int(signal.SIGINT)
            and signum == signal.SIGINT
        ):
            raise KeyboardInterrupt
        self._preempt_signum = int(signum)
        print(
            f"[resilience] received signal {signum}: draining at the next "
            "dispatch boundary (emergency checkpoint, then exit "
            f"{PREEMPT_EXIT_CODE})",
            file=sys.stderr,
            flush=True,
        )

    def _preempt_exit(self) -> None:
        """The preemption drain, at a train dispatch boundary: wait out the
        in-flight async checkpoint, write a RESUMABLE
        ``train_model_emergency`` checkpoint (tagged ``preemption`` and
        carrying the partial epoch's metric history), emit the telemetry
        ``preemption`` record + a forensic flight-recorder dump, and raise
        ``PreemptedError`` (a SystemExit with the distinct preemption exit
        code). ``continue_from_epoch='latest'`` on the restarted run picks
        this checkpoint up and resumes at the exact iteration."""
        from . import checkpoint as ckpt

        signum = int(self._preempt_signum)
        it = int(self.state["current_iter"])
        self._close_pbar()
        self._log(
            f"[resilience] preemption drain at iter {it} "
            f"(signal {signum})"
        )
        self._beat("preempt_drain")
        ckpt.wait_for_pending()  # pending async epoch save lands first
        self._stamp_elastic_state()
        exp_state = dict(self.state)
        exp_state["emergency_reason"] = "preemption"
        exp_state["preempt_signal"] = signum
        if it % self.cfg.total_iter_per_epoch != 0 and self.total_losses:
            exp_state["inflight"] = {
                "total_losses": self._serialize_total_losses()
            }
        self._beat("emergency_checkpoint")
        ckpt_path = self.retry.call(
            lambda: self.model.save_model(
                self.saved_models_filepath, "emergency", exp_state,
            ),
            site="ckpt_save",
        )
        ckpt.wait_for_pending()  # on disk before the exit, not after
        if self._drain_coordinator is not None and self.is_primary:
            # the drain is consumed: every process has observed the commit
            # (the collective emergency save above barriered them all), so
            # the coordination files can never strand a resumed run
            self._drain_coordinator.clear()
        self.telemetry.event(
            "preemption", iter=it, signal=signum, checkpoint=ckpt_path,
        )
        if self.flight_recorder is not None:
            self.flight_recorder.note_event(
                "preemption", iter=it, signal=signum, checkpoint=ckpt_path,
            )
            try:
                dump_dir = self.flight_recorder.dump(
                    "preemption",
                    it,
                    details={"signal": signum, "checkpoint": ckpt_path},
                    state_dump_fn=None,  # the emergency ckpt IS the state
                    force=True,
                )
            except Exception as e:  # noqa: BLE001 - forensics are garnish;
                # the preemption exit (with its checkpoint) must not become
                # a disk-full crash
                print(f"[resilience] preemption ring dump failed: {e!r}",
                      file=sys.stderr, flush=True)
                dump_dir = None
            if dump_dir is not None:
                self.telemetry.event(
                    "incident", iter=it, reason="preemption", path=dump_dir,
                )
        raise PreemptedError(signum, it, ckpt_path)

    # -- static analysis plumbing (analysis/) ------------------------------

    def _install_analysis(self) -> None:
        """``analysis_level != 'off'``: audit the canonical program family
        against the pinned contracts NOW — before an epoch of compute is
        sunk into a program that double-buffers its state or re-compiles
        every dispatch — and install the runtime retrace detector on the
        system's dispatch sites. Multi-device single-host builds
        additionally run the SPMD performance audit (analysis/spmd.py)
        under a 1xN hybrid mesh over the model's devices: sharding,
        per-axis collective census, static HBM budget and the roofline
        model — so an accidental store gather or an over-budget config
        fails the BUILD, not the pod job. 'warn' logs violations and
        telemeters retraces; 'strict' raises (AuditError here,
        RetraceError at the offending dispatch)."""
        import dataclasses as _dc

        import jax

        from ..analysis import auditor as audit_lib
        from ..analysis import contracts as contracts_lib

        cfg = self.cfg
        strict = cfg.analysis_level == "strict"
        if jax.process_count() > 1:
            self._log(
                "[analysis] build-time program audit skipped on multihost "
                "runs (every process would compile the audit family); the "
                "retrace detector is still installed"
            )
        else:
            baseline = contracts_lib.load_baseline()
            fingerprint = contracts_lib.config_fingerprint(
                _dc.asdict(cfg)
            )
            if baseline is not None and not contracts_lib.baseline_comparable(
                baseline,
                jax_version=jax.__version__,
                config_fingerprint=fingerprint,
            ):
                # CONTRACTS.json is pinned against the canonical audit
                # config (cli audit --pin); a real experiment config (or a
                # different jax) disarms the census-regression compare —
                # say so, the invariant contracts still run
                self._log(
                    "[analysis] pinned CONTRACTS.json baseline is not "
                    "comparable to this run (different jax version or "
                    "audit config); census regression checks skipped, "
                    "invariant contracts still enforced"
                )
            auditor = audit_lib.ProgramAuditor(
                cfg, baseline=baseline, config_fingerprint=fingerprint
            )
            reports = audit_lib.audit_system_programs(cfg, auditor=auditor)
            spmd_reports = []
            if self.model.mesh is not None:
                spmd_reports = self._audit_spmd(
                    baseline, fingerprint
                )
            violations = [
                v for r in list(reports) + spmd_reports for v in r.violations
            ]
            for v in violations:
                print(f"[analysis] CONTRACT VIOLATION {v}",
                      file=sys.stderr, flush=True)
            self._log(
                f"[analysis] program audit: {len(reports)} program(s)"
                + (
                    f" + {len(spmd_reports)} SPMD program(s)"
                    if spmd_reports else ""
                )
                + f", {len(violations)} violation(s)"
            )
            roofline_summary = None
            mesh_spec = None
            if spmd_reports:
                mesh_spec = spmd_reports[0].mesh_spec
                # surface the flagship train step's roofline in telemetry:
                # `cli inspect summary` prints it as the analysis line
                first = next(
                    (r for r in spmd_reports
                     if r.program.startswith("train_step[")
                     and r.roofline is not None),
                    None,
                )
                if first is not None:
                    roofline_summary = {
                        "program": first.program,
                        "bound": first.roofline.get("bound"),
                        "predicted_hfu": first.roofline.get("predicted_hfu"),
                        "predicted_mfu": first.roofline.get("predicted_mfu"),
                        "flops_per_task": first.roofline.get(
                            "flops_per_task"
                        ),
                    }
            self.telemetry.event(
                "analysis",
                programs=len(reports) + len(spmd_reports),
                violations=len(violations),
                mesh=mesh_spec,
                roofline=roofline_summary,
            )
            if violations and strict:
                raise contracts_lib.AuditError(violations)
        self.retrace_detector = audit_lib.RetraceDetector(
            on_retrace=self._on_retrace, strict=strict
        )
        self.model.retrace_detector = self.retrace_detector

    def _audit_spmd(self, baseline, fingerprint):
        """The SPMD half of the build-time audit: the program family under
        a 1xN hybrid mesh over the model's task-mesh devices (single-host
        multi-device builds only — the callers gate). Failures inside the
        audit itself degrade to a warning: the audit must never be the
        thing that kills a run the contracts would have passed."""
        from ..analysis import spmd as spmd_lib

        devices = list(self.model.mesh.devices.flat)
        try:
            mesh = spmd_lib.build_audit_mesh(1, len(devices), devices)
            auditor = spmd_lib.SpmdAuditor(
                self.cfg, mesh, baseline=baseline,
                config_fingerprint=fingerprint,
            )
            return spmd_lib.audit_spmd_programs(
                self.cfg, mesh=mesh, auditor=auditor
            )
        except Exception as e:  # noqa: BLE001 - best-effort build audit
            print(
                f"[analysis] SPMD audit unavailable ({e!r}); sharding/"
                "collective/HBM contracts not checked at build time",
                file=sys.stderr, flush=True,
            )
            return []

    def _on_retrace(self, site: str, signature: str,
                    n_signatures: int) -> None:
        """RetraceDetector callback: one loud stderr line + a telemetry
        ``retrace`` record (schema v4) + a flight-recorder note per mid-run
        retrace — runs BEFORE the strict-mode raise, so even a fatal
        retrace documents itself."""
        it = int(self.state["current_iter"])
        print(
            f"[analysis] RETRACE at iter {it}: dispatch site {site!r} "
            f"compiled its {n_signatures}th distinct abstract signature "
            f"({signature}) — mid-run recompiles should never happen",
            file=sys.stderr,
            flush=True,
        )
        self.telemetry.event(
            "retrace",
            iter=it,
            site=site,
            signature=signature,
            n_signatures=int(n_signatures),
        )
        if self.flight_recorder is not None:
            self.flight_recorder.note_event(
                "retrace", iter=it, site=site, signature=signature,
            )

    # -- telemetry plumbing ------------------------------------------------

    def _beat(self, stage: str):
        """Report train-loop progress to the hang watchdog."""
        if self.watchdog is not None:
            self.watchdog.beat(stage)

    def _on_watchdog_stall(self, record: Dict):
        """Called from the watchdog thread when progress stops: one loud
        stderr line (every host) + the full diagnostic record with
        all-thread stacks in the telemetry log (primary) — or, with
        telemetry off, the stacks on stderr so the diagnosis is never
        lost."""
        print(
            f"[watchdog] no progress for "
            f"{record['seconds_since_progress']:.1f}s "
            f"(stage={record['stage']!r}, beats={record['beat_count']})",
            file=sys.stderr,
            flush=True,
        )
        if self.telemetry.enabled:
            # since schema v2 the stall record also carries the flight-
            # recorder tail and the last evaluated health entry (when the
            # monitor is on): a hang and a divergence preceding it are
            # diagnosable from ONE record, without cross-referencing the
            # incident directory
            context = {}
            if self.flight_recorder is not None:
                context["recorder_tail"] = self.flight_recorder.snapshot()[-8:]
            if self.health_monitor is not None:
                context["last_health"] = self.health_monitor.last_entry
            self.telemetry.event("watchdog_stall", **record, **context)
        else:
            for name, stack in record["stacks"].items():
                print(f"[watchdog] thread {name}:\n{stack}",
                      file=sys.stderr, flush=True)
        if self.flight_recorder is not None:
            # ring + manifest only: no state checkpoint from the watchdog
            # thread — fetching device state while the device is the thing
            # that is wedged would hang the diagnostic itself. force=True:
            # the recorder cooldown is reason-agnostic, and an anomaly dump
            # moments before the hang (divergence-then-wedge) must not
            # swallow the stall incident; the watchdog itself fires once
            # per stall, so this cannot spam.
            try:
                path = self.flight_recorder.dump(
                    "watchdog_stall",
                    int(self.state["current_iter"]),
                    details={
                        "stage": record["stage"],
                        "seconds_since_progress":
                            record["seconds_since_progress"],
                        "beat_count": record["beat_count"],
                    },
                    state_dump_fn=None,
                    force=True,
                )
            except Exception as e:  # noqa: BLE001 - best-effort: an I/O
                # failure in the diagnostic must not crash the watchdog
                # thread before the stacks above reach the log
                print(f"[watchdog] ring dump failed: {e!r}",
                      file=sys.stderr, flush=True)
                path = None
            if path is not None:
                print(f"[watchdog] flight-recorder ring dumped to {path}",
                      file=sys.stderr, flush=True)
                self.telemetry.event(
                    "incident",
                    iter=int(self.state["current_iter"]),
                    reason="watchdog_stall",
                    path=path,
                )

    def _pop_health(self, losses: Dict) -> bool:
        """Divert the on-device health probes out of the metric dict (never
        into the reference-compatible CSV) and hand them to the monitor,
        which evaluates them one dispatch behind (see telemetry/health.py).
        Popped unconditionally: a probes-on config must not leak the dict
        into the epoch summary even if the monitor is absent.

        Returns True when the monitor latched a halt decision. The CALLER
        escalates, after advancing ``current_iter`` past the dispatch it
        just enqueued: the emergency checkpoint fetches ``model.state``
        (which contains that dispatch's updates) and must pair it with a
        counter that covers them, or a resumed run would re-apply the
        in-flight update(s) and skew the LR/MSL schedule."""
        health = losses.pop("health", None)
        if health is not None and self.health_monitor is not None:
            self.health_monitor.observe(
                int(self.state["current_iter"]), health
            )
            return self.health_monitor.should_halt
        return False

    def _halt_for_divergence(self):
        """The ``health_level='halt'`` escalation: drain the monitor, write
        a RESUMABLE emergency checkpoint (``train_model_emergency`` — the
        divergent state itself, loadable via
        ``model.load_model(dir, 'emergency')`` for postmortem or a rolled-
        back restart) plus a final forced incident dump, then raise
        ``TrainingDivergedError`` instead of training on garbage. Multihost
        runs reach this point on every host at the same iteration (the
        probes reduce replicated metrics), so the collective checkpoint
        save is safe; only the primary writes the ring dump."""
        from ..telemetry import TrainingDivergedError
        from . import checkpoint as ckpt

        mon = self.health_monitor
        mon.flush()  # the deferred last dispatch: we're stopping anyway
        anomaly = mon.halt_anomaly or {}
        it = int(anomaly.get("iter", self.state["current_iter"]))
        self._beat("emergency_checkpoint")
        self._stamp_elastic_state()
        # essential write behind the retry seam: a transient fault must not
        # lose the divergent state the postmortem needs
        ckpt_path = self.retry.call(
            lambda: self.model.save_model(
                self.saved_models_filepath, "emergency", self.state,
            ),
            site="ckpt_save",
        )
        ckpt.wait_for_pending()  # on disk before the raise, not after
        dump_dir = None
        if self.flight_recorder is not None:
            try:
                dump_dir = self.flight_recorder.dump(
                    "halt",
                    it,
                    details={
                        "anomaly": anomaly,
                        "anomalous_iterations":
                            mon.detector.anomalous_iterations,
                        "patience": mon.patience,
                        "emergency_checkpoint": ckpt_path,
                    },
                    state_dump_fn=mon.state_dump_fn,
                    force=True,  # a routine anomaly dump moments earlier
                )                # must not cooldown-swallow the forensics
            except Exception as e:  # noqa: BLE001 - the dump is best-effort
                # garnish: TrainingDivergedError (with the emergency
                # checkpoint already on disk) must still be the exception
                # the caller sees, not a disk-full OSError
                print(f"[health] halt incident dump failed: {e!r}",
                      file=sys.stderr, flush=True)
            if dump_dir is not None:
                self.telemetry.event(
                    "incident", iter=it, reason="halt", path=dump_dir,
                )
        msg = (
            f"training diverged: {anomaly.get('reason', 'anomaly')} at "
            f"iter {it} ({mon.detector.anomalous_iterations} anomalous "
            f"iteration(s) >= health_patience={mon.patience}); emergency "
            f"checkpoint: {ckpt_path}, incident dump: {dump_dir}"
        )
        print(f"[health] HALT — {msg}", file=sys.stderr, flush=True)
        raise TrainingDivergedError(
            msg, iter_at_halt=it, dump_dir=dump_dir,
            checkpoint_path=ckpt_path,
        )

    def _dump_state_for_incident(self, dump_dir: str) -> None:
        """State-checkpoint hook the flight recorder calls inside an
        anomaly incident dump (single-host; the monitor passes None on
        multihost meshes)."""
        self._beat("incident_state_dump")
        self.model.dump_state(dump_dir, self.state)

    def _pop_dynamics(self, losses: Dict, n_iters: int):
        """Divert the on-device dynamics stacks (still device arrays) out of
        the metric dict before accumulation; they flush at epoch-summary
        time, never into the reference-compatible CSV."""
        dyn = losses.pop("dynamics", None)
        if dyn is not None:
            self._dyn_pending.append(
                (int(self.state["current_iter"]), n_iters, dyn)
            )

    def _flush_dynamics(self):
        """Emit one ``dynamics`` record per fused dispatch. ONE batched
        device->host fetch for the whole epoch's buffer (jax.device_get over
        the list) — per-leaf np.asarray would issue thousands of sequential
        transfers per epoch over a networked device transport."""
        pending, self._dyn_pending = self._dyn_pending, []
        if not self.telemetry.enabled or not pending:
            return
        import jax

        pending = jax.device_get(pending)
        for iter_start, n_iters, dyn in pending:
            if isinstance(dyn, list):
                # multihost fallback: per-iteration dicts, one record each
                for j, d in enumerate(dyn):
                    self.telemetry.dynamics(iter_start + j, 1, d)
            else:
                self.telemetry.dynamics(iter_start, n_iters, dyn)

    # -- phases -----------------------------------------------------------

    def train_iteration(self, train_sample, epoch_idx):
        # the sample passes through whole: the system dispatches on its form
        # (pixel tuple — x_s, x_t, y_s, y_t leading — or IndexBatch)
        self._maybe_profile_step()
        self._beat("train_dispatch")
        # the span covers the ENQUEUE interval (the dispatch is
        # asynchronous; the device executes under the one-step lag) —
        # exactly the causal timeline reading, and zero added syncs
        with self.tracer.span(
            "train_dispatch", cat="train",
            iter=int(self.state["current_iter"]), k=1,
        ):
            losses = self.model.run_train_iter(train_sample, epoch=epoch_idx)
        self._pop_dynamics(losses, 1)
        halt = self._pop_health(losses)
        self._accumulate(losses, self.total_losses)
        self.state["current_iter"] += 1
        # fault-injection heartbeat: publishes the completed-iteration
        # counter (iter=N conditions) and delivers pseudo-site `signal`
        # faults — a handled SIGTERM lands in _on_preempt_signal and is
        # drained at the loop's next boundary check
        faults.tick(int(self.state["current_iter"]))
        # with the model's one-step-lag sync, tick intervals equal device
        # step time at steady state (one step in flight, host waits on k-1)
        self.step_timer.tick()
        self._steps_this_run += 1
        if halt:
            # raised on the train-loop thread, so the loop unwinds cleanly;
            # deferred past the increment so the emergency checkpoint's
            # counter covers the update already in model.state (resumable)
            self._halt_for_divergence()

    def train_iterations(self, train_samples, epoch_idx):
        """Chunked variant: len(train_samples) updates in ONE device
        dispatch (``steps_per_dispatch``). Per-iteration metrics are still
        accumulated individually; the step timer ticks once per dispatch
        (its percentiles then measure dispatch latency, k iterations
        each)."""
        if len(train_samples) == 1:
            self.train_iteration(train_samples[0], epoch_idx)
            return
        self._maybe_profile_step()
        self._beat("train_dispatch")
        with self.tracer.span(
            "train_dispatch", cat="train",
            iter=int(self.state["current_iter"]), k=len(train_samples),
        ):
            losses = self.model.run_train_iters(
                list(train_samples), epoch=epoch_idx
            )
        self._pop_dynamics(losses, len(train_samples))
        halt = self._pop_health(losses)
        # ONE accumulation per chunk: device metrics arrive (k,)-stacked and
        # the epoch summary flattens them — per-iteration slicing here would
        # issue 2k tiny device programs per chunk (see run_train_iters)
        self._accumulate(losses, self.total_losses)
        self.state["current_iter"] += len(train_samples)
        faults.tick(int(self.state["current_iter"]))  # see train_iteration
        self.step_timer.tick()
        self._steps_this_run += len(train_samples)
        if halt:
            self._halt_for_divergence()

    def _sync_device(self):
        """Drain in-flight dispatches (trace-window stop barrier)."""
        import jax

        jax.block_until_ready(self.model.state.net)

    def _maybe_profile_step(self):
        """Scheduled trace capture: iterations [profile_start_step,
        profile_start_step + profile_num_steps) of ``profile_epoch``
        (-1 = this run's first steps; iteration 0 is compile, not steady
        state) when ``profile_trace_dir`` is set — see TraceWindow. The
        on-demand profiler polls its runtime trigger (logs/PROFILE_REQUEST
        or SIGUSR2) unconditionally — live-incident capture needs no
        config."""
        cfg = self.cfg
        self.ondemand_profiler.step(sync=self._sync_device)
        if not cfg.profile_trace_dir:
            return
        it = int(self.state["current_iter"])
        self.trace_window.step(
            epoch=it // cfg.total_iter_per_epoch,
            step_in_epoch=it % cfg.total_iter_per_epoch,
            step_in_run=self._steps_this_run,
            sync=self._sync_device,
        )

    def evaluation_iteration(self, val_sample, total_losses):
        self._beat("eval_dispatch")
        with self.tracer.span("eval_chunk", cat="eval", k=1):
            losses, _ = self.model.run_validation_iter(val_sample)
        self._accumulate(losses, total_losses)

    def evaluation_iterations(self, val_samples, total_losses):
        """Chunked variant: len(val_samples) eval passes in ONE device
        dispatch (``eval_batches_per_dispatch``); metrics arrive
        (k,)-stacked and the epoch summary flattens them — same contract
        as ``train_iterations``."""
        if len(val_samples) == 1:
            self.evaluation_iteration(val_samples[0], total_losses)
            return
        self._beat("eval_dispatch")
        with self.tracer.span(
            "eval_chunk", cat="eval", k=len(val_samples),
        ):
            losses, _ = self.model.run_validation_iters(list(val_samples))
        self._accumulate(losses, total_losses)

    def run_validation_epoch(
        self, pre_summary_fn=None
    ) -> Dict[str, float]:
        """The fused validation sweep. ``pre_summary_fn`` (the
        epoch-boundary overlap, ISSUE 11): host work to run AFTER the last
        fused eval dispatch is enqueued but BEFORE its metrics are synced
        — the train loop passes its epoch-summary reduction here, so the
        device->host fetch of the epoch's train metrics overlaps the
        in-flight eval tail instead of serializing behind it. The wall
        time that work took under an in-flight dispatch is recorded as
        ``overlap_ms`` (per-epoch ``dispatch`` telemetry, schema v7); its
        return value is picked up from ``self._pre_summary_result``."""
        total_losses: Dict[str, List[float]] = {}
        pbar_sums: Dict[str, tuple] = {}
        n_batches = int(self.cfg.num_evaluation_tasks / self.cfg.batch_size)
        chunk_k = max(1, int(self.cfg.eval_batches_per_dispatch))
        pbar = self._pbar(n_batches, "val")
        pending: List = []
        try:
            for val_sample in self.data.get_val_batches(total_batches=n_batches):
                pending.append(val_sample)
                if len(pending) < chunk_k:
                    continue
                n_flushed = len(pending)
                self.evaluation_iterations(pending, total_losses)
                pending = []
                if pbar is not None:  # interactive: pay the sync for liveness
                    if n_flushed > 1:
                        pbar.update(n_flushed - 1)
                    self._pbar_tick(
                        pbar,
                        self._running_summary(pbar_sums, total_losses, "val"),
                        "val",
                    )
            if pending:  # tail chunk when chunk_k doesn't divide n_batches
                self.evaluation_iterations(pending, total_losses)
                if pbar is not None:
                    pbar.update(len(pending))
        finally:
            if pbar is not None:
                pbar.close()
        self._pre_summary_result = None
        if pre_summary_fn is not None:
            # the last eval chunk is still in flight (the system's
            # one-step-lag never blocks on the dispatch it just enqueued)
            # — the epoch_summary span therefore OVERLAPS the in-flight
            # eval tail on the trace timeline, which is the PR 11
            # boundary overlap made visible as overlapping intervals
            t0 = time.perf_counter()
            with self.tracer.span(
                "epoch_summary", cat="train", epoch=int(self.epoch),
            ):
                self._pre_summary_result = pre_summary_fn()
            self._last_overlap_ms = (time.perf_counter() - t0) * 1e3
        # the one synchronization point: reduce the val metric stacks
        with self.tracer.span(
            "eval_sync", cat="eval", epoch=int(self.epoch),
        ):
            return self.build_summary_dict(total_losses, "val")

    def _stream_metrics(self) -> Dict[str, float]:
        """The loader producer's cumulative stats (episode assembly, queue
        stall, prefetch-queue depth) over the epoch just finished, as
        per-batch rates — visible in normal training runs' epoch summary,
        not only under bench.py."""
        stream = self.data.pop_stream_stats()
        denom = max(1, int(stream["batches"]))
        metrics = {
            "stream_assembly_ms_per_batch": stream["assembly_s"] / denom * 1e3,
            "stream_stall_ms_per_batch": stream["stall_s"] / denom * 1e3,
            "stream_queue_depth_mean": stream["depth_sum"] / denom,
        }
        self.telemetry.event(
            "stream",
            epoch=int(self.epoch),
            batches=int(stream["batches"]),
            assembly_ms_per_batch=metrics["stream_assembly_ms_per_batch"],
            stall_ms_per_batch=metrics["stream_stall_ms_per_batch"],
            queue_depth_mean=metrics["stream_queue_depth_mean"],
        )
        return metrics

    def pack_and_save_metrics(self, train_losses, val_losses):
        """Per-epoch CSV/JSON metric rows (experiment_builder.py:208-245),
        plus per-step timing and loader stream stats the reference never
        had; mirrors the row to the telemetry sinks and flushes the
        buffered on-device dynamics stacks."""
        timing = self.step_timer.summary()
        epoch_summary = {
            **train_losses, **val_losses, **timing, **self._stream_metrics(),
        }
        self.step_timer.reset()
        self.state.setdefault("per_epoch_statistics", {})
        for key, value in epoch_summary.items():
            self.state["per_epoch_statistics"].setdefault(key, []).append(value)
        epoch_summary["epoch"] = self.epoch
        epoch_summary["epoch_run_time"] = time.perf_counter() - self.start_time
        if self.create_summary_csv:
            self._csv_keys = list(epoch_summary.keys())
            created = True
            if self.is_primary:
                created = self._write_stats(
                    lambda: save_statistics(
                        self.logs_filepath, self._csv_keys, create=True
                    ),
                    site="stats_write",
                ) is not None
            # an exhausted header write keeps this True so the NEXT epoch
            # re-attempts the header (create='w' truncates any partial
            # file) and this epoch's row append below is skipped — clearing
            # it unconditionally would let later successful appends build a
            # headerless CSV that breaks resume's header read
            self.create_summary_csv = not created
        if self._csv_keys is None:
            # resumed run: append in the on-disk header's column order — a
            # header written by older code (fewer metric columns) must not
            # get rows shifted out of register by newly-grown keys
            self._csv_keys = (
                self._existing_csv_header() or list(epoch_summary.keys())
            )
            if set(self._csv_keys) != set(epoch_summary):
                self._log(
                    "[builder] resumed summary CSV has a different column "
                    "set than this build produces; rows stay aligned to "
                    "the existing header, extra metrics appear in "
                    "summary_statistics.json / telemetry only"
                )
        self.start_time = time.perf_counter()
        self._log(f"epoch {self.epoch} -> " + ", ".join(
            f"{k}: {v:.4f}" for k, v in epoch_summary.items()
            if "loss" in k or "accuracy" in k
        ))
        if self.is_primary and not self.create_summary_csv:
            # non-essential: retried, then skipped on exhaustion (the epoch
            # telemetry record and the checkpoint's experiment state still
            # carry the numbers); also skipped while the header itself is
            # still owed — a row must never land before its header
            self._write_stats(
                lambda: save_statistics(
                    self.logs_filepath,
                    [epoch_summary.get(k, "") for k in self._csv_keys],
                ),
                site="stats_write",
            )
        # structured twins of the CSV row: epoch scalars (+ TensorBoard
        # mirror), dispatch-timing stats, device memory vs the store
        # registry's expectation, and the buffered on-device dynamics
        self.telemetry.epoch_scalars(self.epoch, epoch_summary)
        if self.telemetry.enabled:
            if timing:
                # schema v7: the dispatch record carries the epoch-boundary
                # overlap (ms of train-summary host work that ran under the
                # in-flight eval tail + how many phase-transition lag
                # blocks the system skipped) and the step's accumulation
                # setting, so `cli inspect summary` can print utilization
                # without the run's stdout
                overlap = self.model.pop_overlap_stats()
                self.telemetry.event(
                    "dispatch", epoch=int(self.epoch), **timing,
                    overlap_ms=(
                        round(self._last_overlap_ms, 3)
                        if self._last_overlap_ms is not None else None
                    ),
                    boundary_overlaps=int(overlap["boundary_overlaps"]),
                    accum_steps=int(self.cfg.meta_accum_steps),
                )
            self.telemetry.event(
                "device_memory",
                epoch=int(self.epoch),
                **self.model.device_memory_stats(),
            )
        self._flush_dynamics()
        # health probes still deferred from the epoch's last dispatch: the
        # summary above already synced the device, so this costs nothing
        if self.health_monitor is not None:
            self.health_monitor.flush()
            if self.health_monitor.should_halt:
                self._halt_for_divergence()
        if self.flight_recorder is not None:
            # epoch marker in the ring: a dumped ring shows where in the
            # run its steps sat
            self.flight_recorder.note_event(
                "epoch",
                epoch=int(self.epoch),
                **{
                    k: float(epoch_summary[k])
                    for k in ("train_loss_mean", "train_accuracy_mean",
                              "val_loss_mean", "val_accuracy_mean")
                    if k in epoch_summary
                },
            )

    # -- the loop (experiment_builder.py:302-371) -------------------------

    def run_experiment(self):
        # graceful preemption: SIGTERM/SIGINT latch a drain request for the
        # duration of the run (previous handlers restored on every exit
        # path, so nested/test-harness use never leaks a handler)
        previous_handlers = self._install_signal_handlers()
        # SIGUSR2 = "profile the next N dispatches" (main-thread runs
        # only; the PROFILE_REQUEST file trigger works everywhere)
        self.ondemand_profiler.install_signal_handler()
        if self.watchdog is not None:
            self.watchdog.start()
        try:
            return self._run_experiment()
        finally:
            # flush the in-flight async checkpoint: the caller (and the
            # controlled-pause sys.exit) must find every save on disk. A
            # failed write re-raises here, but must not lose the trace below
            from . import checkpoint as ckpt

            try:
                self._beat("checkpoint_barrier")
                ckpt.wait_for_pending()
            finally:
                if previous_handlers is not None:
                    for sig, handler in previous_handlers.items():
                        signal.signal(sig, handler)
                # SIGUSR2 too — the profiler handler closure would
                # otherwise outlive the run (and its telemetry sink)
                self.ondemand_profiler.uninstall_signal_handler()
                # the trace only materialises at stop — don't lose it when
                # the run ends/pauses/raises before profile_num_steps
                # completes (scheduled and on-demand windows alike)
                self.trace_window.close(self._sync_device)
                self.ondemand_profiler.close(self._sync_device)
                if self.watchdog is not None:
                    self.watchdog.stop()
                # dynamics/health buffered since the last epoch flush
                # (partial epoch at pause/crash), then the run_end marker
                self._flush_dynamics()
                if self.health_monitor is not None:
                    try:
                        self.health_monitor.flush()
                    except Exception as e:  # noqa: BLE001 - the pending
                        # payload may be poisoned by the very device failure
                        # that is unwinding this finally; evaluating it must
                        # not mask that exception or lose run_end below
                        print(f"[health] final flush failed: {e!r}",
                              file=sys.stderr, flush=True)
                self.telemetry.close()

    def _close_pbar(self):
        if self._active_pbar is not None:
            self._active_pbar.close()
            self._active_pbar = None

    def _run_experiment(self):
        cfg = self.cfg
        total_iters = cfg.total_epochs * cfg.total_iter_per_epoch
        try:
            return self._train_loop(cfg, total_iters)
        finally:
            self._close_pbar()

    def _train_loop(self, cfg, total_iters):
        while (
            self.state["current_iter"] < total_iters
            and not cfg.evaluate_on_test_set_only
        ):
            remaining = total_iters - self.state["current_iter"]
            self._active_pbar = self._pbar(
                cfg.total_iter_per_epoch
                - self.state["current_iter"] % cfg.total_iter_per_epoch,
                f"train epoch {self.epoch}",
            )
            # chunked dispatch: accumulate steps_per_dispatch samples and
            # flush them as one device program; always flush at the epoch
            # boundary so a chunk never spans an epoch (LR/MSL/order are
            # epoch-functions)
            dispatch_k = max(1, int(cfg.steps_per_dispatch))
            pending: List = []
            for train_sample in self.data.get_train_batches(
                total_batches=remaining, augment_images=self.augment_flag
            ):
                pending.append(train_sample)
                at_boundary = (
                    self.state["current_iter"] + len(pending)
                ) % cfg.total_iter_per_epoch == 0
                if len(pending) < dispatch_k and not at_boundary:
                    continue
                epoch_idx = self.state["current_iter"] / cfg.total_iter_per_epoch
                n_flushed = len(pending)
                self.train_iterations(pending, epoch_idx)
                pending = []
                if self._active_pbar is not None:
                    # interactive: pay the device sync for live numbers;
                    # batch runs stay fully pipelined (no per-step sync)
                    if n_flushed > 1:
                        self._active_pbar.update(n_flushed - 1)
                    self._pbar_tick(
                        self._active_pbar,
                        self._running_summary(
                            self._pbar_sums, self.total_losses, "train"
                        ),
                        "train",
                    )

                if self.state["current_iter"] % cfg.total_iter_per_epoch == 0:
                    self._close_pbar()
                    # double-buffered epoch boundary: the fused eval
                    # dispatches are enqueued FIRST, then the train-side
                    # epoch summary (a device->host reduction over the
                    # whole epoch's buffered metrics) runs while the eval
                    # tail is still executing — see run_validation_epoch
                    val_losses = self.run_validation_epoch(
                        pre_summary_fn=lambda: self.build_summary_dict(
                            self.total_losses, "train"
                        )
                    )
                    train_losses = self._pre_summary_result
                    if val_losses["val_accuracy_mean"] > self.state["best_val_acc"]:
                        self._log(
                            f"Best validation accuracy "
                            f"{val_losses['val_accuracy_mean']:.4f}"
                        )
                        self.state["best_val_acc"] = val_losses["val_accuracy_mean"]
                        self.state["best_val_iter"] = self.state["current_iter"]
                        self.state["best_epoch"] = int(
                            self.state["best_val_iter"] // cfg.total_iter_per_epoch
                        )
                    self.epoch += 1
                    self.state.update(train_losses)
                    self.state.update(val_losses)

                    # metrics BEFORE the checkpoint writes (deliberate
                    # divergence from the reference's :352-365 order): the
                    # epoch-N checkpoint must carry its own epoch's
                    # per_epoch_statistics row, or a resumed run's stat rows
                    # shift one checkpoint out of register — misranking the
                    # final ensemble and, worse, mis-PRUNING checkpoints.
                    # Worst crash case now is a duplicate CSV row for a
                    # re-trained epoch (cosmetic) instead of a permanently
                    # missing stat row (corrupting).
                    self.pack_and_save_metrics(train_losses, val_losses)
                    # dual checkpoint: epoch-numbered + latest (:190-206) —
                    # ONE save whose host-side clone materialises `latest`
                    # (one device->host serialization; the disk write
                    # overlaps the next epoch's training, see checkpoint.py)
                    self._beat("checkpoint_save")
                    # surface a PREVIOUS epoch's async-finalize failure
                    # BEFORE entering the retry: that write's host snapshot
                    # is gone, so it is not retryable — inside the retry it
                    # would be mis-attributed to THIS save, absorbed on the
                    # next attempt, and the run would train on with the
                    # previous checkpoint permanently missing
                    wait_for_pending()
                    # topology-portable resume keys (episode cursor +
                    # writing process count) ride every checkpoint
                    self._stamp_elastic_state()
                    # essential write: transient failures retried with
                    # backoff; an exhausted budget halts the run cleanly
                    # (RetriesExhaustedError) — training past a lost
                    # checkpoint would silently widen the crash window
                    with self.tracer.span(
                        "checkpoint", cat="train", epoch=int(self.epoch),
                    ):
                        ckpt_path = self.retry.call(
                            lambda: self.model.save_model(
                                self.saved_models_filepath, int(self.epoch),
                                self.state, also_latest=True,
                            ),
                            site="ckpt_save",
                        )
                    self._prune_consumed_emergency()
                    self.telemetry.event(
                        "checkpoint",
                        epoch=int(self.epoch),
                        path=ckpt_path,
                        also_latest=True,
                    )
                    if self.flight_recorder is not None:
                        self.flight_recorder.note_event(
                            "checkpoint", epoch=int(self.epoch),
                            path=ckpt_path,
                        )
                    self._prune_saved_models()
                    self.total_losses = {}
                    self._pbar_sums = {}
                    self.epochs_done_in_this_run += 1
                    if self.is_primary:
                        self._write_stats(
                            lambda: save_to_json(
                                os.path.join(
                                    self.logs_filepath,
                                    "summary_statistics.json",
                                ),
                                self.state["per_epoch_statistics"],
                            ),
                            site="json_write",
                        )
                    if self.epochs_done_in_this_run >= cfg.total_epochs_before_pause:
                        # controlled pause for preemptible clusters (:367-370)
                        self._log(
                            f"pause after {self.epochs_done_in_this_run} epochs"
                        )
                        sys.exit()
                    if self.state["current_iter"] < total_iters:
                        self._active_pbar = self._pbar(
                            cfg.total_iter_per_epoch, f"train epoch {self.epoch}"
                        )
                # drained AFTER the epoch-boundary block: a signal that
                # lands near a boundary lets the epoch finish its
                # stats/checkpoint bookkeeping first, so the resumed
                # run's history has no hole. Multi-process runs route
                # through the coordinated drain (resilience/elastic.py):
                # a local latch publishes a request, and EVERY process —
                # signalled or not — drains at the committed iteration
                self._check_drain()
            if pending:
                # safety net: the loader always ends at an epoch boundary,
                # but a truncated stream must not drop trained-sample work
                self.train_iterations(
                    pending,
                    self.state["current_iter"] / cfg.total_iter_per_epoch,
                )
                pending = []
            self._close_pbar()
        return self.evaluated_test_set_using_the_best_models(top_n_models=5)

    def _prune_saved_models(self) -> None:
        """Honor ``max_models_to_save`` (config.py — the reference parses it
        but never acts on it, keeping every epoch's checkpoint on disk,
        experiment_builder.py:190-206).  Keep ``latest`` plus the top-K
        epochs by validation accuracy — the same ``argsort`` ranking the
        final top-5 ensemble uses (``evaluated_test_set_using_the_best_
        models``), so pruning can never delete a checkpoint the ensemble
        will ask for as long as K >= its ``top_n_models``.  K <= 0 disables
        pruning.
        """
        k = int(self.cfg.max_models_to_save)
        if k <= 0 or not self.is_primary:
            return
        val_acc = np.asarray(
            self.state["per_epoch_statistics"]["val_accuracy_mean"],
            dtype=float,
        )
        if not self._stats_cover_on_disk_checkpoints(
            len(val_acc), "skipping pruning"
        ):
            return
        # stat row i corresponds to checkpoint i+1 (1-based epoch counter at
        # save time — the ensemble's model_idx + 1 mapping). kind='stable' +
        # reverse = ties broken toward the LATER epoch, identically in every
        # prune and in the final ensemble ranking; an unstable sort could
        # order tied epochs differently between the epoch-N prune and the
        # final length-M ranking and delete a checkpoint the ensemble then
        # asks for (val accuracies are quantized to 1/num_evaluation_tasks,
        # so exact ties are common)
        keep = {
            int(i) + 1
            for i in np.argsort(val_acc, kind="stable")[::-1][:k]
        }
        for epoch_idx in range(1, len(val_acc) + 1):
            if epoch_idx not in keep:
                remove_checkpoint(
                    self.saved_models_filepath, "train_model", epoch_idx
                )

    def _existing_csv_header(self) -> Optional[List[str]]:
        """First row of the on-disk summary CSV (None when absent/empty)."""
        import csv

        path = os.path.join(self.logs_filepath, "summary_statistics.csv")
        try:
            with open(path) as f:
                header = next(csv.reader(f))
        except (OSError, StopIteration):
            return None
        return header or None

    def _highest_epoch_checkpoint_index(self) -> int:
        """Largest N with a finalized ``train_model_N`` directory on disk
        (0 when none). In-flight ``.tmp`` writes don't count — they are not
        loadable checkpoints yet."""
        import re

        highest = 0
        try:
            names = os.listdir(self.saved_models_filepath)
        except OSError:
            return 0
        for name in names:
            m = re.fullmatch(r"train_model_(\d+)", name)
            if m and os.path.isdir(
                os.path.join(self.saved_models_filepath, name)
            ):
                highest = max(highest, int(m.group(1)))
        return highest

    def _stats_cover_on_disk_checkpoints(self, n_rows: int, what: str) -> bool:
        """Sanity-check the 'stat row i <-> checkpoint i+1' register before
        acting on it: checkpoints written by code that saved BEFORE recording
        metrics (the pre-reorder order) can sit one epoch ahead of
        per_epoch_statistics after a crash+resume, and ranking such a history
        would prune/ensemble the wrong epoch's checkpoint (ADVICE.md r5)."""
        highest = self._highest_epoch_checkpoint_index()
        if highest <= n_rows:
            return True
        self._log(
            f"[builder] WARNING: {what}: on-disk epoch checkpoints reach "
            f"train_model_{highest} but per_epoch_statistics has only "
            f"{n_rows} val rows — the stat-row/checkpoint register is off "
            "(history written by a pre-reorder run?); ranking it could "
            "target the wrong epoch's checkpoint"
        )
        return False

    # -- final test ensemble (experiment_builder.py:247-300) --------------

    def evaluated_test_set_using_the_best_models(self, top_n_models: int = 5):
        if self.cfg.max_models_to_save > 0:
            # pruning kept only the top-K epoch checkpoints; asking the
            # ensemble for more would load checkpoints that no longer exist
            top_n_models = min(top_n_models, int(self.cfg.max_models_to_save))
        per_epoch = self.state["per_epoch_statistics"]
        val_acc = np.copy(per_epoch["val_accuracy_mean"])
        self._stats_cover_on_disk_checkpoints(
            len(val_acc), "ensembling anyway"
        )
        # kind='stable': must break ties exactly like _prune_saved_models
        # (see there) so a pruned run's surviving checkpoints are the ones
        # ranked here
        sorted_idx = np.argsort(val_acc, axis=0, kind="stable").astype(
            np.int32
        )[::-1][:top_n_models]
        self._log(f"top-{top_n_models} val epochs {sorted_idx} acc {val_acc[sorted_idx]}")

        n_batches = int(self.cfg.num_evaluation_tasks / self.cfg.batch_size)
        self._active_pbar = self._pbar(n_batches * len(sorted_idx), "test")
        try:
            per_model_preds, all_targets = self._ensemble_predict(
                sorted_idx, n_batches
            )
        finally:
            self._close_pbar()

        # ensemble: mean softmax over models -> argmax (:282-288)
        per_batch_preds = np.mean(np.array(per_model_preds), axis=0)
        per_batch_max = np.argmax(per_batch_preds, axis=2)
        per_batch_targets = np.array(all_targets).reshape(per_batch_max.shape)
        accuracy = float(np.mean(np.equal(per_batch_targets, per_batch_max)))
        accuracy_std = float(np.std(np.equal(per_batch_targets, per_batch_max)))
        test_losses = {
            "test_accuracy_mean": accuracy,
            "test_accuracy_std": accuracy_std,
        }
        if self.is_primary:
            self._write_stats(
                lambda: save_statistics(
                    self.logs_filepath, list(test_losses.keys()),
                    create=True, filename="test_summary.csv",
                ),
                site="stats_write",
            )
            self._write_stats(
                lambda: save_statistics(
                    self.logs_filepath, list(test_losses.values()),
                    filename="test_summary.csv",
                ),
                site="stats_write",
            )
        self._log(str(test_losses))
        return test_losses

    def _ensemble_predict(self, sorted_idx, n_batches):
        """Collect per-model softmax preds (and, once, the targets) over the
        test stream for each top checkpoint. Loads each checkpoint into
        ``self.model`` (reference experiment_builder.py:262-276). Batches are
        dispatched in ``eval_batches_per_dispatch`` chunks like the
        validation epoch — the per-checkpoint test sweep is the other half
        of the epoch-boundary dispatch tail."""
        chunk_k = max(1, int(self.cfg.eval_batches_per_dispatch))
        per_model_preds: List[List[np.ndarray]] = [[] for _ in sorted_idx]
        all_targets: List[np.ndarray] = []

        def flush(idx, samples):
            self._beat("test_ensemble")
            _, preds = self.model.run_validation_iters(
                list(samples), return_preds=True
            )
            if self._active_pbar is not None:
                self._active_pbar.update(len(samples))
            # preds arrive (k, tasks, targets, classes): per-batch slices
            # keep the sequential path's list-of-task-arrays accumulation
            from ..data.loader import IndexBatch

            for j, sample in enumerate(samples):
                per_model_preds[idx].extend(list(preds[j]))
                if idx == 0:
                    # the test stream is identical per call (fixed seed), so
                    # targets only need gathering once, not once per model
                    if isinstance(sample, IndexBatch):
                        # index-only batches carry no pixel targets; labels
                        # are positional (sample j of class i has label i)
                        t = sample.target_labels(self.cfg.num_target_samples)
                    else:
                        t = np.asarray(sample[3])
                    all_targets.extend(
                        list(
                            self.model.gather_across_hosts(
                                t.reshape(t.shape[0], -1)
                            )
                        )
                    )

        for idx, model_idx in enumerate(sorted_idx):
            # checkpoint of epoch (model_idx + 1) — the reference's off-by-one
            # (experiment_builder.py:265): epoch counter is 1-based at save.
            # Behind the retry seam: a transient restore fault mid-ensemble
            # must not throw away the whole training run's final test.
            epoch_idx = int(model_idx) + 1
            self.state = self.retry.call(
                lambda: self.model.load_model(
                    self.saved_models_filepath, epoch_idx
                ),
                site="ckpt_restore",
            )
            pending: List = []
            for test_sample in self.data.get_test_batches(total_batches=n_batches):
                pending.append(test_sample)
                if len(pending) < chunk_k:
                    continue
                flush(idx, pending)
                pending = []
            if pending:
                flush(idx, pending)
        return per_model_preds, all_targets
