from . import builder, checkpoint, system
from .builder import ExperimentBuilder
from .system import MAMLFewShotClassifier
