"""Checkpoint save/restore: orbax for the device state, JSON for the
experiment state.

Preserves the reference's checkpoint contract (few_shot_learning_system.py:
399-424, experiment_builder.py:190-206):

* each save writes TWO checkpoints — ``train_model_<epoch>`` and
  ``train_model_latest`` — so a killed run restarts from ``latest`` while the
  per-epoch history feeds the top-N test ensemble;
* the checkpoint carries network params (incl. LSLR learning rates and
  per-step BN state — nn.Parameters of the module in the reference), the
  Adam optimizer state, and the experiment-state dict (best_val_acc,
  best_val_iter, current_iter, per_epoch_statistics);
* restore returns the experiment state and replaces the model/optimizer
  state in place.

TPU-native: orbax writes the array pytree (async-capable, multi-host-safe),
replacing ``torch.save`` of a state_dict.
"""

from __future__ import annotations

import json
import os
import shutil
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

from ..core.maml import MetaState

_EXPERIMENT_STATE_FILE = "experiment_state.json"


def _ckpt_dir(model_save_dir: str, model_name: str, model_idx) -> str:
    return os.path.join(model_save_dir, f"{model_name}_{model_idx}")


class _NumpyEncoder(json.JSONEncoder):
    def default(self, obj):
        if isinstance(obj, (np.floating, np.integer)):
            return obj.item()
        if isinstance(obj, np.ndarray):
            return obj.tolist()
        return super().default(obj)


def save_checkpoint(
    model_save_dir: str,
    model_name: str,
    model_idx,
    state: MetaState,
    experiment_state: Dict[str, Any],
) -> str:
    """Write one checkpoint directory (ref: save_model,
    few_shot_learning_system.py:399-408)."""
    path = _ckpt_dir(model_save_dir, model_name, model_idx)
    tmp = path + ".tmp"
    multiprocess = jax.process_count() > 1
    if not multiprocess or jax.process_index() == 0:
        shutil.rmtree(tmp, ignore_errors=True)
    if multiprocess:
        from jax.experimental import multihost_utils

        # a killed run can leave a stale tmp on the shared filesystem; no
        # process may reach orbax's destination-exists check before the
        # primary's cleanup lands
        multihost_utils.sync_global_devices(
            f"ckpt_tmp_clean_{model_name}_{model_idx}"
        )
    ckptr = ocp.StandardCheckpointer()
    # collective in multi-process runs: every process calls save on the SAME
    # path (orbax shards the write and barriers internally)
    ckptr.save(os.path.join(tmp, "state"), state._asdict())
    ckptr.wait_until_finished()
    if not multiprocess or jax.process_index() == 0:
        # host-side files + the atomic-ish swap happen once per (shared)
        # filesystem, not once per process — concurrent rmtree/os.replace of
        # the same path from two processes would race
        with open(os.path.join(tmp, _EXPERIMENT_STATE_FILE), "w") as f:
            json.dump(experiment_state, f, cls=_NumpyEncoder)
        shutil.rmtree(path, ignore_errors=True)
        os.replace(tmp, path)
    if multiprocess:
        from jax.experimental import multihost_utils

        # non-primary processes must not race ahead and load (or re-save)
        # before the primary's swap lands
        multihost_utils.sync_global_devices(
            f"ckpt_swap_{model_name}_{model_idx}"
        )
    return path


def load_checkpoint(
    model_save_dir: str,
    model_name: str,
    model_idx,
    target_state: MetaState,
) -> Tuple[MetaState, Dict[str, Any]]:
    """Restore (ref: load_model, few_shot_learning_system.py:410-424).

    :param target_state: a state of the right structure (e.g. from
        ``maml.init_state``) providing shapes/dtypes for orbax.
    """
    path = _ckpt_dir(model_save_dir, model_name, model_idx)
    abstract = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype)
        if hasattr(x, "shape")
        else x,
        target_state._asdict(),
    )
    ckptr = ocp.StandardCheckpointer()
    restored = ckptr.restore(os.path.join(path, "state"), abstract)
    with open(os.path.join(path, _EXPERIMENT_STATE_FILE)) as f:
        experiment_state = json.load(f)
    return MetaState(**restored), experiment_state


def checkpoint_exists(model_save_dir: str, model_name: str, model_idx) -> bool:
    return os.path.isdir(_ckpt_dir(model_save_dir, model_name, model_idx))


def remove_checkpoint(model_save_dir: str, model_name: str, model_idx) -> None:
    """Delete one checkpoint directory; missing is fine.

    Multi-host: only the primary touches the shared filesystem (no barrier
    needed — pruning is best-effort hygiene, never load-bearing).
    """
    if jax.process_count() > 1 and jax.process_index() != 0:
        return
    shutil.rmtree(
        _ckpt_dir(model_save_dir, model_name, model_idx), ignore_errors=True
    )
