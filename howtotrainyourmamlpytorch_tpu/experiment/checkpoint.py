"""Checkpoint save/restore: orbax for the device state, JSON for the
experiment state.

Preserves the reference's checkpoint contract (few_shot_learning_system.py:
399-424, experiment_builder.py:190-206):

* each save writes TWO checkpoints — ``train_model_<epoch>`` and
  ``train_model_latest`` — so a killed run restarts from ``latest`` while the
  per-epoch history feeds the top-N test ensemble;
* the checkpoint carries network params (incl. LSLR learning rates and
  per-step BN state — nn.Parameters of the module in the reference), the
  Adam optimizer state, and the experiment-state dict (best_val_acc,
  best_val_iter, current_iter, per_epoch_statistics);
* restore returns the experiment state and replaces the model/optimizer
  state in place.

TPU-native: orbax writes the array pytree (async-capable, multi-host-safe),
replacing ``torch.save`` of a state_dict.

Single-host saves are ASYNC and DEDUPLICATED (``save_checkpoint_async``):
``ocp.AsyncCheckpointer`` copies the pytree device->host synchronously (so
the caller may immediately donate the state to the next train dispatch) and
writes to ``<ckpt>.tmp`` in the background; a finalizer thread then swaps the
tmp into place and, when requested, clones ``train_model_latest`` from the
finished epoch directory HOST-side — one device->host serialization per
epoch where the reference (and our previous sync path) paid two.  Crash
safety: ``latest`` is only ever replaced from a fully-written epoch
directory, so a kill anywhere between save-start and the barrier leaves the
previous ``latest`` loadable.  ``wait_for_pending`` is the correctness
barrier — called before every subsequent save/load/exists, before pruning
the in-flight path, and at interpreter exit.

Multi-process runs keep the synchronous collective path (``save_checkpoint``)
with its cross-host barriers: the per-dispatch overhead the async path
amortizes is a single-host tunnel artifact, and the primary-only swap logic
would otherwise need a third barrier.
"""

from __future__ import annotations

import atexit
import json
import os
import shutil
import threading
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

from ..core.maml import MetaState
from ..resilience import faults

_EXPERIMENT_STATE_FILE = "experiment_state.json"


class CheckpointCorruptError(RuntimeError):
    """A checkpoint directory exists but cannot be restored (partial write
    survived a crash, bit rot, a foreign directory under ``saved_models/``).

    Replaces the opaque orbax traceback with the path that failed, the
    underlying error, and the *surviving* sibling checkpoints the operator
    can fall back to (``latest``, ``emergency``, the kept best-val epochs)
    — the triage decision is in the exception, not in a shell session.
    """

    def __init__(self, path: str, cause: BaseException,
                 fallbacks: List[str]):
        self.path = path
        self.fallbacks = list(fallbacks)
        hint = (
            "surviving checkpoints in the same directory: "
            + ", ".join(self.fallbacks)
            if self.fallbacks
            else "no other checkpoints survive in that directory"
        )
        super().__init__(
            f"checkpoint at {path} is corrupt or partially written "
            f"({cause!r}); {hint}. Resume with continue_from_epoch="
            "'latest' (or a surviving epoch index), or delete the corrupt "
            "directory and restart from_scratch."
        )


def list_checkpoints(model_save_dir: str, model_name: str) -> List[str]:
    """Finalized ``<model_name>_*`` checkpoint directories (suffixes only,
    e.g. ``['3', '5', 'emergency', 'latest']``) — in-flight ``.tmp`` and
    crash-leftover ``.old`` siblings excluded."""
    try:
        names = os.listdir(model_save_dir)
    except OSError:
        return []
    prefix = model_name + "_"
    return sorted(
        name[len(prefix):]
        for name in names
        if name.startswith(prefix)
        and not name.endswith((".tmp", ".old"))
        and os.path.isdir(os.path.join(model_save_dir, name))
    )

# one in-flight async save at a time: (finalizer thread, paths it will
# create/replace, error holder). Module-level because checkpoints are a
# process-wide filesystem resource, not per-system-object.
_pending_save: Optional[Tuple[threading.Thread, Tuple[str, ...], List]] = None
_async_checkpointer: Optional[ocp.AsyncCheckpointer] = None


def _get_async_checkpointer() -> ocp.AsyncCheckpointer:
    global _async_checkpointer
    if _async_checkpointer is None:
        _async_checkpointer = ocp.AsyncCheckpointer(
            ocp.StandardCheckpointHandler()
        )
    return _async_checkpointer


def wait_for_pending(touching: Optional[str] = None) -> None:
    """Barrier for the in-flight async save.

    ``touching=None`` always waits; ``touching=<path>`` waits only when the
    pending finalize will create or replace that path — pruning an unrelated
    epoch directory can proceed concurrently with the background write.
    Re-raises any exception the finalizer hit (a failed checkpoint write
    must fail the run, not vanish into a daemon thread).
    """
    global _pending_save
    if _pending_save is None:
        return
    thread, paths, errors = _pending_save
    if touching is not None and touching not in paths:
        return
    thread.join()
    _pending_save = None
    if errors:
        raise errors[0]


atexit.register(wait_for_pending)


def _ckpt_dir(model_save_dir: str, model_name: str, model_idx) -> str:
    return os.path.join(model_save_dir, f"{model_name}_{model_idx}")


class _NumpyEncoder(json.JSONEncoder):
    def default(self, obj):
        if isinstance(obj, (np.floating, np.integer)):
            return obj.item()
        if isinstance(obj, np.ndarray):
            return obj.tolist()
        return super().default(obj)


def save_checkpoint(
    model_save_dir: str,
    model_name: str,
    model_idx,
    state: MetaState,
    experiment_state: Dict[str, Any],
) -> str:
    """Write one checkpoint directory (ref: save_model,
    few_shot_learning_system.py:399-408)."""
    wait_for_pending()  # serialize with any in-flight async save
    faults.fire("ckpt_save")  # injectable seam (resilience/faults.py)
    path = _ckpt_dir(model_save_dir, model_name, model_idx)
    tmp = path + ".tmp"
    multiprocess = jax.process_count() > 1
    if not multiprocess or jax.process_index() == 0:
        shutil.rmtree(tmp, ignore_errors=True)
    if multiprocess:
        from jax.experimental import multihost_utils

        # a killed run can leave a stale tmp on the shared filesystem; no
        # process may reach orbax's destination-exists check before the
        # primary's cleanup lands
        multihost_utils.sync_global_devices(
            f"ckpt_tmp_clean_{model_name}_{model_idx}"
        )
    ckptr = ocp.StandardCheckpointer()
    # collective in multi-process runs: every process calls save on the SAME
    # path (orbax shards the write and barriers internally)
    ckptr.save(os.path.join(tmp, "state"), state._asdict())
    ckptr.wait_until_finished()
    if not multiprocess or jax.process_index() == 0:
        # host-side files + the atomic-ish swap happen once per (shared)
        # filesystem, not once per process — concurrent rmtree/os.replace of
        # the same path from two processes would race
        with open(os.path.join(tmp, _EXPERIMENT_STATE_FILE), "w") as f:
            json.dump(experiment_state, f, cls=_NumpyEncoder)
        _swap_into_place(tmp, path)
    if multiprocess:
        from jax.experimental import multihost_utils

        # non-primary processes must not race ahead and load (or re-save)
        # before the primary's swap lands
        multihost_utils.sync_global_devices(
            f"ckpt_swap_{model_name}_{model_idx}"
        )
    return path


def _swap_into_place(tmp: str, path: str) -> None:
    """Crash-safe tmp -> final swap shared by the sync and async paths.

    The previous directory is renamed aside (atomic) before the new one is
    renamed in (atomic), then deleted — never rmtree'd while it is the only
    copy. A kill between the two renames leaves ``<path>.old``, which
    ``_recover_interrupted_swap`` restores on the next exists/load; so a
    complete checkpoint is recoverable at every instant, closing the
    rmtree-length window the old rmtree+replace sequence had.
    """
    old = path + ".old"
    shutil.rmtree(old, ignore_errors=True)
    if os.path.isdir(path):
        os.replace(path, old)
    os.replace(tmp, path)
    shutil.rmtree(old, ignore_errors=True)


def _recover_interrupted_swap(path: str) -> None:
    """Finish a swap that was killed between its two renames: if ``path`` is
    gone but ``<path>.old`` survives, the old checkpoint is still complete —
    move it back."""
    old = path + ".old"
    if not os.path.isdir(path) and os.path.isdir(old):
        try:
            os.replace(old, path)
        except OSError:
            # lost the recovery race to another process on the shared
            # filesystem — whoever won produced the same result
            pass


def save_checkpoint_async(
    model_save_dir: str,
    model_name: str,
    model_idx,
    state: MetaState,
    experiment_state: Dict[str, Any],
    clone_to=None,
) -> str:
    """Start an async checkpoint write; returns once the pytree is copied
    device->host (safe to donate/mutate ``state`` afterwards).

    The background finalizer waits for orbax's write, swaps ``.tmp`` into
    ``<model_name>_<model_idx>``, then — when ``clone_to`` is given (the
    builder passes ``"latest"``) — clones that finished directory host-side
    into ``<model_name>_<clone_to>`` via its own tmp+swap.  The epoch-N
    write and ``latest`` therefore share ONE device->host serialization, and
    ``latest`` is only ever replaced from a complete on-disk checkpoint.

    Single-host only: multi-process callers use the collective
    ``save_checkpoint``.
    """
    global _pending_save
    if jax.process_count() > 1:
        raise RuntimeError(
            "save_checkpoint_async is single-host only; multi-process runs "
            "use the collective save_checkpoint"
        )
    wait_for_pending()  # one in-flight save: serialize with the previous one
    faults.fire("ckpt_save")  # injectable seam (resilience/faults.py);
    # fired HERE, in the caller's thread before any state is handed to
    # orbax, so a retry wrapper can simply re-call this function
    path = _ckpt_dir(model_save_dir, model_name, model_idx)
    tmp = path + ".tmp"
    shutil.rmtree(tmp, ignore_errors=True)
    os.makedirs(tmp)
    # The host copy must be REAL before this function returns: on the CPU
    # backend a jax.Array is a zero-copy view of the device buffer, so
    # handing the raw pytree to the background writer and then letting the
    # next train step DONATE those buffers lets XLA reuse the very memory
    # the write is still reading — silently corrupt early-epoch
    # checkpoints (the first save, paying orbax's one-time setup, reliably
    # lost that race) or a use-after-free segfault. On accelerators
    # np.array() IS the device->host serialization the contract promises;
    # either way it stays exactly one copy per epoch.
    host_state = jax.tree_util.tree_map(
        lambda x: np.array(x) if isinstance(x, jax.Array) else x,
        state._asdict(),
    )
    ckptr = _get_async_checkpointer()
    # blocks only for the device->host copy; the disk write is backgrounded
    ckptr.save(
        os.path.join(tmp, "state"),
        args=ocp.args.StandardSave(host_state),
    )
    with open(os.path.join(tmp, _EXPERIMENT_STATE_FILE), "w") as f:
        json.dump(experiment_state, f, cls=_NumpyEncoder)
    clone_path = (
        _ckpt_dir(model_save_dir, model_name, clone_to)
        if clone_to is not None
        else None
    )
    errors: List = []

    def _finalize():
        try:
            ckptr.wait_until_finished()
            # injectable seam: a sigkill fault here dies mid-finalize with
            # the write complete but the swap not yet done — the window the
            # crash-safe tmp/.old rename dance exists for
            faults.fire("ckpt_finalize")
            _swap_into_place(tmp, path)
            if clone_path is not None:
                clone_tmp = clone_path + ".tmp"
                shutil.rmtree(clone_tmp, ignore_errors=True)
                shutil.copytree(path, clone_tmp)
                _swap_into_place(clone_tmp, clone_path)
        except BaseException as e:  # noqa: BLE001 - re-raised at the barrier
            errors.append(e)

    thread = threading.Thread(
        target=_finalize, name="ckpt-finalize", daemon=True
    )
    thread.start()
    touched = (path,) if clone_path is None else (path, clone_path)
    _pending_save = (thread, touched, errors)
    return path


def load_checkpoint(
    model_save_dir: str,
    model_name: str,
    model_idx,
    target_state: MetaState,
) -> Tuple[MetaState, Dict[str, Any]]:
    """Restore (ref: load_model, few_shot_learning_system.py:410-424).

    :param target_state: a state of the right structure (e.g. from
        ``maml.init_state``) providing shapes/dtypes for orbax.
    """
    wait_for_pending()  # never read past an in-flight async save
    faults.fire("ckpt_restore")  # injectable seam (resilience/faults.py)
    path = _ckpt_dir(model_save_dir, model_name, model_idx)
    _recover_interrupted_swap(path)
    abstract = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype)
        if hasattr(x, "shape")
        else x,
        target_state._asdict(),
    )
    if not os.path.isdir(path):
        # genuinely absent (callers normally gate on checkpoint_exists):
        # stays a FileNotFoundError, not a corruption report
        raise FileNotFoundError(
            f"checkpoint directory {path} does not exist"
        )
    try:
        ckptr = ocp.StandardCheckpointer()
        restored = ckptr.restore(os.path.join(path, "state"), abstract)
        # orbax hands back numpy VIEWS over tensorstore-owned buffers
        # (owndata=False, base=PyCapsule). Training then feeds them to the
        # donating train step; tying XLA buffer lifetime to a foreign
        # allocator's capsule is how resumed runs died with heap-corruption
        # segfaults at random later points. Copy ONCE into numpy-owned
        # memory here, while the restore context is alive.
        restored = jax.tree_util.tree_map(
            lambda x: np.array(x) if isinstance(x, np.ndarray) else x,
            restored,
        )
        with open(os.path.join(path, _EXPERIMENT_STATE_FILE)) as f:
            experiment_state = json.load(f)
    except Exception as e:  # noqa: BLE001 - orbax surfaces partial writes
        # as a zoo of ValueError/KeyError/FileNotFoundError/XlaRuntimeError;
        # all of them mean the same operational thing here
        fallbacks = [
            s for s in list_checkpoints(model_save_dir, model_name)
            if s != str(model_idx)
        ]
        raise CheckpointCorruptError(path, e, fallbacks) from e
    return MetaState(**restored), experiment_state


def peek_experiment_state(
    model_save_dir: str, model_name: str, model_idx
) -> Optional[Dict[str, Any]]:
    """The experiment-state dict of a checkpoint WITHOUT restoring the
    array pytree (None when the checkpoint or its JSON is absent/corrupt).
    The resume logic uses this to compare ``current_iter`` across the
    ``latest`` and ``emergency`` candidates before paying a restore."""
    path = _ckpt_dir(model_save_dir, model_name, model_idx)
    wait_for_pending(touching=path)
    _recover_interrupted_swap(path)
    try:
        with open(os.path.join(path, _EXPERIMENT_STATE_FILE)) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def checkpoint_exists(model_save_dir: str, model_name: str, model_idx) -> bool:
    path = _ckpt_dir(model_save_dir, model_name, model_idx)
    wait_for_pending(touching=path)
    _recover_interrupted_swap(path)
    return os.path.isdir(path)


def remove_checkpoint(model_save_dir: str, model_name: str, model_idx) -> None:
    """Delete one checkpoint directory; missing is fine.

    Waits for the in-flight async save only when IT targets this path —
    otherwise a prune of the just-written epoch would race the background
    finalize (rmtree of a not-yet-materialized dir, then the finalize
    resurrecting it). Pruning unrelated epochs overlaps the write freely.

    Multi-host: only the primary touches the shared filesystem (no barrier
    needed — pruning is best-effort hygiene, never load-bearing).
    """
    if jax.process_count() > 1 and jax.process_index() != 0:
        return
    path = _ckpt_dir(model_save_dir, model_name, model_idx)
    wait_for_pending(touching=path)
    shutil.rmtree(path, ignore_errors=True)
    # also drop a crash-leftover swap sibling: were it to linger,
    # _recover_interrupted_swap would resurrect the pruned checkpoint with
    # pre-prune contents on the next exists/load probe
    shutil.rmtree(path + ".old", ignore_errors=True)
