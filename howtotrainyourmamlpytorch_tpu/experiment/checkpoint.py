"""Checkpoint save/restore: orbax for the device state, JSON for the
experiment state.

Preserves the reference's checkpoint contract (few_shot_learning_system.py:
399-424, experiment_builder.py:190-206):

* each save writes TWO checkpoints — ``train_model_<epoch>`` and
  ``train_model_latest`` — so a killed run restarts from ``latest`` while the
  per-epoch history feeds the top-N test ensemble;
* the checkpoint carries network params (incl. LSLR learning rates and
  per-step BN state — nn.Parameters of the module in the reference), the
  Adam optimizer state, and the experiment-state dict (best_val_acc,
  best_val_iter, current_iter, per_epoch_statistics);
* restore returns the experiment state and replaces the model/optimizer
  state in place.

TPU-native: orbax writes the array pytree (async-capable, multi-host-safe),
replacing ``torch.save`` of a state_dict.

Single-host saves are ASYNC and DEDUPLICATED (``save_checkpoint_async``):
``ocp.AsyncCheckpointer`` copies the pytree device->host synchronously (so
the caller may immediately donate the state to the next train dispatch) and
writes to ``<ckpt>.tmp`` in the background; a finalizer thread then swaps the
tmp into place and, when requested, clones ``train_model_latest`` from the
finished epoch directory HOST-side — one device->host serialization per
epoch where the reference (and our previous sync path) paid two.  Crash
safety: ``latest`` is only ever replaced from a fully-written epoch
directory, so a kill anywhere between save-start and the barrier leaves the
previous ``latest`` loadable.  ``wait_for_pending`` is the correctness
barrier — called before every subsequent save/load/exists, before pruning
the in-flight path, and at interpreter exit.

Multi-process runs keep the synchronous collective path (``save_checkpoint``)
with its cross-host barriers: the per-dispatch overhead the async path
amortizes is a single-host tunnel artifact, and the primary-only swap logic
would otherwise need a third barrier.  Those barriers are BOUNDED
(``_process_barrier``, ``ckpt_follower_timeout_s``): a gang member that
dies mid-save turns into ``CheckpointBarrierTimeoutError`` on the
survivors — naming the phase and the primary's expected swap path —
instead of an unbounded spin-wait.
"""

from __future__ import annotations

import atexit
import json
import os
import shutil
import threading
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

from ..core.maml import MetaState
from ..resilience import faults

_EXPERIMENT_STATE_FILE = "experiment_state.json"


class CheckpointBarrierTimeoutError(RuntimeError):
    """A cross-process checkpoint barrier expired: some process never
    arrived (killed mid-save, wedged collective, dead shared filesystem).

    Replaces the former unbounded wait — a non-primary process used to
    spin at the post-swap synchronization forever if the primary died
    between orbax's write and the tmp -> final swap. The message names the
    phase, the primary's expected swap path and the crash-forensics
    siblings (``<path>.tmp`` = swap never started, ``<path>.old`` = killed
    between the two renames; ``_recover_interrupted_swap`` restores the
    latter on the next load), so the triage decision ships in the
    exception. Tune the bound with ``ckpt_follower_timeout_s``.
    """

    def __init__(self, phase: str, path: str, timeout_s: float,
                 cause: Optional[BaseException] = None):
        self.phase = phase
        self.path = path
        self.timeout_s = float(timeout_s)
        super().__init__(
            f"checkpoint barrier {phase!r} for {path} expired after "
            f"{timeout_s:.0f}s: not every process arrived"
            + (f" ({cause!r})" if cause is not None else "")
            + f". The primary's swap should have produced {path} (look for "
            f"{path}.tmp — swap never started — or {path}.old — killed "
            "between renames; the next load recovers it). Likely causes: a "
            "process died mid-save, or the shared filesystem stalled; "
            "restart the gang with continue_from_epoch='latest' (raise "
            "ckpt_follower_timeout_s if the filesystem is just slow)."
        )


class CheckpointCorruptError(RuntimeError):
    """A checkpoint directory exists but cannot be restored (partial write
    survived a crash, bit rot, a foreign directory under ``saved_models/``).

    Replaces the opaque orbax traceback with the path that failed, the
    underlying error, and the *surviving* sibling checkpoints the operator
    can fall back to (``latest``, ``emergency``, the kept best-val epochs)
    — the triage decision is in the exception, not in a shell session.
    """

    def __init__(self, path: str, cause: BaseException,
                 fallbacks: List[str]):
        self.path = path
        self.fallbacks = list(fallbacks)
        hint = (
            "surviving checkpoints in the same directory: "
            + ", ".join(self.fallbacks)
            if self.fallbacks
            else "no other checkpoints survive in that directory"
        )
        super().__init__(
            f"checkpoint at {path} is corrupt or partially written "
            f"({cause!r}); {hint}. Resume with continue_from_epoch="
            "'latest' (or a surviving epoch index), or delete the corrupt "
            "directory and restart from_scratch."
        )


def list_checkpoints(model_save_dir: str, model_name: str) -> List[str]:
    """Finalized ``<model_name>_*`` checkpoint directories (suffixes only,
    e.g. ``['3', '5', 'emergency', 'latest']``) — in-flight ``.tmp`` and
    crash-leftover ``.old`` siblings excluded."""
    try:
        names = os.listdir(model_save_dir)
    except OSError:
        return []
    prefix = model_name + "_"
    return sorted(
        name[len(prefix):]
        for name in names
        if name.startswith(prefix)
        and not name.endswith((".tmp", ".old"))
        and os.path.isdir(os.path.join(model_save_dir, name))
    )

# one in-flight async save at a time: (finalizer thread, paths it will
# create/replace, error holder). Module-level because checkpoints are a
# process-wide filesystem resource, not per-system-object.
_pending_save: Optional[Tuple[threading.Thread, Tuple[str, ...], List]] = None
_async_checkpointer: Optional[ocp.AsyncCheckpointer] = None


def _get_async_checkpointer() -> ocp.AsyncCheckpointer:
    global _async_checkpointer
    if _async_checkpointer is None:
        _async_checkpointer = ocp.AsyncCheckpointer(
            ocp.StandardCheckpointHandler()
        )
    return _async_checkpointer


def wait_for_pending(touching: Optional[str] = None) -> None:
    """Barrier for the in-flight async save.

    ``touching=None`` always waits; ``touching=<path>`` waits only when the
    pending finalize will create or replace that path — pruning an unrelated
    epoch directory can proceed concurrently with the background write.
    Re-raises any exception the finalizer hit (a failed checkpoint write
    must fail the run, not vanish into a daemon thread).
    """
    global _pending_save
    if _pending_save is None:
        return
    thread, paths, errors = _pending_save
    if touching is not None and touching not in paths:
        return
    thread.join()
    _pending_save = None
    if errors:
        raise errors[0]


atexit.register(wait_for_pending)


def _ckpt_dir(model_save_dir: str, model_name: str, model_idx) -> str:
    return os.path.join(model_save_dir, f"{model_name}_{model_idx}")


#: default bound on the collective save's cross-process barriers; the
#: builder passes cfg.ckpt_follower_timeout_s instead
DEFAULT_BARRIER_TIMEOUT_S = 600.0

# per-(name, idx) barrier sequence numbers: barrier ids must be unique per
# crossing, and every process calls save_checkpoint in the same
# deterministic order, so a module-level counter agrees across the gang
# (the coordination service restarts with the gang, so resumes agree too).
# Known limit: a PER-PROCESS retry of the collective save (one worker's
# transient OSError re-entering save_checkpoint alone) desynchronizes the
# sequence — the gang then fails BOUNDED and diagnosable via
# CheckpointBarrierTimeoutError on every process (the pre-elastic
# sync_global_devices path wedged forever in the same scenario); a
# gang-coordinated retry would need a cross-process agreement of its own.
_barrier_seq: Dict[str, int] = {}


_orbax_sync_rerouted = False
# bound for the rerouted orbax barriers when orbax itself passes no
# timeout: kept in lockstep with the configured ckpt_follower_timeout_s by
# the save/load entry points (a mutable cell the closure reads, so raising
# the config knob also raises orbax's internal sync bound)
_orbax_barrier_timeout_s = [DEFAULT_BARRIER_TIMEOUT_S]


def _reroute_orbax_sync_through_coordination_service() -> None:
    """Replace orbax's cross-process sync (a jitted 4-byte device psum via
    ``multihost_utils.sync_global_devices``) with the coordination-service
    barrier, once per process, in multi-process runs.

    The device-psum barrier is a COLLECTIVE PROGRAM: on backends whose
    cross-process collectives share one tag space per process pair
    (XLA:CPU gloo), a barrier psum from one process can interleave against
    a different in-flight collective on a peer and corrupt the transport
    ("op.preamble.length <= op.nbytes" aborts — observed reliably in the
    multi-process test-ensemble phase, where checkpoint restores alternate
    with eval dispatches). The coordination service is the same mechanism
    orbax's async path and our ``_process_barrier`` already use, provides
    identical happens-before guarantees, and keeps checkpoint
    synchronization off the device interconnect entirely — also one less
    compiled program per barrier on real pods.
    """
    global _orbax_sync_rerouted
    if _orbax_sync_rerouted or jax.process_count() <= 1:
        return
    from jax._src import distributed as jax_distributed

    client = jax_distributed.global_state.client
    if client is None:
        return  # no coordination service: leave orbax's default in place
    try:
        from orbax.checkpoint import multihost as ocp_multihost
        from orbax.checkpoint.multihost import utils as ocp_mh_utils
    except ImportError:
        return

    def _sync(name: str, *, timeout=None, processes=None,
              barrier_sync_fn=None, **_kwargs) -> None:
        if processes is not None and len(processes) <= 1:
            return
        bound = timeout or _orbax_barrier_timeout_s[0]
        try:
            # orbax barrier names are unique per use (its contract), so
            # they map 1:1 onto coordination-service barrier ids
            client.wait_at_barrier(
                f"orbax_{name}", timeout_in_ms=int(bound * 1000)
            )
        except Exception as e:  # noqa: BLE001 - expiry surfaces as a raw
            # backend JaxRuntimeError; give it the same operator guidance
            # as the repo's own checkpoint barriers
            raise RuntimeError(
                f"orbax checkpoint sync barrier {name!r} expired after "
                f"{bound:.0f}s: not every process arrived (a gang member "
                "died mid-save/restore, or the shared filesystem stalled "
                "— raise ckpt_follower_timeout_s if it is just slow)"
            ) from e

    for mod in (ocp_mh_utils, ocp_multihost):
        mod.sync_global_processes = _sync
    try:  # legacy aliases some orbax call sites import
        from orbax.checkpoint import utils as ocp_utils

        ocp_utils.sync_global_processes = _sync
        ocp_utils.sync_global_devices = _sync
    except (ImportError, AttributeError):
        pass
    _orbax_sync_rerouted = True


def _process_barrier(name: str, swap_path: str, timeout_s: float,
                     phase: str) -> None:
    """A BOUNDED cross-process barrier for the collective checkpoint path,
    via the jax coordination-service client (the same service the
    collectives and orbax already depend on). Replaces the former
    unbounded ``sync_global_devices`` spin: expiry raises
    ``CheckpointBarrierTimeoutError`` naming the phase and the primary's
    expected swap path instead of wedging every surviving process forever.
    Also a chaos-injectable seam (site ``barrier``)."""
    faults.fire("barrier")  # injectable seam (resilience/faults.py)
    seq = _barrier_seq.get(name, 0) + 1
    _barrier_seq[name] = seq
    from jax._src import distributed as jax_distributed

    client = jax_distributed.global_state.client
    if client is None:
        # multi-process jax without an initialized coordination service
        # cannot happen through initialize_distributed; degrade to the
        # legacy unbounded barrier rather than skipping synchronization
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"{name}_{seq}")
        return
    try:
        client.wait_at_barrier(
            f"ckpt_{name}_{seq}", timeout_in_ms=max(1, int(timeout_s * 1000))
        )
    except Exception as e:  # noqa: BLE001 - the runtime surfaces expiry as
        # a backend-specific JaxRuntimeError (DEADLINE_EXCEEDED)
        raise CheckpointBarrierTimeoutError(
            phase, swap_path, timeout_s, cause=e
        ) from e


class _NumpyEncoder(json.JSONEncoder):
    def default(self, obj):
        if isinstance(obj, (np.floating, np.integer)):
            return obj.item()
        if isinstance(obj, np.ndarray):
            return obj.tolist()
        return super().default(obj)


def save_checkpoint(
    model_save_dir: str,
    model_name: str,
    model_idx,
    state: MetaState,
    experiment_state: Dict[str, Any],
    barrier_timeout_s: float = DEFAULT_BARRIER_TIMEOUT_S,
) -> str:
    """Write one checkpoint directory (ref: save_model,
    few_shot_learning_system.py:399-408).

    Multi-process runs synchronize through BOUNDED barriers
    (``_process_barrier``): a gang member that dies mid-save surfaces as a
    ``CheckpointBarrierTimeoutError`` on the survivors after
    ``barrier_timeout_s`` instead of an unbounded spin-wait on the
    primary's swap."""
    wait_for_pending()  # serialize with any in-flight async save
    faults.fire("ckpt_save")  # injectable seam (resilience/faults.py)
    path = _ckpt_dir(model_save_dir, model_name, model_idx)
    tmp = path + ".tmp"
    multiprocess = jax.process_count() > 1
    if multiprocess:
        _orbax_barrier_timeout_s[0] = float(barrier_timeout_s)
        _reroute_orbax_sync_through_coordination_service()
    if not multiprocess or jax.process_index() == 0:
        shutil.rmtree(tmp, ignore_errors=True)
    if multiprocess:
        # a killed run can leave a stale tmp on the shared filesystem; no
        # process may reach orbax's destination-exists check before the
        # primary's cleanup lands
        _process_barrier(
            f"tmp_clean_{model_name}_{model_idx}", path, barrier_timeout_s,
            phase="tmp_clean",
        )
    ckptr = ocp.StandardCheckpointer()
    # collective in multi-process runs: every process calls save on the SAME
    # path (orbax shards the write and barriers internally)
    ckptr.save(os.path.join(tmp, "state"), state._asdict())
    ckptr.wait_until_finished()
    if not multiprocess or jax.process_index() == 0:
        # host-side files + the atomic-ish swap happen once per (shared)
        # filesystem, not once per process — concurrent rmtree/os.replace of
        # the same path from two processes would race
        with open(os.path.join(tmp, _EXPERIMENT_STATE_FILE), "w") as f:
            json.dump(experiment_state, f, cls=_NumpyEncoder)
        _swap_into_place(tmp, path)
    if multiprocess:
        # non-primary processes must not race ahead and load (or re-save)
        # before the primary's swap lands — the follower path: bounded, and
        # the expiry diagnosis names the expected swap path
        _process_barrier(
            f"swap_{model_name}_{model_idx}", path, barrier_timeout_s,
            phase="swap",
        )
    return path


def _swap_into_place(tmp: str, path: str) -> None:
    """Crash-safe tmp -> final swap shared by the sync and async paths.

    The previous directory is renamed aside (atomic) before the new one is
    renamed in (atomic), then deleted — never rmtree'd while it is the only
    copy. A kill between the two renames leaves ``<path>.old``, which
    ``_recover_interrupted_swap`` restores on the next exists/load; so a
    complete checkpoint is recoverable at every instant, closing the
    rmtree-length window the old rmtree+replace sequence had.
    """
    old = path + ".old"
    shutil.rmtree(old, ignore_errors=True)
    if os.path.isdir(path):
        os.replace(path, old)
    os.replace(tmp, path)
    shutil.rmtree(old, ignore_errors=True)


def _recover_interrupted_swap(path: str) -> None:
    """Finish a swap that was killed between its two renames: if ``path`` is
    gone but ``<path>.old`` survives, the old checkpoint is still complete —
    move it back."""
    old = path + ".old"
    if not os.path.isdir(path) and os.path.isdir(old):
        try:
            os.replace(old, path)
        except OSError:
            # lost the recovery race to another process on the shared
            # filesystem — whoever won produced the same result
            pass


def save_checkpoint_async(
    model_save_dir: str,
    model_name: str,
    model_idx,
    state: MetaState,
    experiment_state: Dict[str, Any],
    clone_to=None,
) -> str:
    """Start an async checkpoint write; returns once the pytree is copied
    device->host (safe to donate/mutate ``state`` afterwards).

    The background finalizer waits for orbax's write, swaps ``.tmp`` into
    ``<model_name>_<model_idx>``, then — when ``clone_to`` is given (the
    builder passes ``"latest"``) — clones that finished directory host-side
    into ``<model_name>_<clone_to>`` via its own tmp+swap.  The epoch-N
    write and ``latest`` therefore share ONE device->host serialization, and
    ``latest`` is only ever replaced from a complete on-disk checkpoint.

    Single-host only: multi-process callers use the collective
    ``save_checkpoint``.
    """
    global _pending_save
    if jax.process_count() > 1:
        raise RuntimeError(
            "save_checkpoint_async is single-host only; multi-process runs "
            "use the collective save_checkpoint"
        )
    wait_for_pending()  # one in-flight save: serialize with the previous one
    faults.fire("ckpt_save")  # injectable seam (resilience/faults.py);
    # fired HERE, in the caller's thread before any state is handed to
    # orbax, so a retry wrapper can simply re-call this function
    path = _ckpt_dir(model_save_dir, model_name, model_idx)
    tmp = path + ".tmp"
    shutil.rmtree(tmp, ignore_errors=True)
    os.makedirs(tmp)
    # The host copy must be REAL before this function returns: on the CPU
    # backend a jax.Array is a zero-copy view of the device buffer, so
    # handing the raw pytree to the background writer and then letting the
    # next train step DONATE those buffers lets XLA reuse the very memory
    # the write is still reading — silently corrupt early-epoch
    # checkpoints (the first save, paying orbax's one-time setup, reliably
    # lost that race) or a use-after-free segfault. On accelerators
    # np.array() IS the device->host serialization the contract promises;
    # either way it stays exactly one copy per epoch.
    host_state = jax.tree_util.tree_map(
        lambda x: np.array(x) if isinstance(x, jax.Array) else x,
        state._asdict(),
    )
    ckptr = _get_async_checkpointer()
    # blocks only for the device->host copy; the disk write is backgrounded
    ckptr.save(
        os.path.join(tmp, "state"),
        args=ocp.args.StandardSave(host_state),
    )
    with open(os.path.join(tmp, _EXPERIMENT_STATE_FILE), "w") as f:
        json.dump(experiment_state, f, cls=_NumpyEncoder)
    clone_path = (
        _ckpt_dir(model_save_dir, model_name, clone_to)
        if clone_to is not None
        else None
    )
    errors: List = []

    def _finalize():
        try:
            ckptr.wait_until_finished()
            # injectable seam: a sigkill fault here dies mid-finalize with
            # the write complete but the swap not yet done — the window the
            # crash-safe tmp/.old rename dance exists for
            faults.fire("ckpt_finalize")
            _swap_into_place(tmp, path)
            if clone_path is not None:
                clone_tmp = clone_path + ".tmp"
                shutil.rmtree(clone_tmp, ignore_errors=True)
                shutil.copytree(path, clone_tmp)
                _swap_into_place(clone_tmp, clone_path)
        except BaseException as e:  # noqa: BLE001 - re-raised at the barrier
            errors.append(e)

    thread = threading.Thread(
        target=_finalize, name="ckpt-finalize", daemon=True
    )
    thread.start()
    touched = (path,) if clone_path is None else (path, clone_path)
    _pending_save = (thread, touched, errors)
    return path


def _resolve_readonly_path(path: str) -> str:
    """The directory a READ-ONLY load should restore from, with no
    filesystem mutation: ``path`` itself when it exists, else the
    complete ``<path>.old`` a swap killed between its two renames left
    behind (see ``_swap_into_place``). The training-owned load path
    instead *renames* the ``.old`` back into place
    (``_recover_interrupted_swap``) — a mutation a serving reader of a
    live training run's directory must never perform: the training
    process owns that recovery, and racing it from a second process
    turns a crash-forensics rename into a cross-process rename race."""
    old = path + ".old"
    if not os.path.isdir(path) and os.path.isdir(old):
        return old
    return path


def load_checkpoint(
    model_save_dir: str,
    model_name: str,
    model_idx,
    target_state: MetaState,
    readonly: bool = False,
) -> Tuple[MetaState, Dict[str, Any]]:
    """Restore (ref: load_model, few_shot_learning_system.py:410-424).

    :param target_state: a state of the right structure (e.g. from
        ``maml.init_state`` or ``jax.eval_shape`` of it) providing
        shapes/dtypes for orbax.
    :param readonly: never mutate the checkpoint directory — the serving
        path's contract (serving/engine.py): a crash-leftover ``.old``
        sibling is *read from* instead of renamed back into place, and
        the load performs no write of any kind in ``model_save_dir``.
        The default (training-owned) path keeps the recovery rename.
    """
    wait_for_pending()  # never read past an in-flight async save
    faults.fire("ckpt_restore")  # injectable seam (resilience/faults.py)
    if jax.process_count() > 1:
        _reroute_orbax_sync_through_coordination_service()
    path = _ckpt_dir(model_save_dir, model_name, model_idx)
    if readonly:
        path = _resolve_readonly_path(path)
    else:
        _recover_interrupted_swap(path)
    # restore template: HOST numpy arrays, not ShapeDtypeStructs. A
    # ShapeDtypeStruct template makes orbax rebuild each leaf's recorded
    # jax sharding — which names the devices of the gang that WROTE the
    # checkpoint and fails to deserialize on any other topology (elastic
    # resume on N±1 hosts would die right here). A numpy template restores
    # plain host arrays with no device opinion at all; the caller
    # (system.load_model) re-replicates over whatever mesh exists NOW.
    abstract = jax.tree_util.tree_map(
        lambda x: np.zeros(x.shape, x.dtype)
        if hasattr(x, "shape")
        else x,
        target_state._asdict(),
    )
    if not os.path.isdir(path):
        # genuinely absent (callers normally gate on checkpoint_exists):
        # stays a FileNotFoundError, not a corruption report
        raise FileNotFoundError(
            f"checkpoint directory {path} does not exist"
        )
    try:
        ckptr = ocp.StandardCheckpointer()
        restored = ckptr.restore(os.path.join(path, "state"), abstract)
        # orbax hands back numpy VIEWS over tensorstore-owned buffers
        # (owndata=False, base=PyCapsule). Training then feeds them to the
        # donating train step; tying XLA buffer lifetime to a foreign
        # allocator's capsule is how resumed runs died with heap-corruption
        # segfaults at random later points. Copy ONCE into numpy-owned
        # memory here, while the restore context is alive.
        restored = jax.tree_util.tree_map(
            lambda x: np.array(x) if isinstance(x, np.ndarray) else x,
            restored,
        )
        with open(os.path.join(path, _EXPERIMENT_STATE_FILE)) as f:
            experiment_state = json.load(f)
    except Exception as e:  # noqa: BLE001 - orbax surfaces partial writes
        # as a zoo of ValueError/KeyError/FileNotFoundError/XlaRuntimeError;
        # all of them mean the same operational thing here
        fallbacks = [
            s for s in list_checkpoints(model_save_dir, model_name)
            if s != str(model_idx)
        ]
        raise CheckpointCorruptError(path, e, fallbacks) from e
    return MetaState(**restored), experiment_state


def peek_experiment_state(
    model_save_dir: str, model_name: str, model_idx,
    readonly: bool = False,
) -> Optional[Dict[str, Any]]:
    """The experiment-state dict of a checkpoint WITHOUT restoring the
    array pytree (None when the checkpoint or its JSON is absent/corrupt).
    The resume logic uses this to compare ``current_iter`` across the
    ``latest`` and ``emergency`` candidates before paying a restore.

    :param readonly: never mutate the checkpoint directory — the
        serving-side contract (``_resolve_readonly_path``): a reader of
        a LIVE training run's dir (the rollover refresh daemon polls
        this every few seconds) must not perform the ``.old`` recovery
        rename — racing the trainer's two-rename swap from a second
        process can crash the trainer's save with a non-empty
        destination. The training-owned default keeps the recovery."""
    path = _ckpt_dir(model_save_dir, model_name, model_idx)
    wait_for_pending(touching=path)
    if readonly:
        path = _resolve_readonly_path(path)
    else:
        _recover_interrupted_swap(path)
    try:
        with open(os.path.join(path, _EXPERIMENT_STATE_FILE)) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def checkpoint_exists(model_save_dir: str, model_name: str, model_idx) -> bool:
    path = _ckpt_dir(model_save_dir, model_name, model_idx)
    wait_for_pending(touching=path)
    _recover_interrupted_swap(path)
    return os.path.isdir(path)


def remove_checkpoint(model_save_dir: str, model_name: str, model_idx) -> None:
    """Delete one checkpoint directory; missing is fine.

    Waits for the in-flight async save only when IT targets this path —
    otherwise a prune of the just-written epoch would race the background
    finalize (rmtree of a not-yet-materialized dir, then the finalize
    resurrecting it). Pruning unrelated epochs overlaps the write freely.

    Multi-host: only the primary touches the shared filesystem (no barrier
    needed — pruning is best-effort hygiene, never load-bearing).
    """
    if jax.process_count() > 1 and jax.process_index() != 0:
        return
    path = _ckpt_dir(model_save_dir, model_name, model_idx)
    wait_for_pending(touching=path)
    shutil.rmtree(path, ignore_errors=True)
    # also drop a crash-leftover swap sibling: were it to linger,
    # _recover_interrupted_swap would resurrect the pruned checkpoint with
    # pre-prune contents on the next exists/load probe
    shutil.rmtree(path + ".old", ignore_errors=True)
