"""TPU-native MAML / MAML++ few-shot learning framework.

A brand-new JAX/XLA re-design of the capabilities of
``AntreasAntoniou/HowToTrainYourMAMLPytorch`` (see SURVEY.md): bi-level
meta-optimization as one jit-compiled pure function (grad-through-scan inner
loop, vmap over tasks, mesh-sharded outer step), MAML++'s LSLR / MSL /
per-step batch-norm, deterministic resumable episodic data, and a
fault-tolerant experiment runner.
"""

from .config import MAMLConfig

__version__ = "0.1.0"
__all__ = ["MAMLConfig"]
