"""SPMD performance-contract auditor: the program family under a real mesh.

:mod:`analysis.auditor` verifies donation/transfer/dtype contracts on
single-device programs; nothing there can answer the questions that
decide whether a pod reservation survives its first hour: does
``hybrid_task_mesh`` introduce an accidental all-gather of the resident
store?  Are the batch arguments actually sharded over ``(data, task)`` or
is every device redundantly computing the global batch?  Will this config
OOM per-device before the first checkpoint?  This module compiles the
canonical nine-program family **under a real mesh** (8 fake CPU devices
via ``--xla_force_host_platform_device_count`` in tests/CI, real chips on
hardware) and verifies, per ``program@backend@mesh`` key pinned in
``CONTRACTS.json``:

* ``sharding``          — batch args sharded over ``(data, task)`` per
  ``parallel.distributed.global_batch_sharding``; state and resident
  stores replicated on the way in AND the way out (an output that comes
  back sharded forces a reshard on the next dispatch);
* ``collective_census`` — all-reduce / all-gather / reduce-scatter /
  collective-permute / all-to-all counts and byte volumes from the
  optimized HLO, classified per mesh axis (ICI task axis vs DCN data
  axis via the replica groups), compared against the mesh-keyed baseline
  with the op-census semantics (growth fails, shrinkage suggests a
  re-pin); invariant regardless of baseline: no collective carries uint8
  (pixel-store) data and none moves store-sized volumes — residency
  exists so pixels never cross the interconnect;
* ``hbm_budget``        — the static per-device peak
  (``memory_analysis``: arguments + outputs + temps - aliased) plus the
  resident-store expectation against a configured ``hbm_budget_gb``, so
  an OOM config fails ``cli audit`` on a laptop instead of a pod job;
* ``roofline``          — the static roofline/MFU model
  (:mod:`analysis.roofline`) produced a usable prediction for this
  device, cross-checked against a recorded ``xla_flops_per_task`` when
  one is supplied.

Audits are fully abstract (``ShapeDtypeStruct`` arguments carrying
``NamedSharding``\\ s — nothing is allocated); the mesh is the hybrid
``(data, task)`` mesh of ``parallel.distributed``, degenerating to
``1xN`` for single-host multi-device runs.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import MAMLConfig
from ..core import maml
from ..ops import device_pipeline
from ..parallel import distributed, mesh as mesh_lib
from . import contracts as C
from . import roofline as R
from . import auditor as audit_lib
from .auditor import _batch_avals, _index_avals, _state_avals, tree_byte_size

#: expected-sharding tags for one top-level argument of an audited program
BATCH0 = "batch0"          # task axis at dim 0: P((data, task))
BATCH1 = "batch1"          # stacked k-chunk, task axis at dim 1
REPLICATED = "replicated"  # state / stores / scalars: P()

_EXPECTED_SPECS = {
    BATCH0: P((distributed.DATA_AXIS, mesh_lib.TASK_AXIS)),
    BATCH1: P(None, (distributed.DATA_AXIS, mesh_lib.TASK_AXIS)),
    REPLICATED: P(),
}


def parse_mesh_spec(spec: str) -> Tuple[int, int]:
    """``"RxC"`` -> (data_rows, task_cols); raises ValueError on junk."""
    m = spec.lower().split("x")
    if len(m) != 2 or not all(p.isdigit() for p in m) or "0" in (m[0], m[1]):
        raise ValueError(
            f"mesh spec must be 'RxC' with positive integers "
            f"(data x task, e.g. '1x8'), got {spec!r}"
        )
    return int(m[0]), int(m[1])


def mesh_spec_str(rows: int, cols: int) -> str:
    return f"{rows}x{cols}"


def build_audit_mesh(
    rows: int, cols: int, devices: Optional[Sequence] = None
) -> Mesh:
    """The hybrid ``(data, task)`` audit mesh over ``rows*cols`` devices —
    the same construction production uses (``hybrid_task_mesh``), with the
    row count simulated on single-process backends."""
    devs = list(devices if devices is not None else jax.devices())
    need = rows * cols
    if len(devs) < need:
        raise ValueError(
            f"mesh {mesh_spec_str(rows, cols)} needs {need} devices but "
            f"only {len(devs)} are visible (tests/CI: set XLA_FLAGS="
            f"--xla_force_host_platform_device_count={need})"
        )
    return distributed.hybrid_task_mesh(devices=devs[:need], processes=rows)


def _mesh_shape(mesh: Mesh) -> Tuple[int, int]:
    shape = dict(mesh.shape)
    return (shape[distributed.DATA_AXIS], shape[mesh_lib.TASK_AXIS])


def _sharded(sds, mesh: Mesh, tag: str):
    return jax.ShapeDtypeStruct(
        sds.shape, sds.dtype,
        sharding=NamedSharding(mesh, _EXPECTED_SPECS[tag]),
    )


def _spec_of(sharding) -> Optional[P]:
    return getattr(sharding, "spec", None)


def _stripped(spec) -> Optional[Tuple]:
    """A PartitionSpec as a trailing-None-stripped tuple (GSPMD pads and
    truncates unsharded trailing dims freely); None when the sharding
    exposes no spec."""
    if spec is None:
        return None
    t = tuple(spec)
    while t and t[-1] is None:
        t = t[:-1]
    return t


class SpmdAuditor:
    """Verify the SPMD performance contracts on jitted callables.

    ``baseline`` / ``config_fingerprint`` arm the mesh-keyed collective
    census compare exactly like the op census (``baseline_comparable``);
    ``hbm_budget_gb`` (fallback: ``cfg.hbm_budget_gb``; 0 disables)
    bounds the static per-device peak; ``peaks`` overrides the device
    roofline table (tests perturb it)."""

    def __init__(
        self,
        cfg: MAMLConfig,
        mesh: Mesh,
        baseline: Optional[dict] = None,
        config_fingerprint: str = "",
        hbm_budget_gb: Optional[float] = None,
        peaks: Optional[List[dict]] = None,
    ):
        self.cfg = cfg
        self.mesh = mesh
        self.rows, self.cols = _mesh_shape(mesh)
        self.baseline = baseline
        self.peaks = peaks
        self.hbm_budget_gb = (
            cfg.hbm_budget_gb if hbm_budget_gb is None else hbm_budget_gb
        )
        self._census_armed = C.baseline_comparable(
            baseline,
            jax_version=jax.__version__,
            config_fingerprint=config_fingerprint,
        )

    @property
    def mesh_spec(self) -> str:
        return mesh_spec_str(self.rows, self.cols)

    # -- the audit ---------------------------------------------------------

    def audit(
        self,
        program: str,
        jitted,
        args: Sequence[Any],
        expected: Sequence[str],
        donate: Tuple[int, ...] = (),
        expect_replicated_outputs: bool = True,
        store_bytes: int = 0,
        model_flops: Optional[float] = None,
        reference_flops_per_task: Optional[float] = None,
    ) -> C.SpmdAuditReport:
        """Compile ``jitted(*args)`` under the mesh and check the SPMD
        contracts. ``expected`` tags each top-level argument (``BATCH0`` /
        ``BATCH1`` / ``REPLICATED``) with the sharding the contract
        demands — independent of what ``args`` actually carry, so a
        mutation that drops the batch sharding is caught, not blessed.
        ``store_bytes`` arms the store-sized-collective rule."""
        violations: List[C.ContractViolation] = []

        def flag(contract: str, detail: str) -> None:
            violations.append(C.ContractViolation(contract, program, detail))

        compiled = jitted.trace(*args).lower().compile()
        hlo_text = compiled.as_text()

        self._check_shardings(
            program, compiled, args, expected, expect_replicated_outputs,
            flag,
        )
        collectives = C.collective_census(hlo_text, self.rows, self.cols)
        self._check_collectives(
            program, hlo_text, collectives, store_bytes, flag
        )
        hbm = self._check_hbm(compiled, store_bytes, flag)
        tasks = self._tasks_per_device()
        roofline = R.roofline_report(
            compiled,
            device_kind=jax.devices()[0].device_kind,
            dtype=self.cfg.compute_dtype,
            tasks=tasks,
            model_flops=model_flops,
            peaks=self.peaks,
        )
        violations.extend(
            R.verify_roofline(
                roofline, program,
                reference_flops_per_task=reference_flops_per_task,
            )
        )
        donation = C.donation_stats(compiled, donate) if donate else None
        return C.SpmdAuditReport(
            program=program,
            backend=jax.default_backend(),
            contracts_checked=C.SPMD_CONTRACT_NAMES,
            violations=violations,
            census=C.interesting_census(hlo_text),
            donation=donation,
            mesh_spec=self.mesh_spec,
            collectives=collectives,
            hbm=hbm,
            roofline=roofline,
        )

    def _tasks_per_device(self) -> int:
        n_dev = self.rows * self.cols
        return max(1, self.cfg.batch_size // n_dev)

    def _check_shardings(
        self, program, compiled, args, expected, expect_replicated_outputs,
        flag,
    ) -> None:
        if len(args) != len(expected):
            raise ValueError(
                f"{program}: {len(args)} args but {len(expected)} "
                "expected-sharding tags"
            )
        try:
            in_shardings, _ = compiled.input_shardings
            out_shardings = compiled.output_shardings
        except Exception as e:  # noqa: BLE001 - backend without the API
            flag("sharding",
                 f"compiled executable exposes no shardings ({e!r}); the "
                 "sharding contract is unverifiable")
            return
        # input_shardings mirrors the call's top-level arguments: one
        # entry per arg, itself a pytree of per-leaf shardings. Leaves the
        # executable PRUNED (an unused rot_k under augment=False, the Adam
        # moments in an eval step) carry no sharding — every leaf that
        # survived must still match the arg's contract spec, which is
        # uniform per argument, so partial pairing verifies exactly the
        # leaves that exist on device.
        if len(in_shardings) != len(args):
            flag("sharding",
                 f"{len(in_shardings)} committed input shardings for "
                 f"{len(args)} arguments — cannot verify")
            return
        for argnum, (arg, tag, arg_sh) in enumerate(
            zip(args, expected, in_shardings)
        ):
            want = _stripped(_EXPECTED_SPECS[tag])
            for sh in jax.tree_util.tree_leaves(arg_sh):
                committed = _stripped(_spec_of(sh))
                if committed != want:
                    flag(
                        "sharding",
                        f"arg {argnum} ({tag}) leaf committed sharding "
                        f"spec {committed} != contract {want} — "
                        + (
                            "the batch is not sharded over (data, task): "
                            "every device computes the global batch "
                            "redundantly"
                            if tag in (BATCH0, BATCH1)
                            else "state/store must stay replicated"
                        ),
                    )
                    break  # one violation per argument, not per leaf
        if expect_replicated_outputs:
            for i, sh in enumerate(jax.tree_util.tree_leaves(out_shardings)):
                spec = _spec_of(sh)
                if spec is not None and tuple(spec) and any(
                    s is not None for s in tuple(spec)
                ):
                    flag(
                        "sharding",
                        f"output leaf {i} comes back sharded ({spec}) — a "
                        "sharded new state forces a reshard/all-gather on "
                        "the next dispatch",
                    )
                    break

    def _check_collectives(
        self, program, hlo_text, collectives, store_bytes, flag
    ) -> None:
        # invariants (baseline-free): pixel/store bytes never cross the
        # interconnect — no uint8 collective, nothing store-sized
        insns = C.collective_instructions(hlo_text)
        u8 = [i for i in insns if "u8[" in i["shape"]]
        if u8:
            flag(
                "collective_census",
                f"{len(u8)} collective(s) carry uint8 (pixel-store) data "
                f"(e.g. {u8[0]['op']} {u8[0]['shape']}) — the replicated "
                "store is being gathered/resharded inside the step",
            )
        if store_bytes > 0:
            big = [i for i in insns if i["bytes"] >= store_bytes]
            if big:
                flag(
                    "collective_census",
                    f"collective {big[0]['op']} moves {big[0]['bytes']} "
                    f"bytes >= the {store_bytes}-byte resident store — "
                    "store-sized data is crossing the interconnect",
                )
        if self._census_armed:
            key = C.spmd_census_key(
                program, jax.default_backend(), self.mesh_spec
            )
            pinned = (self.baseline or {}).get("programs", {}).get(key)
            if pinned is not None:
                regressions = C.compare_collective_census(
                    collectives, pinned.get("collectives", {})
                )
                if regressions:
                    flag(
                        "collective_census",
                        "collective census regression vs pinned baseline: "
                        + ", ".join(regressions),
                    )

    def _check_hbm(self, compiled, store_bytes, flag) -> Optional[dict]:
        try:
            ma = compiled.memory_analysis()
            hbm = {
                "argument_bytes": int(ma.argument_size_in_bytes),
                "output_bytes": int(ma.output_size_in_bytes),
                "temp_bytes": int(ma.temp_size_in_bytes),
                "alias_bytes": int(ma.alias_size_in_bytes),
            }
        except Exception as e:  # noqa: BLE001 - backend without the API
            if self.hbm_budget_gb > 0:
                flag("hbm_budget",
                     f"memory_analysis unavailable ({e!r}); the HBM budget "
                     "is unverifiable on this backend")
            return None
        # static per-device peak: args + outputs + temps, minus the donated
        # aliases counted twice, plus the resident stores the step expects
        # in HBM beside it
        peak = (
            hbm["argument_bytes"] + hbm["output_bytes"] + hbm["temp_bytes"]
            - hbm["alias_bytes"]
        )
        hbm["peak_bytes"] = peak
        hbm["store_bytes_expected"] = int(store_bytes)
        hbm["budget_gb"] = float(self.hbm_budget_gb)
        if self.hbm_budget_gb > 0:
            budget = self.hbm_budget_gb * 2**30
            if peak > budget:
                flag(
                    "hbm_budget",
                    f"static per-device peak {peak / 2**30:.3f} GiB "
                    f"(args {hbm['argument_bytes']} + outputs "
                    f"{hbm['output_bytes']} + temps {hbm['temp_bytes']} - "
                    f"aliased {hbm['alias_bytes']}) exceeds hbm_budget_gb="
                    f"{self.hbm_budget_gb} — this config OOMs before a "
                    "TPU reservation is burned",
                )
        return hbm


# -- the canonical family under the mesh --------------------------------------


def audit_spmd_programs(
    cfg: MAMLConfig,
    mesh: Optional[Mesh] = None,
    auditor: Optional[SpmdAuditor] = None,
    second_order: Optional[bool] = None,
    k: int = 2,
    programs: Optional[Sequence[str]] = None,
) -> List[C.SpmdAuditReport]:
    """Audit the canonical nine-program family under ``mesh`` (default: a
    1xN hybrid mesh over every visible device). The batch size is rounded
    up to the mesh size when it does not divide it — the audit needs a
    shardable batch, and the census keys carry the mesh so rounded and
    exact configs never compare against each other's entries."""
    if mesh is None and auditor is not None:
        mesh = auditor.mesh
    if mesh is None:
        mesh = build_audit_mesh(1, len(jax.devices()))
    rows, cols = _mesh_shape(mesh)
    n_dev = rows * cols
    if cfg.batch_size % n_dev != 0:
        cfg = cfg.replace(
            batch_size=max(1, -(-cfg.batch_size // n_dev)) * n_dev
        )
    if auditor is None:
        auditor = SpmdAuditor(cfg, mesh)
    else:
        auditor.cfg = cfg
    so = cfg.second_order if second_order is None else bool(second_order)
    so_tag = int(so)

    def rep(tree):
        return jax.tree_util.tree_map(
            lambda s: _sharded(s, mesh, REPLICATED), tree
        )

    state = rep(_state_avals(cfg))
    weights = _sharded(
        jax.ShapeDtypeStruct(
            (cfg.number_of_training_steps_per_iter,), jnp.float32
        ), mesh, REPLICATED,
    )
    lr = _sharded(jax.ShapeDtypeStruct((), jnp.float32), mesh, REPLICATED)
    batch = tuple(_sharded(b, mesh, BATCH0) for b in _batch_avals(cfg))
    batch_k = tuple(_sharded(b, mesh, BATCH1) for b in _batch_avals(cfg, k))
    store_sds, gather_sds, rot_sds = _index_avals(cfg)
    store = _sharded(store_sds, mesh, REPLICATED)
    gather = _sharded(gather_sds, mesh, BATCH0)
    rot_k = _sharded(rot_sds, mesh, BATCH0)
    _, gather_k_sds, rot_k_k_sds = _index_avals(cfg, k)
    gather_k = _sharded(gather_k_sds, mesh, BATCH1)
    rot_k_k = _sharded(rot_k_k_sds, mesh, BATCH1)
    store_bytes = tree_byte_size(store)

    b0, b1, rp = BATCH0, BATCH1, REPLICATED
    specs: List[tuple] = [
        (
            f"train_step[so={so_tag}]",
            jax.jit(maml.make_train_step(cfg, so),
                    donate_argnums=maml.TRAIN_DONATE),
            (state, *batch, weights, lr),
            (rp, b0, b0, b0, b0, rp, rp),
            maml.TRAIN_DONATE, True, 0,
        ),
        (
            f"train_multi_step[so={so_tag},k={k}]",
            jax.jit(maml.make_train_multi_step(cfg, so),
                    donate_argnums=maml.TRAIN_DONATE),
            (state, *batch_k, weights, lr),
            (rp, b1, b1, b1, b1, rp, rp),
            maml.TRAIN_DONATE, True, 0,
        ),
        (
            f"train_step_indexed[so={so_tag}]",
            jax.jit(maml.make_train_step_indexed(cfg, so, augment=False),
                    donate_argnums=maml.TRAIN_DONATE),
            (state, store, gather, rot_k, weights, lr),
            (rp, rp, b0, b0, rp, rp),
            maml.TRAIN_DONATE, True, store_bytes,
        ),
        (
            f"train_multi_step_indexed[so={so_tag},k={k}]",
            jax.jit(maml.make_train_multi_step_indexed(cfg, so,
                                                       augment=False),
                    donate_argnums=maml.TRAIN_DONATE),
            (state, store, gather_k, rot_k_k, weights, lr),
            (rp, rp, b1, b1, rp, rp),
            maml.TRAIN_DONATE, True, store_bytes,
        ),
        (
            f"eval_multi_step[k={k}]",
            jax.jit(maml.make_eval_multi_step(cfg, with_preds=False)),
            (state, *batch_k),
            (rp, b1, b1, b1, b1),
            (), True, 0,
        ),
        (
            "index_expander",
            jax.jit(device_pipeline.make_index_expander(cfg, augment=False)),
            (store, gather, rot_k),
            (rp, b0, b0),
            # outputs are the expanded per-task pixel batches: sharded over
            # the task axis BY DESIGN
            (), False, store_bytes,
        ),
        (
            f"serve_step[b={cfg.batch_size}]",
            jax.jit(maml.make_serve_step(cfg),
                    donate_argnums=maml.SERVE_DONATE),
            (state, *batch,
             _sharded(jax.ShapeDtypeStruct((cfg.batch_size,), jnp.float32),
                      mesh, BATCH0)),
            (rp, b0, b0, b0, b0, b0),
            # per-tenant outputs (preds/loss/accuracy) are sharded over
            # the tenant axis BY DESIGN; the passthrough state keeps its
            # replicated input sharding
            maml.SERVE_DONATE, False, 0,
        ),
        (
            f"serve_step_uint8[b={cfg.batch_size}]",
            jax.jit(maml.make_serve_step(cfg, ingest="uint8"),
                    donate_argnums=maml.SERVE_DONATE),
            (state,
             *(_sharded(b, mesh, BATCH0)
               for b in audit_lib._batch_avals_uint8(cfg)),
             _sharded(jax.ShapeDtypeStruct((cfg.batch_size,), jnp.float32),
                      mesh, BATCH0)),
            (rp, b0, b0, b0, b0, b0),
            # same profile as the f32 serve step: the on-device LUT
            # decode is elementwise per tenant and introduces no
            # collectives
            maml.SERVE_DONATE, False, 0,
        ),
        (
            f"predict_step[b={cfg.batch_size}]",
            jax.jit(maml.make_predict_step(cfg),
                    donate_argnums=maml.PREDICT_DONATE),
            (state,
             jax.tree_util.tree_map(
                 lambda s: _sharded(s, mesh, BATCH0),
                 audit_lib._fast_avals(cfg, cfg.batch_size),
             ),
             _sharded(jax.ShapeDtypeStruct(
                 (cfg.batch_size, cfg.num_classes_per_set,
                  cfg.num_target_samples, *cfg.im_shape), jnp.float32),
                 mesh, BATCH0),
             _sharded(jax.ShapeDtypeStruct(
                 (cfg.batch_size, cfg.num_classes_per_set,
                  cfg.num_target_samples), jnp.int32), mesh, BATCH0),
             _sharded(jax.ShapeDtypeStruct((cfg.batch_size,), jnp.float32),
                      mesh, BATCH0)),
            (rp, b0, b0, b0, b0),
            # cached fast weights ride the TENANT axis (each tenant its
            # own adapted clone) — batch-sharded like the pixel inputs
            maml.PREDICT_DONATE, False, 0,
        ),
    ]
    reports = []
    for name, jitted, args, expected, donate, rep_out, sbytes in specs:
        if programs is not None and name not in programs:
            continue
        reports.append(
            auditor.audit(
                name, jitted, args, expected,
                donate=donate,
                expect_replicated_outputs=rep_out,
                store_bytes=sbytes,
            )
        )
    return reports
